#!/usr/bin/env python
"""Chunked TPC-H parquet generator for large scale factors (SF>=10).

The in-process generator (ballista_tpu/tpch.py, ref
benchmarks/tpch-gen.sh's dockerised dbgen) builds whole tables in memory
with Python-string columns — infeasible at SF=100 (600M lineitem rows).
This writer generates each table in fixed-size row chunks with a
deterministic per-(table, chunk) RNG stream and appends them to one
parquet file per table, so peak memory is one chunk (~8M rows) no matter
the SF.

Large-SF deviations from the small-SF generator (documented, bench-only):
- free-text columns (comments, addresses, clerk, phone) draw from a small
  precomputed vocabulary and are written dictionary-encoded — the TPC-H
  queries this dataset serves (q1/q3/q5/q6/q18, BASELINE.md configs 4-5)
  never read them, and real per-row text would dominate generation time
  and double the file size;
- `part`/`partsupp` are only written when explicitly requested (the
  headline query set touches neither).

Key relationships and value domains (PK/FK integrity, price formula,
date windows, returnflag/linestatus derivation) match ballista_tpu/tpch.py
so plans, pruning, and kernels see spec-shaped data.

Usage:
  python -m benchmarks.gen_parquet --scale 100 --path .data/tpch_sf100 \
      [--tables lineitem,orders,...] [--chunk-rows 8000000]
"""

from __future__ import annotations

import argparse
import datetime
import json
import os
import pathlib
import sys
import time

import numpy as np
import pyarrow as pa
import pyarrow.parquet as papq

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

from ballista_tpu.tpch import (  # noqa: E402
    _CARD,
    COMMENT_WORDS,
    DATE_HI,
    DATE_LO,
    NATIONS,
    PRIORITIES,
    REGIONS,
    SEGMENTS,
    SHIPINSTRUCT,
    SHIPMODES,
    TPCH_TABLES,
    _d,
    gen_table,
    tpch_schema,
)

EPOCH = datetime.date(1970, 1, 1)

# Small fixed vocabularies for free-text columns (see module docstring).
_VOCAB_RNG = np.random.default_rng(7)
_COMMENT_VOCAB = [
    " ".join(
        COMMENT_WORDS[j]
        for j in _VOCAB_RNG.integers(0, len(COMMENT_WORDS), 5)
    )
    for _ in range(1024)
]
_CLERK_VOCAB = [f"Clerk#{i:09d}" for i in range(1, 1001)]
_PHONE_VOCAB = [
    f"{10 + int(n)}-{_VOCAB_RNG.integers(100, 1000)}-"
    f"{_VOCAB_RNG.integers(100, 1000)}-{_VOCAB_RNG.integers(1000, 10000)}"
    for n in _VOCAB_RNG.integers(0, 25, 512)
]


def _dict_col(codes: np.ndarray, vocab: list[str]) -> pa.Array:
    return pa.DictionaryArray.from_arrays(
        pa.array(codes.astype(np.int32)), pa.array(vocab)
    )


def _date_col(days: np.ndarray) -> pa.Array:
    return pa.array(days.astype(np.int32), type=pa.date32())


def _rng(seed: int, table: str, chunk: int) -> np.random.Generator:
    return np.random.default_rng(
        np.random.SeedSequence([seed, TPCH_TABLES.index(table), chunk])
    )


def _arrow_schema(table: str) -> pa.schema:
    """Arrow schema matching ballista_tpu's engine schema dtypes."""
    m = {
        "int64": pa.int64(),
        "int32": pa.int32(),
        "float64": pa.float64(),
        "string": pa.string(),
        "date32": pa.date32(),
    }
    return pa.schema(
        [
            pa.field(f.name, m[f.dtype.value], nullable=False)
            for f in tpch_schema(table)
        ]
    )


class _Writer:
    """ParquetWriter that normalizes dictionary columns to the declared
    utf8 schema lazily per chunk (parquet dictionary-encodes on disk
    regardless; keeping the logical type utf8 matches the engine schema)."""

    def __init__(self, path: pathlib.Path, table: str, row_group: int):
        self.schema = _arrow_schema(table)
        self.w = papq.ParquetWriter(
            str(path), self.schema, compression="snappy"
        )
        self.row_group = row_group
        self.rows = 0

    def write(self, cols: dict) -> None:
        arrs = []
        for f in self.schema:
            a = cols[f.name]
            if isinstance(a, np.ndarray):
                a = pa.array(a)
            if pa.types.is_dictionary(a.type):
                a = a.cast(pa.string()) if f.type == pa.string() else a
            arrs.append(a)
        t = pa.table(
            dict(zip([f.name for f in self.schema], arrs))
        ).cast(self.schema)
        self.w.write_table(t, row_group_size=self.row_group)
        self.rows += t.num_rows

    def close(self) -> None:
        self.w.close()


def _orders_chunk(scale: float, seed: int, start: int, n: int, ncust: int):
    """Rows [start, start+n) of orders, deterministic per chunk index
    (chunk index = start // chunk size, passed via the caller's rng)."""
    rng = _rng(seed, "orders", start)
    keys = (np.arange(start, start + n, dtype=np.int64) * 4) + 1
    ck = rng.integers(1, ncust + 1, n).astype(np.int64)
    odate = rng.integers(DATE_LO, DATE_HI - 151, n).astype(np.int32)
    return rng, keys, ck, odate


def gen_orders_chunks(scale: float, seed: int, chunk_rows: int):
    ncust = max(1, int(_CARD["customer"] * scale))
    n = max(1, int(_CARD["orders"] * scale))
    for start in range(0, n, chunk_rows):
        m = min(chunk_rows, n - start)
        rng, keys, ck, odate = _orders_chunk(scale, seed, start, m, ncust)
        status_codes = np.where(
            odate + 100 < _d(1995, 6, 17),
            0,
            np.where(odate > _d(1996, 1, 1), 1, 2),
        )
        yield {
            "o_orderkey": keys,
            "o_custkey": ck,
            "o_orderstatus": _dict_col(status_codes, ["F", "O", "P"]),
            "o_totalprice": np.round(rng.uniform(850.0, 555000.0, m), 2),
            "o_orderdate": _date_col(odate),
            "o_orderpriority": _dict_col(
                rng.integers(0, 5, m), PRIORITIES
            ),
            "o_clerk": _dict_col(
                rng.integers(0, len(_CLERK_VOCAB), m), _CLERK_VOCAB
            ),
            "o_shippriority": np.zeros(m, dtype=np.int32),
            "o_comment": _dict_col(
                rng.integers(0, len(_COMMENT_VOCAB), m), _COMMENT_VOCAB
            ),
        }


def gen_lineitem_chunks(scale: float, seed: int, chunk_rows: int):
    """Lineitem chunks aligned to orders chunks: chunk i covers the
    lineitems of orders rows [i*chunk_rows, (i+1)*chunk_rows)."""
    ncust = max(1, int(_CARD["customer"] * scale))
    npart = max(1, int(_CARD["part"] * scale))
    nsupp = max(1, int(_CARD["supplier"] * scale))
    norders = max(1, int(_CARD["orders"] * scale))
    for start in range(0, norders, chunk_rows):
        m = min(chunk_rows, norders - start)
        _, okeys, _, odates = _orders_chunk(scale, seed, start, m, ncust)
        rng = _rng(seed, "lineitem", start)
        nline = rng.integers(1, 8, m)
        lok = np.repeat(okeys, nline)
        lod = np.repeat(odates, nline)
        n = len(lok)
        # per-order line numbers without a Python loop:
        ends = np.cumsum(nline)
        linenumber = (
            np.arange(n, dtype=np.int64) - np.repeat(ends - nline, nline) + 1
        ).astype(np.int32)
        pk = rng.integers(1, npart + 1, n).astype(np.int64)
        i4 = rng.integers(0, 4, n).astype(np.int64)
        sk = (pk + i4 * (nsupp // 4 + ((pk - 1) // nsupp))) % nsupp + 1
        qty = rng.integers(1, 51, n).astype(np.float64)
        retail = (90000 + (pk % 20001) + 100 * (pk % 1000)) / 100.0
        eprice = np.round(retail * qty, 2)
        sdate = (lod + rng.integers(1, 122, n)).astype(np.int32)
        cdate = (lod + rng.integers(30, 91, n)).astype(np.int32)
        rdate = (sdate + rng.integers(1, 31, n)).astype(np.int32)
        rf_codes = np.where(
            rdate <= _d(1995, 6, 17),
            np.where(rng.random(n) < 0.5, 0, 1),
            2,
        )
        ls_codes = np.where(sdate > _d(1995, 6, 17), 0, 1)
        yield {
            "l_orderkey": lok,
            "l_partkey": pk,
            "l_suppkey": sk,
            "l_linenumber": linenumber,
            "l_quantity": qty,
            "l_extendedprice": eprice,
            "l_discount": np.round(rng.integers(0, 11, n) / 100.0, 2),
            "l_tax": np.round(rng.integers(0, 9, n) / 100.0, 2),
            "l_returnflag": _dict_col(rf_codes, ["R", "A", "N"]),
            "l_linestatus": _dict_col(ls_codes, ["O", "F"]),
            "l_shipdate": _date_col(sdate),
            "l_commitdate": _date_col(cdate),
            "l_receiptdate": _date_col(rdate),
            "l_shipinstruct": _dict_col(
                rng.integers(0, 4, n), SHIPINSTRUCT
            ),
            "l_shipmode": _dict_col(rng.integers(0, 7, n), SHIPMODES),
            "l_comment": _dict_col(
                rng.integers(0, len(_COMMENT_VOCAB), n), _COMMENT_VOCAB
            ),
        }


def gen_customer_chunks(scale: float, seed: int, chunk_rows: int):
    n = max(1, int(_CARD["customer"] * scale))
    for start in range(0, n, chunk_rows):
        m = min(chunk_rows, n - start)
        rng = _rng(seed, "customer", start)
        keys = np.arange(start + 1, start + m + 1, dtype=np.int64)
        nk = rng.integers(0, len(NATIONS), m).astype(np.int64)
        yield {
            "c_custkey": keys,
            "c_name": pa.array([f"Customer#{k:09d}" for k in keys]),
            "c_address": _dict_col(
                rng.integers(0, len(_COMMENT_VOCAB), m), _COMMENT_VOCAB
            ),
            "c_nationkey": nk,
            "c_phone": _dict_col(
                rng.integers(0, len(_PHONE_VOCAB), m), _PHONE_VOCAB
            ),
            "c_acctbal": np.round(rng.uniform(-999.99, 9999.99, m), 2),
            "c_mktsegment": _dict_col(rng.integers(0, 5, m), SEGMENTS),
            "c_comment": _dict_col(
                rng.integers(0, len(_COMMENT_VOCAB), m), _COMMENT_VOCAB
            ),
        }


def gen_supplier_chunks(scale: float, seed: int, chunk_rows: int):
    n = max(1, int(_CARD["supplier"] * scale))
    for start in range(0, n, chunk_rows):
        m = min(chunk_rows, n - start)
        rng = _rng(seed, "supplier", start)
        keys = np.arange(start + 1, start + m + 1, dtype=np.int64)
        nk = rng.integers(0, len(NATIONS), m).astype(np.int64)
        yield {
            "s_suppkey": keys,
            "s_name": pa.array([f"Supplier#{k:09d}" for k in keys]),
            "s_address": _dict_col(
                rng.integers(0, len(_COMMENT_VOCAB), m), _COMMENT_VOCAB
            ),
            "s_nationkey": nk,
            "s_phone": _dict_col(
                rng.integers(0, len(_PHONE_VOCAB), m), _PHONE_VOCAB
            ),
            "s_acctbal": np.round(rng.uniform(-999.99, 9999.99, m), 2),
            "s_comment": _dict_col(
                rng.integers(0, len(_COMMENT_VOCAB), m), _COMMENT_VOCAB
            ),
        }


_CHUNKED = {
    "orders": gen_orders_chunks,
    "lineitem": gen_lineitem_chunks,
    "customer": gen_customer_chunks,
    "supplier": gen_supplier_chunks,
}

DEFAULT_TABLES = "lineitem,orders,customer,supplier,nation,region"


def write_table(
    table: str,
    scale: float,
    out_dir: pathlib.Path,
    seed: int = 42,
    chunk_rows: int = 4_000_000,
    row_group: int = 2_000_000,
) -> dict:
    path = out_dir / f"{table}.parquet"
    t0 = time.time()
    if table in _CHUNKED:
        w = _Writer(path, table, row_group)
        for cols in _CHUNKED[table](scale, seed, chunk_rows):
            w.write(cols)
        w.close()
        rows = w.rows
    else:
        t = gen_table(table, scale, seed)
        papq.write_table(
            t.cast(_arrow_schema(table)),
            str(path),
            row_group_size=row_group,
            compression="snappy",
        )
        rows = t.num_rows
    return {
        "rows": rows,
        "seconds": round(time.time() - t0, 1),
        "bytes": path.stat().st_size,
    }


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, required=True)
    ap.add_argument("--path", required=True)
    ap.add_argument("--tables", default=DEFAULT_TABLES)
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--chunk-rows", type=int, default=4_000_000)
    ap.add_argument("--row-group", type=int, default=2_000_000)
    args = ap.parse_args()
    out = pathlib.Path(args.path)
    out.mkdir(parents=True, exist_ok=True)
    manifest = {"scale": args.scale, "seed": args.seed, "tables": {}}
    for table in args.tables.split(","):
        table = table.strip()
        info = write_table(
            table, args.scale, out, args.seed, args.chunk_rows,
            args.row_group,
        )
        manifest["tables"][table] = info
        print(f"{table}: {info}", flush=True)
    (out / "manifest.json").write_text(json.dumps(manifest, indent=2))


if __name__ == "__main__":
    main()
