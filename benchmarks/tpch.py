#!/usr/bin/env python
"""TPC-H benchmark harness with the reference's CLI shape.

ref benchmarks/src/bin/tpch.rs:69-260 — subcommands:

  tpch benchmark ballista   --query N --path DIR [--format csv|parquet]
                            [--host H --port P] [--iterations I]
                            [--partitions N] [--batch-size S] [--debug]
                            [--output DIR]
  tpch benchmark datafusion --query N --path DIR ...   (local engine)
  tpch convert              --input DIR --output DIR --format parquet
  tpch loadtest ballista    --query-list 1,6 --path DIR --requests R
                            --concurrency C [--host H --port P]

plus a ``gen`` subcommand standing in for the reference's dockerised
dbgen (benchmarks/tpch-gen.sh — no egress here):

  tpch gen --scale 0.1 --path DIR [--format csv|parquet]

Data layout: ``<path>/<table>.<ext>`` for the 8 TPC-H tables. The summary
JSON mirrors write_summary_json (tpch.rs:407-418).
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import sys
import time
from pathlib import Path

HERE = Path(__file__).resolve().parent
sys.path.insert(0, str(HERE.parent))

TABLES = (
    "part", "supplier", "partsupp", "customer",
    "orders", "lineitem", "nation", "region",
)


def _query_sql(n: int) -> str:
    qfile = HERE / "queries" / f"q{n}.sql"
    return qfile.read_text()


def _make_context(args, remote_ok: bool = True):
    from ballista_tpu.config import BallistaConfig

    config = BallistaConfig().with_setting(
        "ballista.shuffle.partitions", str(args.partitions)
    )
    if args.batch_size:
        config = config.with_setting(
            "ballista.batch.size", str(args.batch_size)
        )
    if remote_ok and args.host and args.port:
        from ballista_tpu.client.context import BallistaContext

        return BallistaContext.remote(args.host, args.port, config)
    from ballista_tpu.exec.context import TpuContext

    return TpuContext(config)


def _register_tables(ctx, path: str, file_format: str) -> None:
    from ballista_tpu.tpch import all_schemas

    schemas = all_schemas()
    for t in TABLES:
        f = Path(path) / f"{t}.{file_format}"
        if not f.exists():
            raise SystemExit(f"missing table file {f}")
        if file_format == "csv":
            ctx.register_csv(t, str(f), schema=schemas[t], has_header=True)
        else:
            ctx.register_parquet(t, str(f))


def _write_summary(output: str | None, run: dict) -> None:
    """ref write_summary_json (tpch.rs:407-418): one timestamped JSON."""
    if not output:
        return
    out = Path(output)
    out.mkdir(parents=True, exist_ok=True)
    f = out / f"tpch-summary--{int(time.time())}.json"
    f.write_text(json.dumps(run, indent=2))
    print(f"Summary written to: {f}")


def cmd_benchmark(args) -> int:
    ctx = _make_context(args, remote_ok=args.engine == "ballista")
    _register_tables(ctx, args.path, args.format)
    sql = _query_sql(args.query)
    run = {
        "engine": args.engine,
        "query": args.query,
        "iterations": [],
        "start_time": int(time.time()),
    }
    for i in range(args.iterations):
        t0 = time.time()
        res = ctx.sql(sql).collect()
        ms = (time.time() - t0) * 1000
        run["iterations"].append({"elapsed_ms": ms, "rows": res.num_rows})
        print(
            f"Query {args.query} iteration {i} took {ms:.1f} ms "
            f"and returned {res.num_rows} rows"
        )
        if args.debug:
            print(res.to_pandas().to_string(index=False))
    best = min(it["elapsed_ms"] for it in run["iterations"])
    print(f"Query {args.query} best time: {best:.1f} ms")
    _write_summary(args.output, run)
    if hasattr(ctx, "close"):
        ctx.close()
    return 0


def cmd_loadtest(args) -> int:
    """ref BallistaLoadtestOpt (tpch.rs:155-199): fire R requests over C
    concurrent clients round-robining the query list."""
    queries = [int(q) for q in args.query_list.split(",")]

    def one(i: int) -> float:
        ctx = _make_context(args)
        _register_tables(ctx, args.path, args.format)
        t0 = time.time()
        ctx.sql(_query_sql(queries[i % len(queries)])).collect()
        dt = time.time() - t0
        if hasattr(ctx, "close"):
            ctx.close()
        return dt

    t0 = time.time()
    with concurrent.futures.ThreadPoolExecutor(args.concurrency) as pool:
        times = list(pool.map(one, range(args.requests)))
    total = time.time() - t0
    print(
        f"loadtest: {args.requests} requests x q[{args.query_list}] in "
        f"{total:.1f}s ({args.requests / total:.2f} req/s, "
        f"mean {sum(times) / len(times) * 1000:.0f} ms)"
    )
    return 0


def cmd_convert(args) -> int:
    """ref ConvertOpt (tpch.rs:201-227): csv -> parquet. Column types come
    from the engine's TPC-H schemas, not CSV inference, so converted files
    match what the benchmark queries assume."""
    import pyarrow.csv as pacsv
    import pyarrow.parquet as papq

    from ballista_tpu.columnar.arrow_interop import schema_to_arrow
    from ballista_tpu.tpch import all_schemas

    schemas = all_schemas()
    out = Path(args.output)
    out.mkdir(parents=True, exist_ok=True)
    for t in TABLES:
        src = Path(args.input) / f"{t}.csv"
        if not src.exists():
            print(f"skipping {t} (no {src})")
            continue
        arrow_schema = schema_to_arrow(schemas[t])
        table = pacsv.read_csv(
            str(src),
            convert_options=pacsv.ConvertOptions(
                column_types={f.name: f.type for f in arrow_schema}
            ),
        )
        papq.write_table(table, str(out / f"{t}.parquet"))
        print(f"converted {t}: {table.num_rows} rows")
    return 0


def cmd_gen(args) -> int:
    import pyarrow.csv as pacsv
    import pyarrow.parquet as papq

    from ballista_tpu.tpch import gen_all

    out = Path(args.path)
    out.mkdir(parents=True, exist_ok=True)
    data = gen_all(scale=args.scale)
    for t, table in data.items():
        f = out / f"{t}.{args.format}"
        if args.format == "csv":
            pacsv.write_csv(table, str(f))
        else:
            papq.write_table(table, str(f))
        print(f"wrote {f} ({table.num_rows} rows)")
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpch", description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)

    bench = sub.add_parser("benchmark")
    bsub = bench.add_subparsers(dest="engine", required=True)
    for engine in ("ballista", "datafusion"):
        b = bsub.add_parser(engine)
        b.add_argument("-q", "--query", type=int, required=True)
        b.add_argument("-d", "--debug", action="store_true")
        b.add_argument("-i", "--iterations", type=int, default=3)
        b.add_argument("-s", "--batch-size", type=int, default=0)
        b.add_argument("-p", "--path", required=True)
        b.add_argument("-f", "--format", default="csv",
                       choices=["csv", "parquet"])
        b.add_argument("-n", "--partitions", type=int, default=2)
        b.add_argument("--host")
        b.add_argument("--port", type=int)
        b.add_argument("-o", "--output")
        b.set_defaults(fn=cmd_benchmark)

    lt = sub.add_parser("loadtest")
    ltsub = lt.add_subparsers(dest="engine", required=True)
    l = ltsub.add_parser("ballista")
    l.add_argument("-q", "--query-list", required=True)
    l.add_argument("-r", "--requests", type=int, default=100)
    l.add_argument("-c", "--concurrency", type=int, default=5)
    l.add_argument("-n", "--partitions", type=int, default=2)
    l.add_argument("-s", "--batch-size", type=int, default=0)
    l.add_argument("-p", "--data-path", dest="path", required=True)
    l.add_argument("-f", "--format", default="csv",
                   choices=["csv", "parquet"])
    l.add_argument("--host")
    l.add_argument("--port", type=int)
    l.set_defaults(fn=cmd_loadtest)

    cv = sub.add_parser("convert")
    cv.add_argument("-i", "--input", required=True)
    cv.add_argument("-o", "--output", required=True)
    cv.add_argument("-f", "--format", default="parquet",
                    choices=["parquet"])
    cv.set_defaults(fn=cmd_convert)

    g = sub.add_parser("gen")
    g.add_argument("--scale", type=float, default=0.01)
    g.add_argument("-p", "--path", required=True)
    g.add_argument("-f", "--format", default="csv",
                   choices=["csv", "parquet"])
    g.set_defaults(fn=cmd_gen)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    raise SystemExit(main())
