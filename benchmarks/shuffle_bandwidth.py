#!/usr/bin/env python
"""Shuffle-bandwidth micro-benchmark (the BASELINE.md north-star metric
names "shuffle GB/s over ICI").

Two tiers are measured, matching the engine's two shuffle paths:

1. **Mesh collective shuffle**: one jitted ``shard_map`` ``all_to_all``
   over the available device mesh — the on-pod path SQL stages use
   (parallel/stage.py). On real multi-chip hardware this rides ICI; under
   ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8``
   it validates the same program on the virtual mesh (numbers then
   characterize host memcpy, not ICI — the harness labels which).
2. **Local device hash partition**: partition-id hashing + stacked
   gather into bucket order on one chip — the file/Flight shuffle's
   device-side cost (executor/shuffle.py).

Usage: python benchmarks/shuffle_bandwidth.py [--mb 256] [--parts 8]
Prints conbench-style JSON records like benchmarks/micro.py.
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def _amortized(fn, *args, reps=6):
    """Dispatch N times, fetch one scalar once — removes the tunnelled
    host round trip (~100ms) from the measurement."""
    import numpy as np

    out = fn(*args)
    np.asarray(out.reshape(-1)[:1])

    def run_k(k):
        t0 = time.time()
        for _ in range(k):
            out = fn(*args)
        np.asarray(out.reshape(-1)[:1])
        return time.time() - t0

    t1 = min(run_k(1) for _ in range(2))
    tn = min(run_k(reps) for _ in range(2))
    return max((tn - t1) / (reps - 1), 1e-9)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--mb", type=int, default=256,
                   help="payload megabytes per measurement")
    p.add_argument("--parts", type=int, default=8)
    p.add_argument("-o", "--output")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ballista_tpu  # noqa: F401 — enables x64

    records = []
    platform = jax.devices()[0].platform
    n_dev = len(jax.devices())

    # -- tier 1: mesh all_to_all ------------------------------------------
    if n_dev >= 2:
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, NamedSharding
        from jax.sharding import PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("x",))
        per_dev = (args.mb << 20) // (n_dev * 4)
        rows = per_dev - (per_dev % n_dev)
        x = jax.device_put(
            jnp.ones((n_dev * rows,), jnp.float32),
            NamedSharding(mesh, P("x")),
        )

        @jax.jit
        def a2a(x):
            def f(xs):  # xs: (rows,) local shard
                blocks = xs.reshape(n_dev, rows // n_dev)
                return jax.lax.all_to_all(
                    blocks, "x", split_axis=0, concat_axis=0, tiled=False
                ).reshape(-1)

            return shard_map(
                f, mesh=mesh, in_specs=P("x"), out_specs=P("x")
            )(x)

        dt = _amortized(a2a, x)
        moved = n_dev * rows * 4  # every element crosses the interconnect
        records.append(
            {
                "name": "shuffle_all_to_all",
                "tags": {
                    "platform": platform,
                    "devices": n_dev,
                    "interconnect": "ici" if platform == "tpu" else "host",
                },
                "seconds": round(dt, 6),
                "gb_per_s": round(moved / dt / 1e9, 3),
                "bytes": moved,
            }
        )
    else:
        records.append(
            {
                "name": "shuffle_all_to_all",
                "tags": {"platform": platform, "devices": n_dev},
                "skipped": "needs >= 2 devices (run under the 8-device "
                "CPU mesh or a TPU pod slice)",
            }
        )

    # -- tier 2: single-device hash partition ------------------------------
    from ballista_tpu.ops.hashing import hash_columns
    from ballista_tpu.ops.perm import stable_argsort

    rows = (args.mb << 20) // 8
    r = np.random.default_rng(0)
    keys = jnp.asarray(r.integers(0, 1 << 30, rows).astype(np.int64))
    payload = jnp.asarray(r.integers(0, 1 << 30, rows).astype(np.int64))
    parts = args.parts

    @jax.jit
    def hash_partition(keys, payload):
        pid = (hash_columns([keys]).view(jnp.int64) % parts).astype(
            jnp.int32
        )
        order = stable_argsort(pid)
        return payload[order]

    dt = _amortized(hash_partition, keys, payload)
    moved = rows * 8 * 2  # key read + payload move (bucket-ordered write)
    records.append(
        {
            "name": "shuffle_hash_partition_local",
            "tags": {"platform": platform, "partitions": parts},
            "seconds": round(dt, 6),
            "gb_per_s": round(moved / dt / 1e9, 3),
            "bytes": moved,
        }
    )

    out = "\n".join(json.dumps(rec) for rec in records)
    print(out)
    if args.output:
        Path(args.output).write_text(out + "\n")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
