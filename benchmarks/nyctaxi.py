#!/usr/bin/env python
"""NYC taxi benchmark.

ref benchmarks/src/bin/nyctaxi.rs:65-134 — registers the tripdata CSV and
runs the `fare_amt_by_passenger` aggregate N times, printing per-iteration
timings. The reference reads a downloaded tripdata CSV; this environment
has no egress, so a deterministic synthetic generator produces data with
the reference's schema (:136-157) — pass ``--data <csv>`` to use a real
tripdata file instead.

Usage: python benchmarks/nyctaxi.py [--rows N] [--iterations N] [--data csv]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

QUERIES = {
    # ref :104-105
    "fare_amt_by_passenger": (
        "SELECT passenger_count, MIN(fare_amount), MAX(fare_amount), "
        "SUM(fare_amount) FROM tripdata GROUP BY passenger_count"
    ),
}


def gen_tripdata(rows: int):
    """Synthetic tripdata with the reference's column layout (:136-157)."""
    import numpy as np
    import pyarrow as pa

    r = np.random.default_rng(7)
    fare = np.round(r.gamma(2.2, 6.0, rows), 2)
    tip = np.round(fare * r.uniform(0, 0.3, rows), 2)
    tolls = np.where(r.uniform(0, 1, rows) < 0.05, 6.55, 0.0)
    return pa.table(
        {
            "VendorID": pa.array(
                [str(v) for v in r.integers(1, 3, rows)]
            ),
            "passenger_count": pa.array(
                r.integers(1, 7, rows).astype("int32")
            ),
            "trip_distance": pa.array(
                [f"{d:.2f}" for d in r.gamma(1.8, 1.7, rows)]
            ),
            "payment_type": pa.array(
                [str(v) for v in r.integers(1, 5, rows)]
            ),
            "fare_amount": pa.array(fare),
            "extra": pa.array(np.where(r.uniform(0, 1, rows) < 0.5, 0.5, 0.0)),
            "mta_tax": pa.array(np.full(rows, 0.5)),
            "tip_amount": pa.array(tip),
            "tolls_amount": pa.array(tolls),
            "improvement_surcharge": pa.array(np.full(rows, 0.3)),
            "total_amount": pa.array(
                np.round(fare + tip + tolls + 1.3, 2)
            ),
        }
    )


def main() -> int:
    p = argparse.ArgumentParser(description="nyctaxi benchmark")
    p.add_argument("--rows", type=int, default=1_000_000)
    p.add_argument("--iterations", type=int, default=3)
    p.add_argument("--data", help="real tripdata CSV (default: synthetic)")
    args = p.parse_args()

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.exec.context import TpuContext

    ctx = TpuContext(
        BallistaConfig().with_setting("ballista.shuffle.partitions", "1")
    )
    if args.data:
        ctx.sql(
            "create external table tripdata stored as csv "
            f"with header row location '{args.data}'"
        )
    else:
        t0 = time.time()
        ctx.register_table("tripdata", gen_tripdata(args.rows))
        print(f"generated {args.rows} rows in {time.time() - t0:.2f}s")

    for name, sql in QUERIES.items():
        print(f"Executing '{name}'")
        for i in range(args.iterations):
            start = time.time()
            res = ctx.sql(sql).collect()
            ms = (time.time() - start) * 1000
            print(f"Query '{name}' iteration {i} took {ms:.0f} ms "
                  f"({res.num_rows} rows)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
