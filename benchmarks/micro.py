#!/usr/bin/env python
"""Kernel micro-benchmarks with conbench-style JSON output.

ref conbench/{benchmarks.py,_criterion.py} — the reference publishes
criterion micro-bench results (per-benchmark name + timing stats) to a
conbench server. Here the engine's kernel primitives are timed directly
(sort, grouped aggregate, join build/probe, hash partition, compaction)
and the same record shape is written to stdout / --output, ready for a
conbench POST or plain regression diffing.

Timing note: on the tunnelled TPU only a blocking fetch observes device
completion, so each sample times `run -> tiny fetch` and subtracts the
measured round-trip baseline.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def main() -> int:
    p = argparse.ArgumentParser(description="kernel micro-benchmarks")
    p.add_argument("--rows", type=int, default=1 << 20)
    p.add_argument("--samples", type=int, default=5)
    p.add_argument("-o", "--output", help="write JSON records here")
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    import ballista_tpu  # noqa: F401 — enables x64
    from ballista_tpu.ops.aggregate import AggOp, group_aggregate
    from ballista_tpu.ops.compact import compact
    from ballista_tpu.ops.join import JoinSide, build_side, probe_side
    from ballista_tpu.ops.partition import partition_ids
    from ballista_tpu.ops.perm import stable_argsort
    from ballista_tpu.columnar.batch import DeviceBatch
    from ballista_tpu.datatypes import DataType, Field, Schema

    n = args.rows
    r = np.random.default_rng(0)
    keys = jnp.asarray(r.integers(0, n // 4, n).astype(np.int64))
    vals = jnp.asarray(r.uniform(0, 100, n))
    valid = jnp.ones(n, dtype=bool)
    schema = Schema([Field("k", DataType.INT64), Field("v", DataType.FLOAT64)])
    batch = DeviceBatch(
        schema=schema, columns=(keys, vals), valid=valid,
        nulls=(None, None), dictionaries={},
    )
    dim_n = max(n // 16, 8)
    dim = DeviceBatch(
        schema=schema,
        columns=(
            jnp.asarray(np.arange(dim_n, dtype=np.int64)),
            jnp.asarray(r.uniform(0, 1, dim_n)),
        ),
        valid=jnp.ones(dim_n, dtype=bool),
        nulls=(None, None),
        dictionaries={},
    )

    trivial = jax.jit(lambda: jnp.zeros(()))
    np.asarray(trivial())
    t0 = time.time()
    np.asarray(trivial())
    rtt = time.time() - t0

    bt = build_side(dim, [0])

    cases = {
        "stable_argsort_i64": lambda: stable_argsort(keys),
        "group_aggregate_sum_count": lambda: group_aggregate(
            [keys], [None], valid, [vals, vals], [None, None],
            [AggOp.SUM, AggOp.COUNT], 1 << 18,
        ).n_groups,
        "join_build": lambda: build_side(dim, [0]).n,
        "join_probe": lambda: probe_side(bt, batch, [0], JoinSide.INNER).valid,
        "hash_partition_ids_8": lambda: partition_ids(batch, [0], 8),
        "compact": lambda: compact(batch).valid,
    }

    records = []
    for name, fn in cases.items():
        fn()  # compile
        samples = []
        for _ in range(args.samples):
            t0 = time.time()
            out = fn()
            leaf = jax.tree_util.tree_leaves(out)[0]
            np.asarray(leaf.reshape(-1)[:1] if leaf.ndim else leaf)
            samples.append(max(time.time() - t0 - rtt, 0.0))
        rec = {
            "run_name": "ballista-tpu-micro",
            "benchmark_name": name,
            "unit": "s",
            "rows": n,
            "stats": {
                "mean": statistics.mean(samples),
                "min": min(samples),
                "max": max(samples),
                "iterations": len(samples),
            },
        }
        records.append(rec)
        print(
            f"{name}: min {rec['stats']['min'] * 1000:.2f} ms "
            f"mean {rec['stats']['mean'] * 1000:.2f} ms over {n} rows"
        )
    if args.output:
        Path(args.output).write_text(json.dumps(records, indent=2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
