#!/usr/bin/env python
"""h2oai db-benchmark (groupby + join) adaptation.

ref benchmarks/db-benchmark/{groupby-datafusion.py,join-datafusion.py} —
the standard G1 groupby questions and the join benchmark, run over the
engine with synthetic data matching the h2o generator's shape (no egress:
the official x.csv inputs aren't downloadable here; pass --data to use a
real G1 file). Questions the engine doesn't support yet are skipped with
a note, mirroring how the reference comments out unsupported questions.

Usage: python benchmarks/db_benchmark.py [--n 1e6] [--k 100] [--iterations 2]
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

GROUPBY_QUERIES = {
    # ref groupby-datafusion.py:73-226 — all ten G1 questions run,
    # including q6's approx_percentile_cont (exact sort-based percentile,
    # exec/percentile.py)
    "q1": "SELECT id1, SUM(v1) AS v1 FROM x GROUP BY id1",
    "q2": "SELECT id1, id2, SUM(v1) AS v1 FROM x GROUP BY id1, id2",
    "q3": "SELECT id3, SUM(v1) AS v1, AVG(v3) AS v3 FROM x GROUP BY id3",
    "q4": "SELECT id4, AVG(v1) AS v1, AVG(v2) AS v2, AVG(v3) AS v3 "
          "FROM x GROUP BY id4",
    "q5": "SELECT id6, SUM(v1) AS v1, SUM(v2) AS v2, SUM(v3) AS v3 "
          "FROM x GROUP BY id6",
    "q6": "SELECT id4, id5, approx_percentile_cont(v3, 0.5) AS median_v3, "
          "stddev(v3) AS stddev_v3 FROM x GROUP BY id4, id5",
    "q7": "SELECT id3, MAX(v1) - MIN(v2) AS range_v1_v2 FROM x GROUP BY id3",
    "q8": "SELECT id6, v3 from (SELECT id6, v3, row_number() OVER "
          "(PARTITION BY id6 ORDER BY v3 DESC) AS row FROM x) t "
          "WHERE row <= 2",
    "q9": "SELECT id2, id4, corr(v1, v2) as corr FROM x GROUP BY id2, id4",
    "q10": "SELECT id1, id2, id3, id4, id5, id6, SUM(v3) as v3, "
           "COUNT(*) AS cnt FROM x GROUP BY id1, id2, id3, id4, id5, id6",
}

JOIN_QUERY = (
    "SELECT x.id1, x.v1, small.v2 FROM x JOIN small ON x.id1 = small.id1"
)


def gen_g1(n: int, k: int):
    """Synthetic G1 table with the h2o generator's column shape."""
    import numpy as np
    import pyarrow as pa

    r = np.random.default_rng(1)
    return pa.table(
        {
            "id1": pa.array([f"id{v:03d}" for v in r.integers(1, k + 1, n)]),
            "id2": pa.array([f"id{v:03d}" for v in r.integers(1, k + 1, n)]),
            "id3": pa.array(
                [f"id{v:010d}" for v in r.integers(1, max(n // k, 1) + 1, n)]
            ),
            "id4": pa.array(r.integers(1, k + 1, n).astype("int64")),
            "id5": pa.array(r.integers(1, k + 1, n).astype("int64")),
            "id6": pa.array(
                r.integers(1, max(n // k, 1) + 1, n).astype("int64")
            ),
            "v1": pa.array(r.integers(1, 6, n).astype("int64")),
            "v2": pa.array(r.integers(1, 16, n).astype("int64")),
            "v3": pa.array(np.round(r.uniform(0, 100, n), 6)),
        }
    )


def main() -> int:
    p = argparse.ArgumentParser(description="h2oai db-benchmark")
    p.add_argument("--n", type=float, default=1e6, help="rows")
    p.add_argument("--k", type=int, default=100, help="group cardinality")
    p.add_argument("--iterations", type=int, default=2)
    p.add_argument("--data", help="real G1 x.csv (default: synthetic)")
    args = p.parse_args()

    import numpy as np
    import pyarrow as pa

    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.exec.context import TpuContext

    ctx = TpuContext(
        BallistaConfig().with_setting("ballista.shuffle.partitions", "1")
    )
    n = int(args.n)
    if args.data:
        ctx.register_csv("x", args.data)
    else:
        t0 = time.time()
        ctx.register_table("x", gen_g1(n, args.k))
        print(f"generated {n} rows in {time.time() - t0:.2f}s")

    for name, sql in GROUPBY_QUERIES.items():
        for i in range(args.iterations):
            t0 = time.time()
            res = ctx.sql(sql).collect()
            print(
                f"groupby {name} run {i + 1}: {(time.time() - t0) * 1000:.0f} "
                f"ms ({res.num_rows} groups)"
            )

    # join benchmark (ref join-datafusion.py): x joined to a small dim
    r = np.random.default_rng(2)
    small = pa.table(
        {
            "id1": pa.array([f"id{v:03d}" for v in range(1, args.k + 1)]),
            "v2": pa.array(r.uniform(0, 100, args.k)),
        }
    )
    ctx.register_table("small", small)
    for i in range(args.iterations):
        t0 = time.time()
        res = ctx.sql(JOIN_QUERY).collect()
        print(
            f"join small run {i + 1}: {(time.time() - t0) * 1000:.0f} ms "
            f"({res.num_rows} rows)"
        )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
