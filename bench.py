#!/usr/bin/env python
"""TPC-H benchmark harness (ref: benchmarks/src/bin/tpch.rs:245-249 —
`tpch benchmark`, N iterations per query, JSON summary).

Runs the headline queries (BASELINE.md: q1/q3/q5/q6/q18) on the default
JAX backend (the TPU when tunnelled), with a cold (compile) pass and warm
iterations, then measures the same queries on the CPU backend in a
subprocess to form the BASELINE.md x5 denominator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "queries/sec", "vs_baseline": N}
where value = warm throughput over the headline set on this backend and
vs_baseline = speedup vs the CPU-executor run (>1 means the device is
faster; BASELINE.md target is >=5). Detailed per-query timings go to
BENCH_DETAIL.json and stderr.

Env knobs: BENCH_SF (default 1; 0.1 for a quick run), BENCH_ITERS
(default 3), BENCH_QUERIES (comma list, default q1,q3,q5,q6,q18),
BENCH_SKIP_CPU=1, BENCH_PREWARM=0 to disable the parallel compile
prewarm. On a fresh compilation cache the suite's cold passes are
dominated by serial XLA compiles (tens of seconds per program over the
tunnelled compile service), so the harness first runs every query ONCE
in concurrent subprocesses — the tunnelled chip multiplexes processes
and compiles are HTTP calls that parallelize — making the fresh-cache
wall clock ~the slowest single query instead of the sum. The measured
suite then runs against a hot persistent cache.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

HERE = pathlib.Path(__file__).resolve().parent
QDIR = HERE / "benchmarks" / "queries"

SF = float(os.environ.get("BENCH_SF", "1"))
ITERS = int(os.environ.get("BENCH_ITERS", "3"))
QUERIES = os.environ.get("BENCH_QUERIES", "q1,q3,q5,q6,q18").split(",")


def run_suite() -> dict:
    """Run the query set in-process on the current JAX backend."""
    sys.path.insert(0, str(HERE))
    import jax

    from ballista_tpu.exec.context import TpuContext
    from ballista_tpu.tpch import gen_all

    backend = jax.devices()[0].platform
    t0 = time.time()
    data = gen_all(scale=SF)
    gen_s = time.time() - t0
    from ballista_tpu.config import BallistaConfig

    if os.environ.get("BENCH_PREWARM_CHILD"):
        # compile-prewarm mode: execute each query once (populating the
        # persistent compilation cache) and exit — timings are discarded
        ctx = TpuContext(
            BallistaConfig().with_setting("ballista.shuffle.partitions", "1")
        )
        for name, t in data.items():
            ctx.register_table(name, t)
        for qn in QUERIES:
            ctx.sql((QDIR / f"{qn}.sql").read_text()).collect()
        print("{}")
        return {}

    # single-chip suite: host-side partition splitting only multiplies
    # blocking syncs (the XLA program parallelizes internally); distributed
    # partitioning is exercised by the cluster tests, not the chip bench
    ctx = TpuContext(
        BallistaConfig().with_setting("ballista.shuffle.partitions", "1")
    )
    rows = {}
    for name, t in data.items():
        ctx.register_table(name, t)
        rows[name] = t.num_rows

    out = {
        "backend": backend,
        "sf": SF,
        "gen_seconds": round(gen_s, 2),
        "table_rows": rows,
        "queries": {},
    }
    for qn in QUERIES:
        sql = (QDIR / f"{qn}.sql").read_text()
        t0 = time.time()
        res = ctx.sql(sql).collect()
        cold = time.time() - t0
        warms = []
        for _ in range(ITERS):
            t0 = time.time()
            res = ctx.sql(sql).collect()
            warms.append(time.time() - t0)
        out["queries"][qn] = {
            "cold_s": round(cold, 4),
            "warm_s": [round(w, 4) for w in warms],
            "warm_best_s": round(min(warms), 4),
            "rows": res.num_rows,
            "lineitem_rows_per_s": int(rows["lineitem"] / min(warms)),
        }
    out["warm_total_s"] = round(
        sum(q["warm_best_s"] for q in out["queries"].values()), 4
    )
    out["queries_per_s"] = round(len(QUERIES) / out["warm_total_s"], 4)
    return out


def _run_child(env: dict, iters: int, timeout: int, label: str):
    """Run one suite in a child process, returning its parsed result dict
    or None. Shared by the device and CPU phases; captures partial output
    on timeout (the wedged-TPU diagnosis) and tolerates trailing non-JSON
    stdout noise from library atexit handlers."""
    env = dict(env)
    env.update(
        {
            "BENCH_CHILD": "1",
            "BENCH_SF": str(SF),
            "BENCH_ITERS": str(iters),
            "BENCH_QUERIES": ",".join(QUERIES),
        }
    )
    try:
        proc = subprocess.run(
            [sys.executable, str(HERE / "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        tail = e.stderr or ""
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        print(
            f"{label} suite exceeded {timeout}s (wedged TPU runtime?); "
            f"partial stderr:\n{tail[-3000:]}",
            file=sys.stderr,
        )
        return None
    if proc.returncode != 0:
        print(f"{label} suite failed:\n{proc.stderr[-4000:]}", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"{label} suite produced no JSON:\n{proc.stdout[-2000:]}",
          file=sys.stderr)
    return None


def main() -> None:
    if os.environ.get("BENCH_CHILD"):
        print(json.dumps(run_suite()))
        return

    # The device suite runs in a SUBPROCESS with a hard timeout: a wedged
    # TPU tunnel (observed: any device op hanging indefinitely) must fail
    # this harness loudly instead of hanging the driver forever.
    device_env = dict(os.environ)
    # PREPEND to PYTHONPATH: clobbering it would break the axon platform
    # plugin the site config registers from it
    device_env["PYTHONPATH"] = os.pathsep.join(
        [str(HERE)]
        + ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH") else [])
    )
    # Parallel compile prewarm: one subprocess per query, concurrently.
    # Best-effort — failures fall through to the (slower, serial) cold
    # pass of the measured suite. Gated to modest SF: each child
    # regenerates the dataset in memory. A sentinel keyed by (code
    # revision, SF, query set) skips the whole phase on hot-cache
    # re-runs, where it could do no useful work.
    sentinel = None
    cache_dir = os.environ.get(
        "BALLISTA_TPU_JAX_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "ballista_tpu_jax"),
    )
    if cache_dir != "off":
        rev = ""
        try:
            rev = subprocess.run(
                ["git", "-C", str(HERE), "rev-parse", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip()
        except Exception:
            pass
        sentinel = pathlib.Path(cache_dir) / (
            f"prewarmed_{rev[:12]}_{SF}_{'_'.join(QUERIES)}"
        )
    if (
        os.environ.get("BENCH_PREWARM", "1") != "0"
        and SF <= 2
        and not (sentinel is not None and sentinel.exists())
    ):
        t0 = time.time()
        procs = []
        for qn in QUERIES:
            env = dict(device_env)
            env.update(
                {
                    "BENCH_CHILD": "1",
                    "BENCH_PREWARM_CHILD": "1",
                    "BENCH_SF": str(SF),
                    "BENCH_QUERIES": qn,
                    "BENCH_ITERS": "0",
                }
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(HERE / "bench.py")],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            )
        deadline = time.time() + int(
            os.environ.get("BENCH_PREWARM_TIMEOUT", 1800)
        )
        for p in procs:
            try:
                p.wait(timeout=max(1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        print(
            f"prewarm: {len(procs)} queries compiled in "
            f"{time.time() - t0:.0f}s",
            file=sys.stderr,
        )
        if sentinel is not None:
            try:
                sentinel.parent.mkdir(parents=True, exist_ok=True)
                sentinel.touch()
            except OSError:
                pass

    device_run = _run_child(
        device_env,
        ITERS,
        int(os.environ.get("BENCH_DEVICE_TIMEOUT", 2700)),
        "device",
    )
    if device_run is None:
        raise SystemExit(1)

    cpu_run = None
    if not os.environ.get("BENCH_SKIP_CPU"):
        env = {
            k: v
            for k, v in os.environ.items()
            if not k.startswith(("PALLAS_AXON", "AXON"))
        }
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": str(HERE)})
        # CPU baseline is best-effort: a failure degrades vs_baseline to 0.
        # Same warm-iteration count as the device so best-of-N variance
        # treats both backends identically.
        cpu_run = _run_child(
            env, ITERS, int(os.environ.get("BENCH_CPU_TIMEOUT", 3600)),
            "cpu",
        )

    detail = {"device": device_run, "cpu": cpu_run}

    # Pinned denominator: a frozen, committed CPU-baseline artifact so
    # round-over-round ratios measure the DEVICE, not drift in a shared
    # host's CPU timings (observed ±30% swings across rounds). Freeze the
    # current live CPU suite with BENCH_FREEZE=1; vs_frozen is reported
    # alongside the live ratio whenever SF + query set match.
    frozen_path = HERE / "BENCH_BASELINE.json"
    vs_frozen = None
    if cpu_run is not None and os.environ.get("BENCH_FREEZE"):
        frozen_path.write_text(
            json.dumps(
                {"sf": SF, "queries": sorted(QUERIES), "cpu": cpu_run},
                indent=2,
            )
        )
    if frozen_path.exists():
        try:
            frozen = json.loads(frozen_path.read_text())
            if frozen.get("sf") == SF and frozen.get("queries") == sorted(
                QUERIES
            ):
                ft = sum(
                    q["warm_best_s"]
                    for q in frozen["cpu"]["queries"].values()
                )
                vs_frozen = round(ft / device_run["warm_total_s"], 3)
                detail["frozen_cpu_total_s"] = round(ft, 4)
        except (json.JSONDecodeError, KeyError, TypeError):
            pass

    (HERE / "BENCH_DETAIL.json").write_text(json.dumps(detail, indent=2))
    print(json.dumps(detail, indent=2), file=sys.stderr)

    vs = 0.0
    if cpu_run is not None:
        # speedup on identical warm work: cpu_total / device_total
        cpu_total = sum(q["warm_best_s"] for q in cpu_run["queries"].values())
        vs = round(cpu_total / device_run["warm_total_s"], 3)
    line = {
        "metric": (
            f"tpch_sf{SF}_warm_throughput_"
            + "_".join(QUERIES)
            + f"_{device_run['backend']}"
        ),
        "value": device_run["queries_per_s"],
        "unit": "queries/sec",
        "vs_baseline": vs,
    }
    if vs_frozen is not None:
        line["vs_frozen_cpu"] = vs_frozen
    print(json.dumps(line))


if __name__ == "__main__":
    main()
