#!/usr/bin/env python
"""TPC-H benchmark harness (ref: benchmarks/src/bin/tpch.rs:245-249 —
`tpch benchmark`, N iterations per query, JSON summary).

Runs the headline queries (BASELINE.md: q1/q3/q5/q6/q18) on the default
JAX backend (the TPU when tunnelled), with a cold (compile) pass and warm
iterations, then measures the same queries on the CPU backend in a
subprocess to form the BASELINE.md x5 denominator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "queries/sec", "vs_baseline": N}
where value = warm throughput over the headline set on this backend and
vs_baseline = speedup vs the CPU-executor run (>1 means the device is
faster; BASELINE.md target is >=5). Detailed per-query timings go to
BENCH_DETAIL.json and stderr.

Env knobs: BENCH_SF (default 1; 0.1 for a quick run), BENCH_ITERS
(default 3), BENCH_QUERIES (comma list, default q1,q3,q5,q6,q18),
BENCH_SKIP_CPU=1, BENCH_PREWARM=0 to disable the parallel compile
prewarm. BENCH_CONFIG applies extra session settings
("ballista.tpu.hbm_budget_mb=16384,ballista.tpu.scan_stream_mb=2048");
BENCH_PARQUET=1 registers the tables as parquet files (written once to
BENCH_PARQUET_DIR, default ./bench_data/sf<SF>) so the streamed-scan +
prefetch paths and row-group pruning are exercised — the SF>=10
out-of-core configurations. BENCH_STREAM_SLICE_MB shrinks the streamed
slice (default 1GB) and BENCH_ROW_GROUP_ROWS the written row groups
(default 1M rows) so the prefetch A/B also runs at small SF.
Details land in BENCH_DETAIL.json (SF=1) or
BENCH_SF<SF>_DETAIL.json, with peak host RSS, per-query spill bytes /
passes, and — when a query streamed — a prefetch-disabled A/B warm
timing. On a fresh compilation cache the suite's cold passes are
dominated by serial XLA compiles (tens of seconds per program over the
tunnelled compile service), so the harness first runs every query ONCE
in concurrent subprocesses — the tunnelled chip multiplexes processes
and compiles are HTTP calls that parallelize — making the fresh-cache
wall clock ~the slowest single query instead of the sum. The measured
suite then runs against a hot persistent cache.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

HERE = pathlib.Path(__file__).resolve().parent
QDIR = HERE / "benchmarks" / "queries"

SF = float(os.environ.get("BENCH_SF", "1"))
ITERS = int(os.environ.get("BENCH_ITERS", "3"))
QUERIES = os.environ.get("BENCH_QUERIES", "q1,q3,q5,q6,q18").split(",")


def _bench_config():
    from ballista_tpu.config import BallistaConfig

    # single-chip suite: host-side partition splitting only multiplies
    # blocking syncs (the XLA program parallelizes internally); distributed
    # partitioning is exercised by the cluster tests, not the chip bench
    cfg = BallistaConfig().with_setting("ballista.shuffle.partitions", "1")
    for kv in os.environ.get("BENCH_CONFIG", "").split(","):
        if kv.strip():
            k, v = kv.split("=", 1)
            cfg = cfg.with_setting(k.strip(), v.strip())
    return cfg


def _register_tables(ctx) -> tuple[dict, float]:
    """Register the TPC-H tables; returns ({name: rows}, gen_seconds).
    BENCH_PARQUET=1 writes the tables once to parquet (multiple row
    groups, so the streamed scan / prefetch / pruning paths run) and
    registers the files; generation is skipped entirely when the files
    already exist — at SF>=10 that is most of a cold run's wall clock."""
    import pyarrow.parquet as papq

    from ballista_tpu.tpch import all_schemas

    names = list(all_schemas())
    rows: dict = {}
    if os.environ.get("BENCH_PARQUET"):
        pdir = pathlib.Path(
            os.environ.get("BENCH_PARQUET_DIR", HERE / "bench_data")
        ) / f"sf{SF:g}"
        gen_s = 0.0
        missing = [n for n in names if not (pdir / f"{n}.parquet").exists()]
        if missing:
            from ballista_tpu.tpch import gen_all

            pdir.mkdir(parents=True, exist_ok=True)
            t0 = time.time()
            data = gen_all(scale=SF)
            rg_rows = int(os.environ.get("BENCH_ROW_GROUP_ROWS", 1 << 20))
            for name in missing:
                papq.write_table(
                    data[name], pdir / f"{name}.parquet",
                    row_group_size=rg_rows,
                )
            gen_s = time.time() - t0
        for name in names:
            path = str(pdir / f"{name}.parquet")
            ctx.register_parquet(name, path)
            rows[name] = papq.ParquetFile(path).metadata.num_rows
        return rows, gen_s
    from ballista_tpu.tpch import gen_all

    t0 = time.time()
    data = gen_all(scale=SF)
    gen_s = time.time() - t0
    for name, t in data.items():
        ctx.register_table(name, t)
        rows[name] = t.num_rows
    return rows, gen_s


def _peak_rss_mb() -> float:
    import resource

    return round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1
    )


_PLAN_COUNTERS = (
    "spill_bytes", "spill_passes", "stream_slices",
    "prefetch_hits", "prefetch_misses",
)


def _plan_counters(phys) -> dict:
    from ballista_tpu.exec.base import plan_counters

    return {
        k: v for k, v in plan_counters(phys, _PLAN_COUNTERS).items() if v
    }


def _collect_with_plan(ctx, sql: str):
    """(table, rows, executed plan) — the plan so per-query metrics
    (spill bytes, prefetch hit ratio) can be read AFTER the run."""
    t, phys = ctx.sql(sql).collect_with_plan()
    return t, t.num_rows, phys


def run_suite() -> dict:
    """Run the query set in-process on the current JAX backend."""
    sys.path.insert(0, str(HERE))
    import jax

    from ballista_tpu.exec.context import TpuContext

    backend = jax.devices()[0].platform
    cfg = _bench_config()

    ssmb = os.environ.get("BENCH_STREAM_SLICE_MB")
    if ssmb:
        # shrink streamed-scan slices so the prefetch A/B is exercisable
        # below SF=10 (default slice is 1GB: smaller runs see one slice
        # and the overlap has nothing to hide behind)
        from ballista_tpu.exec.scan import ParquetScanExec

        ParquetScanExec.STREAM_SLICE_BYTES = int(float(ssmb) * (1 << 20))

    if os.environ.get("BENCH_PREWARM_CHILD"):
        # compile-prewarm mode: execute each query once (populating the
        # persistent compilation cache) and exit — timings are discarded
        ctx = TpuContext(cfg)
        _register_tables(ctx)
        for qn in QUERIES:
            ctx.sql((QDIR / f"{qn}.sql").read_text()).collect()
        print("{}")
        return {}

    ctx = TpuContext(cfg)
    rows, gen_s = _register_tables(ctx)

    out = {
        "backend": backend,
        "sf": SF,
        "gen_seconds": round(gen_s, 2),
        "table_rows": rows,
        "config": cfg.settings(),
        "queries": {},
    }
    prefetch_on = cfg.prefetch_depth() > 0
    for qn in QUERIES:
        sql = (QDIR / f"{qn}.sql").read_text()
        t0 = time.time()
        _, nrows, phys = _collect_with_plan(ctx, sql)
        cold = time.time() - t0
        warms = []
        for _ in range(ITERS):
            t0 = time.time()
            _, nrows, phys = _collect_with_plan(ctx, sql)
            warms.append(time.time() - t0)
        counters = _plan_counters(phys)
        q = {
            "cold_s": round(cold, 4),
            "warm_s": [round(w, 4) for w in warms],
            "warm_best_s": round(min(warms), 4),
            "rows": nrows,
            "lineitem_rows_per_s": int(rows["lineitem"] / min(warms)),
            **counters,
        }
        hits = counters.get("prefetch_hits", 0)
        misses = counters.get("prefetch_misses", 0)
        if hits + misses:
            q["prefetch_hit_ratio"] = round(hits / (hits + misses), 3)
        if prefetch_on and counters.get("stream_slices", 0) > 1:
            # prefetch A/B on streamed queries: same data, same run, depth
            # 0 — the acceptance signal that compute/IO overlap pays
            old = ctx.config
            ctx.config = old.with_setting("ballista.tpu.prefetch_depth", "0")
            try:
                _collect_with_plan(ctx, sql)  # cold (fresh plan instance)
                nwarmeans = []
                for _ in range(ITERS):
                    t0 = time.time()
                    _collect_with_plan(ctx, sql)
                    nwarmeans.append(time.time() - t0)
            finally:
                ctx.config = old
            q["warm_noprefetch_s"] = [round(w, 4) for w in nwarmeans]
            q["prefetch_speedup"] = round(
                min(nwarmeans) / max(min(warms), 1e-9), 3
            )
        out["queries"][qn] = q
    out["warm_total_s"] = round(
        sum(q["warm_best_s"] for q in out["queries"].values()), 4
    )
    out["queries_per_s"] = round(len(QUERIES) / out["warm_total_s"], 4)
    out["peak_rss_mb"] = _peak_rss_mb()
    out["spill_bytes_total"] = sum(
        q.get("spill_bytes", 0) for q in out["queries"].values()
    )
    return out


def _run_child(env: dict, iters: int, timeout: int, label: str):
    """Run one suite in a child process, returning its parsed result dict
    or None. Shared by the device and CPU phases; captures partial output
    on timeout (the wedged-TPU diagnosis) and tolerates trailing non-JSON
    stdout noise from library atexit handlers."""
    env = dict(env)
    env.update(
        {
            "BENCH_CHILD": "1",
            "BENCH_SF": str(SF),
            "BENCH_ITERS": str(iters),
            "BENCH_QUERIES": ",".join(QUERIES),
        }
    )
    try:
        proc = subprocess.run(
            [sys.executable, str(HERE / "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        tail = e.stderr or ""
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        print(
            f"{label} suite exceeded {timeout}s (wedged TPU runtime?); "
            f"partial stderr:\n{tail[-3000:]}",
            file=sys.stderr,
        )
        return None
    if proc.returncode != 0:
        print(f"{label} suite failed:\n{proc.stderr[-4000:]}", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"{label} suite produced no JSON:\n{proc.stdout[-2000:]}",
          file=sys.stderr)
    return None


def main() -> None:
    if os.environ.get("BENCH_CHILD"):
        print(json.dumps(run_suite()))
        return

    # The device suite runs in a SUBPROCESS with a hard timeout: a wedged
    # TPU tunnel (observed: any device op hanging indefinitely) must fail
    # this harness loudly instead of hanging the driver forever.
    device_env = dict(os.environ)
    # PREPEND to PYTHONPATH: clobbering it would break the axon platform
    # plugin the site config registers from it
    device_env["PYTHONPATH"] = os.pathsep.join(
        [str(HERE)]
        + ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH") else [])
    )
    # Parallel compile prewarm: one subprocess per query, concurrently.
    # Best-effort — failures fall through to the (slower, serial) cold
    # pass of the measured suite. Gated to modest SF: each child
    # regenerates the dataset in memory. A sentinel keyed by (code
    # revision, SF, query set) skips the whole phase on hot-cache
    # re-runs, where it could do no useful work.
    sentinel = None
    cache_dir = os.environ.get(
        "BALLISTA_TPU_JAX_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "ballista_tpu_jax"),
    )
    if cache_dir != "off":
        rev = ""
        try:
            rev = subprocess.run(
                ["git", "-C", str(HERE), "rev-parse", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip()
        except Exception:
            pass
        sentinel = pathlib.Path(cache_dir) / (
            f"prewarmed_{rev[:12]}_{SF}_{'_'.join(QUERIES)}"
        )
    if (
        os.environ.get("BENCH_PREWARM", "1") != "0"
        and SF <= 2
        and not (sentinel is not None and sentinel.exists())
    ):
        t0 = time.time()
        procs = []
        for qn in QUERIES:
            env = dict(device_env)
            env.update(
                {
                    "BENCH_CHILD": "1",
                    "BENCH_PREWARM_CHILD": "1",
                    "BENCH_SF": str(SF),
                    "BENCH_QUERIES": qn,
                    "BENCH_ITERS": "0",
                }
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(HERE / "bench.py")],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            )
        deadline = time.time() + int(
            os.environ.get("BENCH_PREWARM_TIMEOUT", 1800)
        )
        for p in procs:
            try:
                p.wait(timeout=max(1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        print(
            f"prewarm: {len(procs)} queries compiled in "
            f"{time.time() - t0:.0f}s",
            file=sys.stderr,
        )
        if sentinel is not None:
            try:
                sentinel.parent.mkdir(parents=True, exist_ok=True)
                sentinel.touch()
            except OSError:
                pass

    device_run = _run_child(
        device_env,
        ITERS,
        int(os.environ.get("BENCH_DEVICE_TIMEOUT", 2700)),
        "device",
    )
    if device_run is None:
        raise SystemExit(1)

    cpu_run = None
    if not os.environ.get("BENCH_SKIP_CPU"):
        env = {
            k: v
            for k, v in os.environ.items()
            if not k.startswith(("PALLAS_AXON", "AXON"))
        }
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": str(HERE)})
        # CPU baseline is best-effort: a failure degrades vs_baseline to 0.
        # Same warm-iteration count as the device so best-of-N variance
        # treats both backends identically.
        cpu_run = _run_child(
            env, ITERS, int(os.environ.get("BENCH_CPU_TIMEOUT", 3600)),
            "cpu",
        )

    detail = {"device": device_run, "cpu": cpu_run}

    # Pinned denominator: a frozen, committed CPU-baseline artifact so
    # round-over-round ratios measure the DEVICE, not drift in a shared
    # host's CPU timings (observed ±30% swings across rounds). Freeze the
    # current live CPU suite with BENCH_FREEZE=1. Frozen baselines are
    # KEYED BY SCALE FACTOR (one file per SF) so SF=10/SF=100 runs report
    # vs_frozen_cpu against their own denominator instead of silently
    # falling back to the live CPU ratio; the legacy un-keyed file is
    # still honored for SF=1 readers of old artifacts.
    frozen_path = HERE / f"BENCH_BASELINE_SF{SF:g}.json"
    legacy_path = HERE / "BENCH_BASELINE.json"
    vs_frozen = None
    if cpu_run is not None and os.environ.get("BENCH_FREEZE"):
        frozen_path.write_text(
            json.dumps(
                {"sf": SF, "queries": sorted(QUERIES), "cpu": cpu_run},
                indent=2,
            )
        )
    for path in (frozen_path, legacy_path):
        if not path.exists():
            continue
        try:
            frozen = json.loads(path.read_text())
            if frozen.get("sf") == SF and frozen.get("queries") == sorted(
                QUERIES
            ):
                ft = sum(
                    q["warm_best_s"]
                    for q in frozen["cpu"]["queries"].values()
                )
                vs_frozen = round(ft / device_run["warm_total_s"], 3)
                detail["frozen_cpu_total_s"] = round(ft, 4)
                break
        except (json.JSONDecodeError, KeyError, TypeError):
            pass

    detail_path = HERE / (
        "BENCH_DETAIL.json" if SF == 1 else f"BENCH_SF{SF:g}_DETAIL.json"
    )
    detail_path.write_text(json.dumps(detail, indent=2))
    print(json.dumps(detail, indent=2), file=sys.stderr)

    vs = 0.0
    if cpu_run is not None:
        # speedup on identical warm work: cpu_total / device_total
        cpu_total = sum(q["warm_best_s"] for q in cpu_run["queries"].values())
        vs = round(cpu_total / device_run["warm_total_s"], 3)
    line = {
        "metric": (
            f"tpch_sf{SF}_warm_throughput_"
            + "_".join(QUERIES)
            + f"_{device_run['backend']}"
        ),
        "value": device_run["queries_per_s"],
        "unit": "queries/sec",
        "vs_baseline": vs,
    }
    if vs_frozen is not None:
        line["vs_frozen_cpu"] = vs_frozen
    print(json.dumps(line))


if __name__ == "__main__":
    main()
