#!/usr/bin/env python
"""TPC-H benchmark harness (ref: benchmarks/src/bin/tpch.rs:245-249 —
`tpch benchmark`, N iterations per query, JSON summary).

Runs the headline queries (BASELINE.md: q1/q3/q5/q6/q18) on the default
JAX backend (the TPU when tunnelled), with a cold (compile) pass and warm
iterations, then measures the same queries on the CPU backend in a
subprocess to form the BASELINE.md x5 denominator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "queries/sec", "vs_baseline": N}
where value = warm throughput over the headline set on this backend and
vs_baseline = speedup vs the CPU-executor run (>1 means the device is
faster; BASELINE.md target is >=5). Detailed per-query timings go to
BENCH_DETAIL.json and stderr.

Env knobs: BENCH_SF (default 1; 0.1 for a quick run), BENCH_ITERS
(default 3), BENCH_QUERIES (comma list, default q1,q3,q5,q6,q18),
BENCH_SKIP_CPU=1, BENCH_PREWARM=0 to disable the parallel compile
prewarm. BENCH_CONFIG applies extra session settings
("ballista.tpu.hbm_budget_mb=16384,ballista.tpu.scan_stream_mb=2048");
BENCH_PARQUET=1 registers the tables as parquet files (written once to
BENCH_PARQUET_DIR, default ./bench_data/sf<SF>) so the streamed-scan +
prefetch paths and row-group pruning are exercised — the SF>=10
out-of-core configurations. BENCH_STREAM_SLICE_MB shrinks the streamed
slice (default 1GB) and BENCH_ROW_GROUP_ROWS the written row groups
(default 1M rows) so the prefetch A/B also runs at small SF.
BENCH_SERVE=1 runs the serving fast-path suite (docs/serving.md):
result-cache cold-vs-hit, a saturated closed-loop point-query ablation
(bypass on/off, grant batch 4/1), and the open-loop mixed sweep with
cache/bypass/batch ablation arms, writing BENCH_SERVE.json.
BENCH_AQE=1 runs the adaptive-query-execution suite (docs/aqe.md):
adaptive-vs-static on seeded skewed/misestimated data plus a TPC-H
warm guardrail, writing BENCH_AQE.json.
Details land in BENCH_DETAIL.json (SF=1) or
BENCH_SF<SF>_DETAIL.json, with peak host RSS, per-query spill bytes /
passes, and — when a query streamed — a prefetch-disabled A/B warm
timing. On a fresh compilation cache the suite's cold passes are
dominated by serial XLA compiles (tens of seconds per program over the
tunnelled compile service), so the harness first runs every query ONCE
in concurrent subprocesses — the tunnelled chip multiplexes processes
and compiles are HTTP calls that parallelize — making the fresh-cache
wall clock ~the slowest single query instead of the sum. The measured
suite then runs against a hot persistent cache.
"""

import json
import os
import pathlib
import subprocess
import sys
import time

HERE = pathlib.Path(__file__).resolve().parent
QDIR = HERE / "benchmarks" / "queries"

SF = float(os.environ.get("BENCH_SF", "1"))
ITERS = int(os.environ.get("BENCH_ITERS", "3"))
QUERIES = os.environ.get("BENCH_QUERIES", "q1,q3,q5,q6,q18").split(",")


def _bench_config():
    from ballista_tpu.config import BallistaConfig

    # single-chip suite: host-side partition splitting only multiplies
    # blocking syncs (the XLA program parallelizes internally); distributed
    # partitioning is exercised by the cluster tests, not the chip bench
    cfg = BallistaConfig().with_setting("ballista.shuffle.partitions", "1")
    for kv in os.environ.get("BENCH_CONFIG", "").split(","):
        if kv.strip():
            k, v = kv.split("=", 1)
            cfg = cfg.with_setting(k.strip(), v.strip())
    return cfg


def _register_tables(ctx) -> tuple[dict, float]:
    """Register the TPC-H tables; returns ({name: rows}, gen_seconds).
    BENCH_PARQUET=1 writes the tables once to parquet (multiple row
    groups, so the streamed scan / prefetch / pruning paths run) and
    registers the files; generation is skipped entirely when the files
    already exist — at SF>=10 that is most of a cold run's wall clock."""
    import pyarrow.parquet as papq

    from ballista_tpu.tpch import all_schemas

    names = list(all_schemas())
    rows: dict = {}
    if os.environ.get("BENCH_PARQUET"):
        pdir = pathlib.Path(
            os.environ.get("BENCH_PARQUET_DIR", HERE / "bench_data")
        ) / f"sf{SF:g}"
        gen_s = 0.0
        missing = [n for n in names if not (pdir / f"{n}.parquet").exists()]
        if missing:
            from ballista_tpu.tpch import gen_all

            pdir.mkdir(parents=True, exist_ok=True)
            t0 = time.time()
            data = gen_all(scale=SF)
            rg_rows = int(os.environ.get("BENCH_ROW_GROUP_ROWS", 1 << 20))
            for name in missing:
                papq.write_table(
                    data[name], pdir / f"{name}.parquet",
                    row_group_size=rg_rows,
                )
            gen_s = time.time() - t0
        for name in names:
            path = str(pdir / f"{name}.parquet")
            ctx.register_parquet(name, path)
            rows[name] = papq.ParquetFile(path).metadata.num_rows
        return rows, gen_s
    from ballista_tpu.tpch import gen_all

    t0 = time.time()
    data = gen_all(scale=SF)
    gen_s = time.time() - t0
    for name, t in data.items():
        ctx.register_table(name, t)
        rows[name] = t.num_rows
    return rows, gen_s


def _peak_rss_mb() -> float:
    import resource

    return round(
        resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1
    )


def _percentiles(xs) -> dict:
    """Nearest-rank p50/p95/p99 over a latency sample (tail tracking:
    means hide exactly the latencies an SLO cares about). Keys match the
    per-query BENCH_* plan-artifact fields."""
    s = sorted(xs)

    def pct(p: float) -> float:
        if not s:
            return 0.0
        return s[min(len(s) - 1, int(round(p * (len(s) - 1))))]

    return {
        "p50": round(pct(0.50), 4),
        "p95": round(pct(0.95), 4),
        "p99": round(pct(0.99), 4),
    }


_PLAN_COUNTERS = (
    "spill_bytes", "spill_passes", "stream_slices",
    "prefetch_hits", "prefetch_misses",
    # shuffle data-plane counters (executor/reader.py): populated when a
    # plan contains ShuffleReaderExec nodes (distributed runs; the
    # single-chip suite shuffles with partitions=1 and shows zeros)
    "fetched_bytes", "fetched_batches",
    "fetch_overlap_hits", "fetch_overlap_misses", "eager_polls",
    # push-shuffle counters (docs/shuffle.md): in-memory bytes committed
    # by writers, bytes the window spilled to disk, and reads that fell
    # back from a push location to the pull plane
    "pushed_bytes", "push_spill_bytes", "push_fallbacks",
)


def _plan_counters(phys) -> dict:
    from ballista_tpu.exec.base import plan_counters

    return {
        k: v for k, v in plan_counters(phys, _PLAN_COUNTERS).items() if v
    }


def _cost_fields(history_store) -> dict:
    """Tracked cost fields (docs/observability.md "Cost accounting"):
    the newest query-log record's cost vector — the perf trajectory
    records efficiency (cpu/shuffle/spill) alongside latency in every
    BENCH_* per-query plan artifact. Reads the engine's OWN accounting
    (the local context's query log, or a cluster scheduler's history
    store) instead of re-measuring."""
    try:
        rows = history_store.jobs(limit=1)
    except Exception:  # noqa: BLE001 — accounting off / empty store
        return {}
    if not rows:
        return {}
    cost = rows[0].get("cost") or {}
    return {
        "cpu_seconds": round(float(cost.get("cpu_seconds", 0)), 4),
        "shuffle_bytes": int(cost.get("shuffle_read_bytes", 0))
        + int(cost.get("shuffle_write_bytes", 0)),
        "spill_bytes": int(cost.get("spill_bytes", 0)),
    }


def _collect_with_plan(ctx, sql: str):
    """(table, rows, executed plan) — the plan so per-query metrics
    (spill bytes, prefetch hit ratio) can be read AFTER the run."""
    t, phys = ctx.sql(sql).collect_with_plan()
    return t, t.num_rows, phys


def run_suite() -> dict:
    """Run the query set in-process on the current JAX backend."""
    sys.path.insert(0, str(HERE))
    import jax

    from ballista_tpu.exec.context import TpuContext

    backend = jax.devices()[0].platform
    cfg = _bench_config()

    ssmb = os.environ.get("BENCH_STREAM_SLICE_MB")
    if ssmb:
        # shrink streamed-scan slices so the prefetch A/B is exercisable
        # below SF=10 (default slice is 1GB: smaller runs see one slice
        # and the overlap has nothing to hide behind)
        from ballista_tpu.exec.scan import ParquetScanExec

        ParquetScanExec.STREAM_SLICE_BYTES = int(float(ssmb) * (1 << 20))

    if os.environ.get("BENCH_PREWARM_CHILD"):
        # compile-prewarm mode: execute each query once (populating the
        # persistent compilation cache) and exit — timings are discarded
        ctx = TpuContext(cfg)
        _register_tables(ctx)
        for qn in QUERIES:
            ctx.sql((QDIR / f"{qn}.sql").read_text()).collect()
        print("{}")
        return {}

    ctx = TpuContext(cfg)
    rows, gen_s = _register_tables(ctx)

    out = {
        "backend": backend,
        "sf": SF,
        "gen_seconds": round(gen_s, 2),
        "table_rows": rows,
        "config": cfg.settings(),
        "queries": {},
    }
    from ballista_tpu.compilecache import metrics as compile_metrics

    prefetch_on = cfg.prefetch_depth() > 0
    for qn in QUERIES:
        sql = (QDIR / f"{qn}.sql").read_text()
        # compile-latency tracking (docs/compile_cache.md): traces during
        # the cold pass = the query's distinct-signature count this
        # process; compile_seconds = wall time inside XLA backend compiles
        with compile_metrics.delta() as cold_d:
            t0 = time.time()
            _, nrows, phys = _collect_with_plan(ctx, sql)
            cold = time.time() - t0
        warms = []
        with compile_metrics.delta() as warm_d:
            for _ in range(ITERS):
                t0 = time.time()
                _, nrows, phys = _collect_with_plan(ctx, sql)
                warms.append(time.time() - t0)
        counters = _plan_counters(phys)
        warm_pcts = _percentiles(warms)
        q = {
            "cold_s": round(cold, 4),
            "warm_s": [round(w, 4) for w in warms],
            "warm_best_s": round(min(warms), 4),
            # tail tracking across repeats (docs/observability.md): the
            # perf trajectory keeps tails, not just bests/averages
            "warm_p50_s": warm_pcts["p50"],
            "warm_p95_s": warm_pcts["p95"],
            "warm_p99_s": warm_pcts["p99"],
            "rows": nrows,
            "lineitem_rows_per_s": int(rows["lineitem"] / min(warms)),
            # tracked compile-cost fields (BENCH_* plan schema): future
            # rounds chart compile cost alongside throughput
            "n_signatures": int(cold_d.value.get("traces", 0)),
            "compile_seconds": round(
                cold_d.value.get("compile_seconds", 0), 4
            ),
            "warm_retraces": int(warm_d.value.get("traces", 0)),
            **counters,
        }
        # tracked cost fields (docs/observability.md): the final warm
        # pass's cost vector from the context's own query log
        q.update(_cost_fields(ctx._system_history()))
        hits = counters.get("prefetch_hits", 0)
        misses = counters.get("prefetch_misses", 0)
        if hits + misses:
            q["prefetch_hit_ratio"] = round(hits / (hits + misses), 3)
        # observability overhead tracking (docs/observability.md):
        # (1) tracing-off overhead must be NIL — with ballista.tpu.trace
        # at its "off" default, no span may have been recorded by the
        # timed passes above (the off path never mints a trace context,
        # so the in-process ring stays empty — asserted, not hoped);
        # (2) BENCH_PROFILE=1 additionally measures EXPLAIN ANALYZE-style
        # per-operator capture: one instrumented warm pass, overhead
        # reported per query.
        from ballista_tpu.obs import trace as obs_trace

        if cfg.trace() == "off":
            n_spans = len(obs_trace.snapshot())
            assert n_spans == 0, (
                f"{qn}: tracing is off but {n_spans} spans were recorded "
                "— the off path must cost (and allocate) nothing"
            )
            q["trace_off_spans"] = 0
        if os.environ.get("BENCH_PROFILE"):
            from ballista_tpu.obs import profile as obs_profile

            # `phys` is the instance the physical-plan cache returns for
            # this (query, config, data) key, so the timed pass below
            # re-executes exactly this instrumented tree (cache hits
            # reset metrics but keep the metering wrappers)
            obs_profile.instrument_plan(phys)
            t0 = time.time()
            _collect_with_plan(ctx, sql)
            profiled = time.time() - t0
            q["profile_capture_s"] = round(profiled, 4)
            q["profile_overhead_s"] = round(profiled - min(warms), 4)
        if prefetch_on and counters.get("stream_slices", 0) > 1:
            # prefetch A/B on streamed queries: same data, same run, depth
            # 0 — the acceptance signal that compute/IO overlap pays
            old = ctx.config
            ctx.config = old.with_setting("ballista.tpu.prefetch_depth", "0")
            try:
                _collect_with_plan(ctx, sql)  # cold (fresh plan instance)
                nwarmeans = []
                for _ in range(ITERS):
                    t0 = time.time()
                    _collect_with_plan(ctx, sql)
                    nwarmeans.append(time.time() - t0)
            finally:
                ctx.config = old
            q["warm_noprefetch_s"] = [round(w, 4) for w in nwarmeans]
            q["prefetch_speedup"] = round(
                min(nwarmeans) / max(min(warms), 1e-9), 3
            )
        out["queries"][qn] = q
    out["warm_total_s"] = round(
        sum(q["warm_best_s"] for q in out["queries"].values()), 4
    )
    out["queries_per_s"] = round(len(QUERIES) / out["warm_total_s"], 4)
    # whole-suite compile surface: distinct signatures traced and XLA
    # compile seconds across every query this process ran (cold + warm —
    # warm retraces count too, they are exactly what tracecache kills)
    suite_compile = compile_metrics.snapshot()
    out["n_signatures"] = int(suite_compile.get("traces", 0))
    out["compile_seconds"] = round(
        suite_compile.get("compile_seconds", 0), 4
    )
    out["persistent_cache_hits"] = int(
        suite_compile.get("persistent_cache_hits", 0)
    )
    out["persistent_cache_misses"] = int(
        suite_compile.get("persistent_cache_misses", 0)
    )
    out["peak_rss_mb"] = _peak_rss_mb()
    out["spill_bytes_total"] = sum(
        q.get("spill_bytes", 0) for q in out["queries"].values()
    )
    return out


def run_shuffle_suite() -> dict:
    """BENCH_SHUFFLE=1: the shuffle data-plane benchmark (ISSUE 6 /
    docs/shuffle.md), reporting toward the "shuffle GB/s over ICI"
    north-star. Two tiers:

    1. **Reader fan-in micro** — one ShuffleReaderExec pulling a 256MB
       partition spread over several Flight servers (the multi-executor
       fan-in shape), over REAL loopback Flight: raw `shuffle_gb_s` plus
       the fetch-overlap counters, per knob configuration.
    2. **Query A/B under an emulated inter-host link** — q5/q18 on a
       2-executor standalone cluster with the local-file fast path off
       (every shuffle byte takes the wire path, as on separate hosts) and
       remote fetches paced to BENCH_SHUFFLE_NIC_GBPS using per-codec
       wire-byte ratios measured from real IPC serialization. Loopback
       has no wire, so WITHOUT pacing the knobs can only cost (threads +
       codec CPU, ~5-10% here) — the pacing restores the one property of
       the target deployment this box cannot exhibit: shuffle bytes take
       time proportional to their size. Sequential baseline
       (concurrency 0, codec none) vs pipelined (concurrency 4, lz4),
       eager OFF in both arms so the A/B isolates the fetch layer.

    An eager-vs-barriered q5 comparison (defaults otherwise, no pacing)
    is included as an informational third section.

    Env: BENCH_SHUFFLE_SF (default 0.05), BENCH_SHUFFLE_NIC_GBPS
    (default 0.002), BENCH_ITERS. Writes BENCH_SHUFFLE.json.

    Why 0.002 GB/s: the emulated rate is chosen to reproduce the TARGET
    deployment's shuffle-time-to-compute-time ratio, not a physical NIC.
    At TPC-H SF100 on the TPU target, a shuffle-heavy query moves
    O(100GB) against tens of seconds of device compute — transfer and
    compute are the same order. This CPU box computes q5/q18 at roughly
    1 MB of shuffled bytes per compute-second (~1000x more compute per
    byte than the device target), so an undistorted wire would make
    shuffle invisible here and ANY fetch-layer A/B meaningless. Scaling
    the emulated link by the same factor restores the target's ratio;
    the artifact labels the rate so nobody mistakes these for loopback
    numbers (the raw, unpaced numbers are reported alongside).
    """
    import shutil
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.ipc as paipc

    import ballista_tpu.client.flight as _fl
    from ballista_tpu.client.context import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.tpch import gen_all

    sf = float(os.environ.get("BENCH_SHUFFLE_SF", "0.05"))
    nic_gbps = float(os.environ.get("BENCH_SHUFFLE_NIC_GBPS", "0.002"))
    iters = max(2, ITERS)
    data = gen_all(scale=sf)

    # measured wire-bytes ratio per codec (real IPC serialization of a
    # representative lineitem batch — what the Flight stream would carry)
    sample = (
        data["lineitem"].slice(0, 1 << 16).combine_chunks().to_batches()[0]
    )

    def ser_len(codec):
        sink = pa.BufferOutputStream()
        opts = paipc.IpcWriteOptions(compression=codec) if codec else None
        kw = {"options": opts} if opts else {}
        with paipc.new_stream(sink, sample.schema, **kw) as w:
            w.write_batch(sample)
        return len(sink.getvalue())

    raw = ser_len(None)
    ratio = {
        "none": 1.0,
        "lz4": round(ser_len("lz4") / raw, 4),
        "zstd": round(ser_len("zstd") / raw, 4),
    }

    out = {
        "sf": sf,
        "emulated_nic_gbps": nic_gbps,
        "emulation_rationale": (
            "rate chosen so shuffle-transfer/compute matches the SF100 "
            "device target (~1000x more compute per byte on this CPU box "
            "than on the TPU; see run_shuffle_suite docstring) — the "
            "query_ab section measures the wire-bound regime the feature "
            "targets, reader_fanin the raw loopback data plane"
        ),
        "codec_wire_ratio": ratio,
        "iters": iters,
    }

    # -- tier 1: reader fan-in micro over real Flight (no pacing) ----------
    import dataclasses as _dc

    from ballista_tpu.executor.flight_service import start_flight_server
    from ballista_tpu.executor.reader import ShuffleReaderExec
    from ballista_tpu.scheduler_types import PartitionLocation
    from ballista_tpu.datatypes import DataType, Field, Schema as BSchema
    from ballista_tpu.exec.base import TaskContext

    tmp = tempfile.mkdtemp(prefix="bench-shuffle-")
    arrow2 = pa.schema([("k", pa.int64()), ("v", pa.float64())])
    rows_per, n_batches, n_servers, files_per = 1 << 16, 32, 4, 2
    rb = pa.record_batch(
        [pa.array(np.arange(rows_per, dtype=np.int64)),
         pa.array(np.random.rand(rows_per))],
        schema=arrow2,
    )
    locs, real, servers = [], {}, []
    orig_ticket = _fl.make_ticket
    try:
        for s in range(n_servers):
            sdir = os.path.join(tmp, f"exec-{s}")
            os.makedirs(sdir)
            svc, port, _t = start_flight_server("127.0.0.1", 0, sdir)
            servers.append(svc)
            for i in range(files_per):
                p = os.path.join(sdir, f"data-{i}.arrow")
                with paipc.new_file(p, arrow2) as w:
                    for _ in range(n_batches):
                        w.write_batch(rb)
                fake = f"/bench-remote/e{s}-{i}.arrow"
                real[fake] = p
                locs.append(
                    PartitionLocation(
                        "j", 1, 0, f"e{s}", "127.0.0.1", port, fake
                    )
                )
        total_bytes = sum(os.path.getsize(p) for p in real.values())
        _fl.make_ticket = lambda l, compression="", **kw: orig_ticket(
            _dc.replace(l, path=real.get(l.path, l.path)), compression, **kw
        )
        bschema = BSchema(
            [Field("k", DataType.INT64), Field("v", DataType.FLOAT64)]
        )

        def fanin(conc, codec, use_locs=None, fastpath=True):
            cfg = (
                BallistaConfig()
                .with_setting(
                    "ballista.tpu.shuffle_fetch_concurrency", str(conc)
                )
                .with_setting("ballista.tpu.shuffle_compression", codec)
                .with_setting(
                    "ballista.tpu.shuffle_local_fastpath",
                    "true" if fastpath else "false",
                )
            )
            best, counters = None, {}
            for _ in range(iters):
                plan = ShuffleReaderExec(
                    [list(use_locs if use_locs is not None else locs)],
                    bschema,
                )
                t0 = time.time()
                for b in plan.execute(0, TaskContext(config=cfg)):
                    np.asarray(b.valid)  # sync to host; drop
                dt = time.time() - t0
                if best is None or dt < best:
                    best, counters = dt, dict(plan.metrics.counters)
            return {
                "seconds": round(best, 4),
                "shuffle_gb_s": round(
                    counters.get("fetched_bytes", 0) / best / 1e9, 3
                ),
                "fetched_bytes": counters.get("fetched_bytes", 0),
                "fetched_batches": counters.get("fetched_batches", 0),
                "fetch_overlap_hits": counters.get("fetch_overlap_hits", 0),
                "fetch_overlap_misses": counters.get(
                    "fetch_overlap_misses", 0
                ),
                "push_fallbacks": counters.get("push_fallbacks", 0),
            }

        # push-stream mirror of the same 256MB: one in-memory registry
        # stream per location, fetched over DoExchange (fastpath off =
        # the Flight wire path; idempotent take -> re-iterable per iter)
        from ballista_tpu.executor.push import REGISTRY as _PUSH_REG
        from ballista_tpu.executor.push import stream_key as _skey

        push_locs = []
        for s in range(n_servers):
            sdir = os.path.join(tmp, f"exec-{s}")
            svc_port = locs[s * files_per].port
            for i in range(files_per):
                key = _skey("jpush", 1, s * files_per + i, 0)
                ppath = os.path.join(
                    sdir, "jpush", "1", "0",
                    f"push-{s * files_per + i}.arrow",
                )
                stream = _PUSH_REG.open(key, ppath, sdir, None)
                for _ in range(n_batches):
                    _PUSH_REG.append(stream, rb, 1 << 40)
                _PUSH_REG.seal(stream)
                push_locs.append(
                    PartitionLocation(
                        "jpush", 1, 0, f"e{s}", "127.0.0.1", svc_port,
                        ppath, push=True,
                        map_partition=s * files_per + i,
                    )
                )

        out["reader_fanin"] = {
            "total_mb": round(total_bytes / 1e6, 1),
            "servers": n_servers,
            "sequential_none": fanin(0, "none"),
            "overlapped_none": fanin(4, "none"),
            "overlapped_lz4": fanin(4, "lz4"),
            # the push plane over the same wire: no disk read server-side
            "overlapped_push_wire": fanin(
                4, "none", use_locs=push_locs, fastpath=False
            ),
            # colocated consumption straight from the registry (the
            # in-process zero-copy ceiling)
            "overlapped_push_colocated": fanin(
                4, "none", use_locs=push_locs, fastpath=True
            ),
        }
    finally:
        # an exception mid-tier must not leave the Flight servers running,
        # the make_ticket monkeypatch installed for the A/B tiers below,
        # ~256MB of generated shuffle files, or the push-registry mirror
        # of the same bytes behind
        _fl.make_ticket = orig_ticket
        from ballista_tpu.executor.push import REGISTRY as _PUSH_REG

        for s in range(n_servers):
            _PUSH_REG.drop_owner(os.path.join(tmp, f"exec-{s}"))
        for svc in servers:
            svc.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)

    # -- tier 2: q5/q18 A/B under the emulated link ------------------------
    nic_bps = nic_gbps * 1e9
    orig_fpb = _fl.fetch_partition_batches
    orig_push = _fl.fetch_push_batches

    def paced(loc, retries=None, backoff_ms=None, timeout_s=None,
              compression="", **kw):
        r = ratio.get(compression or "none", 1.0)
        for b in orig_fpb(loc, retries, backoff_ms, timeout_s, compression,
                          **kw):
            time.sleep(b.nbytes * r / nic_bps)
            yield b

    def paced_push(loc, retries=None, backoff_ms=None, timeout_s=None,
                   compression="", **kw):
        r = ratio.get(compression or "none", 1.0)
        for b in orig_push(loc, retries, backoff_ms, timeout_s, compression,
                           **kw):
            time.sleep(b.nbytes * r / nic_bps)
            yield b

    def query_arm(settings, qns, pace):
        _fl.fetch_partition_batches = paced if pace else orig_fpb
        _fl.fetch_push_batches = paced_push if pace else orig_push
        cfg = (
            BallistaConfig()
            .with_setting("ballista.shuffle.partitions", "4")
            .with_setting("ballista.tpu.shuffle_local_fastpath", "false")
        )
        for k, v in settings.items():
            cfg = cfg.with_setting(k, v)
        ctx = BallistaContext.standalone(cfg, n_executors=2)
        try:
            for name, t in data.items():
                ctx.register_table(name, t)
            res = {}
            for qn in qns:
                sql = (QDIR / f"{qn}.sql").read_text()
                ctx.sql(sql).collect()  # cold
                res[qn] = min(
                    (lambda t0=time.time(): (
                        ctx.sql(sql).collect(), time.time() - t0
                    )[1])()
                    for _ in range(iters)
                )
            return res
        finally:
            ctx.close()
            _fl.fetch_partition_batches = orig_fpb
            _fl.fetch_push_batches = orig_push

    seq = query_arm(
        {
            "ballista.tpu.shuffle_fetch_concurrency": "0",
            "ballista.tpu.shuffle_compression": "none",
            "ballista.tpu.eager_shuffle": "false",
        },
        ("q5", "q18"), pace=True,
    )
    pipe = query_arm(
        {
            "ballista.tpu.shuffle_fetch_concurrency": "4",
            "ballista.tpu.shuffle_compression": "lz4",
            "ballista.tpu.eager_shuffle": "false",
        },
        ("q5", "q18"), pace=True,
    )
    out["query_ab"] = {
        qn: {
            "sequential_s": round(seq[qn], 4),
            "pipelined_s": round(pipe[qn], 4),
            "speedup": round(seq[qn] / pipe[qn], 3),
        }
        for qn in seq
    }

    # -- informational: eager vs barriered, raw loopback -------------------
    barr = query_arm(
        {"ballista.tpu.eager_shuffle": "false"}, ("q5",), pace=False
    )
    eag = query_arm(
        {"ballista.tpu.eager_shuffle": "true"}, ("q5",), pace=False
    )
    out["eager_vs_barriered_raw"] = {
        "q5": {
            "barriered_s": round(barr["q5"], 4),
            "eager_s": round(eag["q5"], 4),
            "speedup": round(barr["q5"] / eag["q5"], 3),
        }
    }
    return out


def run_sf100_suite() -> dict:
    """BENCH_SF100=1: the flagship run toward the BASELINE north-star
    ("TPC-H SF100 queries/sec; shuffle GB/s over ICI"), ISSUE 13 /
    docs/shuffle.md.

    SF100 is ~100GB of tables — this CPU box does not hold it, so the
    artifact records the LARGEST SF the box sustains (``BENCH_SF100_SF``,
    default 1, ~1GB) with the target scale named, exactly like the
    emulated-link rationale in run_shuffle_suite: the RATIOS (push vs
    pull on the wire-bound path, achieved shuffle GB/s vs the data-plane
    ceiling) are the transferable measurements; the absolute
    queries/sec scales with the hardware.

    Sections:

    - **headline** — q1/q5/q18 on a 2-executor standalone cluster at the
      committed defaults (push data plane, auto codec, coalescing):
      warm-best seconds per query, aggregate queries/sec, and the
      shipped data-plane counters (fetched/pushed/spilled bytes).
    - **shuffle_gb_s** — achieved fan-in rate during the headline runs
      (fetched_bytes / elapsed on the shuffle-heavy queries) plus the
      raw loopback data-plane ceiling from the reader-fanin micro
      (BENCH_SHUFFLE.json, committed alongside).
    - **push_vs_pull** — the wire-bound A/B: local fast path OFF (every
      shuffle byte crosses the Flight wire, the separate-hosts shape),
      eager on in both arms, push on vs off. Push must win >= 1.1x: it
      deletes the file write + file read + per-request buffer copy from
      every wire byte's path.

    Env: BENCH_SF100_SF (default 1), BENCH_SF100_QUERIES (default
    q1,q5,q18), BENCH_ITERS. Writes BENCH_SF100.json.
    """
    from ballista_tpu.client.context import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.tpch import gen_all

    sf = float(os.environ.get("BENCH_SF100_SF", "1"))
    qnames = os.environ.get("BENCH_SF100_QUERIES", "q1,q5,q18").split(",")
    iters = max(2, ITERS)
    # the push window is sized to the workload's in-flight shuffle, the
    # way an operator sizes it to host RAM (q18 at SF1 keeps ~1.4GB of
    # map output in flight; the conservative 256MB library default kept
    # ~20%% of push bytes spilling mid-run, which measures the window,
    # not the data plane). Recorded in the artifact.
    window_mb = os.environ.get("BENCH_SF100_WINDOW_MB", "2048")
    data = gen_all(scale=sf)
    table_bytes = sum(t.nbytes for t in data.values())

    def run_arm(settings, qns):
        cfg = (
            BallistaConfig()
            .with_setting("ballista.shuffle.partitions", "4")
            .with_setting(
                "ballista.tpu.push_shuffle_window_mb", window_mb
            )
        )
        for k, v in settings.items():
            cfg = cfg.with_setting(k, v)
        ctx = BallistaContext.standalone(cfg, n_executors=2)
        try:
            for name, t in data.items():
                ctx.register_table(name, t)
            times = {}
            costs = {}
            for qn in qns:
                sql = (QDIR / f"{qn}.sql").read_text()
                ctx.sql(sql).collect()  # cold/compile pass
                best = None
                for _ in range(iters):
                    t0 = time.time()
                    ctx.sql(sql).collect()
                    dt = time.time() - t0
                    best = dt if best is None else min(best, dt)
                times[qn] = best
                # tracked cost fields: the last warm run's record from
                # the scheduler's persistent history
                costs[qn] = _cost_fields(
                    ctx._standalone_cluster.scheduler.history
                )
            counters = dict(
                ctx._standalone_cluster.scheduler.obs_task_counters
            )
            return times, counters, costs
        finally:
            ctx.close()

    out = {
        "target": "TPC-H SF100 queries/sec; shuffle GB/s over ICI",
        "sf": sf,
        "sf_rationale": (
            "largest SF this CPU box sustains in a 2-executor in-proc "
            "cluster (SF100 is ~100GB of tables); ratios are the "
            "transferable measurement, absolutes scale with hardware"
        ),
        "table_bytes": int(table_bytes),
        "queries": list(qnames),
        "iters": iters,
        "push_shuffle_window_mb": int(window_mb),
    }

    # -- headline: committed defaults (push plane on) ----------------------
    times, counters, costs = run_arm({}, qnames)
    total = sum(times.values())
    shuffle_keys = (
        "fetched_bytes", "pushed_bytes", "push_spill_bytes",
        "push_fallbacks", "output_rows",
    )
    out["headline"] = {
        "per_query_s": {q: round(s, 4) for q, s in times.items()},
        # cost fields per query (docs/observability.md): cpu/shuffle/
        # spill from the scheduler's persistent history records
        "per_query_cost": costs,
        "total_warm_s": round(total, 4),
        "queries_per_sec": round(len(times) / total, 4),
        "task_counters": {
            k: int(counters.get(k, 0)) for k in shuffle_keys
        },
    }
    # achieved shuffle rate while the headline queries ran: bytes the
    # readers actually pulled per second of query wall (iters+cold runs
    # all counted in the counters, so scale by runs)
    runs = iters + 1
    fetched = counters.get("fetched_bytes", 0) / runs
    out["shuffle_gb_s"] = {
        "achieved_during_headline": round(fetched / total / 1e9, 4),
        "definition": (
            "mean fetched shuffle bytes per second of warm query wall "
            "across the headline set; the raw data-plane ceiling is "
            "BENCH_SHUFFLE.json reader_fanin"
        ),
    }

    # -- push vs pull: the wire-bound DATA-PLANE A/B -----------------------
    # Produce + serve + consume one shuffle's worth of bytes through each
    # plane end-to-end, nothing else: pull writes Arrow IPC files and
    # serves them over Flight do_get; push commits the same batches into
    # the in-memory registry and serves them over do_exchange. This is
    # where the two planes actually differ — the query A/B below is
    # compute-diluted at this SF (the data plane is a few %% of q5/q18
    # wall, smaller than run-to-run noise on a shared CPU box) and is
    # reported as informational context.
    out["push_vs_pull_dataplane"] = _dataplane_ab(max(3, iters))

    # -- push vs pull under full queries (informational) -------------------
    wire_qs = [q for q in qnames if q != "q1"] or qnames
    wire = {"ballista.tpu.shuffle_local_fastpath": "false"}
    pull_times, pull_counters, _ = run_arm(
        {**wire, "ballista.tpu.push_shuffle": "false"}, wire_qs
    )
    push_times, push_counters, _ = run_arm(
        {**wire, "ballista.tpu.push_shuffle": "true"}, wire_qs
    )
    out["push_vs_pull_queries"] = {
        "regime": (
            "INFORMATIONAL: full q5/q18 wall with the local fast path "
            "off — the data plane is a few % of compute-bound query "
            "wall at this SF, below host noise; the wire-bound verdict "
            "is push_vs_pull_dataplane"
        ),
        "queries": {
            q: {
                "pull_s": round(pull_times[q], 4),
                "push_s": round(push_times[q], 4),
                "speedup": round(pull_times[q] / push_times[q], 3),
            }
            for q in wire_qs
        },
        "total_speedup": round(
            sum(pull_times.values()) / sum(push_times.values()), 3
        ),
        "push_counters": {
            k: int(push_counters.get(k, 0))
            for k in ("pushed_bytes", "push_spill_bytes", "push_fallbacks")
        },
        "pull_pushed_bytes": int(pull_counters.get("pushed_bytes", 0)),
    }
    return out


def _dataplane_ab(iters: int, total_mb: int = 512) -> dict:
    """Wire-bound push-vs-pull A/B: move ``total_mb`` of shuffle bytes
    producer -> wire -> consumer through each data plane END-TO-END.

    Both arms run the production-shaped path (coalesced ~8MB batches, 2
    serving executors x 4 streams, overlapped consumer with the local
    fast path off so every byte crosses the Flight wire):

    - **pull**: append batches to Arrow IPC files (the committed shuffle
      format), then a ShuffleReaderExec fan-in over ``do_get``.
    - **push**: commit the same batches into the in-memory push registry,
      then the same fan-in over ``do_exchange``.

    The difference is exactly what push deletes from every shuffle byte's
    life: the file write on the producer and the file open/map on the
    serve path."""
    import shutil
    import tempfile

    import numpy as np
    import pyarrow as pa

    from ballista_tpu.columnar.arrow_interop import (
        schema_from_arrow,  # noqa: F401 — parity with shuffle suite
    )
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.datatypes import DataType, Field, Schema as BSchema
    from ballista_tpu.exec.base import TaskContext
    from ballista_tpu.executor.flight_service import start_flight_server
    from ballista_tpu.executor.push import REGISTRY, stream_key
    from ballista_tpu.executor.reader import ShuffleReaderExec
    from ballista_tpu.executor.shuffle import _IpcAppender
    from ballista_tpu.scheduler_types import PartitionLocation

    n_servers, streams_per = 2, 4
    rows_per = 1 << 19  # ~8MB/batch at (int64, float64)
    n_streams = n_servers * streams_per
    batches_per = max(1, (total_mb << 20) // (rows_per * 16) // n_streams)
    rb = pa.record_batch(
        [pa.array(np.arange(rows_per, dtype=np.int64)),
         pa.array(np.random.rand(rows_per))],
        names=["k", "v"],
    )
    bschema = BSchema(
        [Field("k", DataType.INT64), Field("v", DataType.FLOAT64)]
    )
    cfg = (
        BallistaConfig()
        .with_setting("ballista.tpu.shuffle_fetch_concurrency", "4")
        .with_setting("ballista.tpu.shuffle_compression", "none")
        .with_setting("ballista.tpu.shuffle_local_fastpath", "false")
    )
    tmp = tempfile.mkdtemp(prefix="bench-dataplane-")
    servers = []
    try:
        ports = []
        for s in range(n_servers):
            sdir = os.path.join(tmp, f"exec-{s}")
            os.makedirs(sdir)
            svc, port, _t = start_flight_server("127.0.0.1", 0, sdir)
            servers.append(svc)
            ports.append(port)

        def consume(locs):
            plan = ShuffleReaderExec([list(locs)], bschema)
            for b in plan.execute(0, TaskContext(config=cfg)):
                np.asarray(b.valid)  # sync; drop
            return plan.metrics.counters.get("fetched_bytes", 0)

        def pull_round(r):
            t0 = time.time()
            locs = []
            for i in range(n_streams):
                sdir = os.path.join(tmp, f"exec-{i % n_servers}")
                path = os.path.join(sdir, "jdp", "1", "0",
                                    f"data-{r}-{i}.arrow")
                os.makedirs(os.path.dirname(path), exist_ok=True)
                w = _IpcAppender(path)
                for _ in range(batches_per):
                    w.write(rb)
                w.close()
                locs.append(PartitionLocation(
                    "jdp", 1, 0, f"e{i % n_servers}", "127.0.0.1",
                    ports[i % n_servers], path,
                ))
            nbytes = consume(locs)
            dt = time.time() - t0
            for loc in locs:
                os.remove(loc.path)
            return dt, nbytes

        def push_round(r):
            t0 = time.time()
            locs = []
            for i in range(n_streams):
                sdir = os.path.join(tmp, f"exec-{i % n_servers}")
                key = stream_key("jdp", 2, 1000 * r + i, 0)
                path = os.path.join(sdir, "jdp", "2", "0",
                                    f"push-{1000 * r + i}.arrow")
                st = REGISTRY.open(key, path, sdir, None)
                for _ in range(batches_per):
                    REGISTRY.append(st, rb, 1 << 40)
                REGISTRY.seal(st)
                locs.append(PartitionLocation(
                    "jdp", 2, 0, f"e{i % n_servers}", "127.0.0.1",
                    ports[i % n_servers], path, push=True,
                    map_partition=1000 * r + i,
                ))
            nbytes = consume(locs)
            dt = time.time() - t0
            for i in range(n_servers):
                REGISTRY.drop_owner(os.path.join(tmp, f"exec-{i}"))
            return dt, nbytes

        pull_best = push_best = None
        moved = 0
        for r in range(iters):
            dt, moved = pull_round(r)
            pull_best = dt if pull_best is None else min(pull_best, dt)
            dt, _ = push_round(r)
            push_best = dt if push_best is None else min(push_best, dt)
        return {
            "regime": (
                "produce + serve + consume one shuffle's bytes through "
                "each plane end-to-end over loopback Flight, local fast "
                "path off, coalesced ~8MB batches — the wire-bound "
                "data-plane cost per byte, undiluted by query compute"
            ),
            "moved_mb": round(moved / 1e6, 1),
            "pull_s": round(pull_best, 4),
            "push_s": round(push_best, 4),
            "pull_gb_s": round(moved / pull_best / 1e9, 3),
            "push_gb_s": round(moved / push_best / 1e9, 3),
            "speedup": round(pull_best / push_best, 3),
        }
    finally:
        for i in range(n_servers):
            REGISTRY.drop_owner(os.path.join(tmp, f"exec-{i}"))
        for svc in servers:
            svc.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)


def run_slo_suite() -> dict:
    """BENCH_SLO=1: the sustained-QPS SLO harness (ISSUE 12 /
    docs/observability.md). Drives a MIXED small/large TPC-H workload at
    a target arrival rate (open-loop: submissions fire on the clock, not
    on completions — the regime where queues actually form) against a
    2-executor standalone cluster, twice:

    - **steady** — no faults; the baseline distribution.
    - **chaos** — one executor killed (shuffle files deleted) mid-round
      while submissions keep arriving; lineage recovery + bounded
      retries must keep completing queries, and the cost shows up in the
      TAIL, which is exactly what this artifact exists to measure.

    Verdicts come from the scheduler's OWN metrics plane: after the
    rounds the harness scrapes ``/api/metrics`` (validated at the
    exposition-parser level), reads the ``ballista_job_latency_seconds``
    / ``ballista_queue_wait_seconds`` histograms per query class, and
    renders p50/p99 + queue-wait-p90 SLO verdicts against declared
    targets. Client-observed per-round latencies are reported alongside
    (they include result fetch; the server series starts at submission).
    ``ballista_spans_dropped_total`` must be 0 — the run itself proves
    the no-silent-caps rule held under load.

    Env: BENCH_SLO_SF (default 0.05), BENCH_SLO_QPS (default 2),
    BENCH_SLO_SECONDS (per round, default 25), BENCH_SLO_SMALL /
    BENCH_SLO_LARGE (query names, default q6 / q3),
    BENCH_SLO_TARGET_SMALL_P99_S / _LARGE_P99_S /
    BENCH_SLO_TARGET_QUEUE_P90_S. Writes BENCH_SLO.json.
    """
    import re
    import threading
    import urllib.request

    import numpy as np  # noqa: F401 — table gen path below

    from ballista_tpu.client.context import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.obs.hist import quantile_from_cumulative
    from ballista_tpu.scheduler.rest import (
        start_rest_server,
        stop_rest_server,
    )
    from ballista_tpu.tpch import gen_all

    sf = float(os.environ.get("BENCH_SLO_SF", "0.05"))
    qps = float(os.environ.get("BENCH_SLO_QPS", "2"))
    round_s = float(os.environ.get("BENCH_SLO_SECONDS", "25"))
    small_q = os.environ.get("BENCH_SLO_SMALL", "q6")
    large_q = os.environ.get("BENCH_SLO_LARGE", "q3")
    targets = {
        "small_p99_s": float(
            os.environ.get("BENCH_SLO_TARGET_SMALL_P99_S", "10")
        ),
        "large_p99_s": float(
            os.environ.get("BENCH_SLO_TARGET_LARGE_P99_S", "20")
        ),
        "queue_wait_p90_s": float(
            os.environ.get("BENCH_SLO_TARGET_QUEUE_P90_S", "2")
        ),
    }
    sqls = {
        "small": (QDIR / f"{small_q}.sql").read_text(),
        "large": (QDIR / f"{large_q}.sql").read_text(),
    }
    # arrival mix: 2 small : 1 large (interactive-heavy, like a real
    # serving tier)
    mix = ("small", "small", "large")

    cfg = (
        BallistaConfig()
        .with_setting("ballista.shuffle.partitions", "2")
        .with_setting("ballista.tpu.task_max_attempts", "4")
    )
    data = gen_all(scale=sf)
    ctx = BallistaContext.standalone(
        cfg,
        n_executors=2,
        # tight liveness so the chaos round's expiry/recovery fits the
        # round instead of a 60s default window
        executor_timeout_s=5.0,
        expiry_check_interval_s=1.0,
    )
    sched = ctx._standalone_cluster.scheduler
    httpd, rest_port = start_rest_server(sched, "127.0.0.1", 0)
    base = f"http://127.0.0.1:{rest_port}"
    out = {
        "sf": sf,
        "qps": qps,
        "round_seconds": round_s,
        "mix": {"small": small_q, "large": large_q, "arrivals": list(mix)},
        "targets": targets,
        "rounds": {},
    }
    try:
        for name, t in data.items():
            ctx.register_table(name, t)
        # warmup (compile + caches) and class-token discovery: the
        # scheduler labels series by the opaque qclass hash; map it back
        # to small/large via the warmup jobs
        class_token = {}
        for cls in ("small", "large"):
            ctx.sql(sqls[cls]).collect()
            with sched._lock:
                latest = max(
                    sched.jobs.values(), key=lambda j: j.submitted_s
                )
            class_token[cls] = latest.query_class
            ctx.sql(sqls[cls]).collect()  # one more fully-warm pass
        assert class_token["small"] != class_token["large"]
        out["query_class_tokens"] = class_token

        lock = threading.Lock()

        def run_round(chaos: bool) -> dict:
            results: list[tuple] = []  # (class, latency_s, ok)
            threads: list[tuple] = []  # (thread, class)

            def one(cls: str) -> None:
                t0 = time.time()
                ok = True
                try:
                    ctx.sql(sqls[cls]).collect()
                except Exception:  # noqa: BLE001 — the SLO artifact
                    # reports failures; it must not die on one
                    ok = False
                with lock:
                    results.append((cls, time.time() - t0, ok))

            killed = None
            t_start = time.time()
            i = 0
            while time.time() - t_start < round_s:
                due = t_start + i / qps
                now = time.time()
                if due > now:
                    time.sleep(due - now)
                cls = mix[i % len(mix)]
                th = threading.Thread(target=one, args=(cls,))
                th.start()
                threads.append((th, cls))
                i += 1
                if chaos and killed is None and cls == "large" and (
                    time.time() - t_start >= 0.4 * round_s
                ):
                    # mid-round executor kill, timed right after a LARGE
                    # query entered flight so its multi-stage work is
                    # guaranteed to straddle the crash: loops stop,
                    # Flight dies, shuffle files are deleted — the full
                    # crashed-machine shape while load keeps arriving.
                    # Recovery (expiry sweep -> task reset + lost-shuffle
                    # recompute) must surface in the TAIL, not in failed
                    # queries.
                    time.sleep(min(0.3, 1.0 / qps))
                    killed = ctx._standalone_cluster.kill_executor(
                        1, lose_shuffle=True
                    )
            for th, _cls in threads:
                th.join(timeout=300)
            # a thread still alive after the join deadline is a HUNG
            # query — exactly the recovery failure this harness exists
            # to catch; it must count as failed, not silently vanish
            # from both the completed and failed tallies
            hung = {"small": 0, "large": 0}
            for th, cls in threads:
                if th.is_alive():
                    hung[cls] += 1
            with lock:
                got = list(results)
            rnd: dict = {"submitted": i}
            for cls in ("small", "large"):
                lat_ok = [l for c, l, ok in got if c == cls and ok]
                failed = sum(
                    1 for c, _l, ok in got if c == cls and not ok
                ) + hung[cls]
                rnd[cls] = {
                    "completed": len(lat_ok),
                    "failed": failed,
                    "hung": hung[cls],
                    "client_latency_s": _percentiles(lat_ok),
                }
            if chaos:
                state = json.load(
                    urllib.request.urlopen(base + "/api/state")
                )
                rnd["killed_executor"] = killed
                rnd["retries_total"] = sum(
                    j["retries"] for j in state["jobs"]
                )
                rnd["recomputes_total"] = sum(
                    j["recomputes"] for j in state["jobs"]
                )
            return rnd

        out["rounds"]["steady"] = run_round(chaos=False)
        out["rounds"]["chaos"] = run_round(chaos=True)

        # -- scrape + verdicts (parser-level validated) --------------------
        from ballista_tpu.obs.prometheus import validate_exposition

        text = urllib.request.urlopen(base + "/api/metrics").read().decode()
        validate_exposition(text)
        out["scrape"] = _scrape_hist_quantiles(
            text, class_token, quantile_from_cumulative
        )
        dropped = sum(
            float(m.group(1))
            for m in re.finditer(
                r"^ballista_spans_dropped_total\{[^}]*\} ([0-9.e+-]+)$",
                text, re.M,
            )
        )
        out["spans_dropped_total"] = int(dropped)
        sc = out["scrape"]
        chaos_failed = (
            out["rounds"]["chaos"]["small"]["failed"]
            + out["rounds"]["chaos"]["large"]["failed"]
        )
        out["slo"] = {
            "small_p99_s": sc["job_latency"]["small"]["p99"],
            "small_p99_ok": (
                sc["job_latency"]["small"]["p99"] <= targets["small_p99_s"]
            ),
            "large_p99_s": sc["job_latency"]["large"]["p99"],
            "large_p99_ok": (
                sc["job_latency"]["large"]["p99"] <= targets["large_p99_s"]
            ),
            "queue_wait_p90_s": sc["queue_wait"]["all"]["p90"],
            "queue_wait_p90_ok": (
                sc["queue_wait"]["all"]["p90"]
                <= targets["queue_wait_p90_s"]
            ),
            "chaos_all_completed": chaos_failed == 0,
            "spans_dropped_ok": dropped == 0,
        }
        out["slo"]["pass"] = all(
            v for k, v in out["slo"].items() if k.endswith("_ok")
            or k == "chaos_all_completed"
        )
    finally:
        stop_rest_server(httpd)
        ctx.close()
    return out


def run_serve_suite() -> dict:
    """BENCH_SERVE=1: the serving fast-path suite (docs/serving.md).

    Three stacked optimizations, each measured on its own and then
    together under open-loop load against a 2-executor standalone
    cluster:

    - **result cache** — cold q6 (miss + async populate) vs repeated
      identical q6 (scheduler-served hits): the headline is
      ``cold_s / hit_median_s`` (acceptance: >= 10x).
    - **single-stage bypass** and **batched task grants** — a
      SATURATED closed-loop ablation: N worker threads submit the
      point query back-to-back for a fixed window (cache off, so every
      rep truly executes). Under saturation the executors poll hot and
      the scheduler event loop + grant round-trips are the bottleneck,
      which is exactly what the bypass and the batch remove; an idle
      closed loop would instead measure the client/executor poll
      intervals (~0.1 s each) and show parity. Three arms share the
      base (bypass on, batch 4): ``bypass_off`` and ``batch_1`` flip
      one knob each. Reported per arm: throughput, p50/p95 latency,
      scheduler events consumed.

    The sweep drives a mixed arrival stream (point queries on a
    1-partition serving session, q6 + q3 on the default session) at a
    target rate for a fixed window, across four arms: **full** (cache +
    bypass + batch), **cache_off**, **bypass_off**, **batch_1**. Each
    arm reports completed queries/sec, scheduler events/sec and
    dispatch-lag p99 (scraped from ``ballista_event_dispatch_lag_
    seconds`` on /api/metrics, parser-validated), and the cache hit
    ratio.

    Env: BENCH_SERVE_SF (default 0.05), BENCH_SERVE_QPS (default 6),
    BENCH_SERVE_SECONDS (per open-loop arm, default 20),
    BENCH_SERVE_HITS (default 15), BENCH_SERVE_SAT_SECONDS (per
    saturated arm, default 8), BENCH_SERVE_WORKERS (default 8).
    Writes BENCH_SERVE.json.
    """
    import re
    import statistics
    import threading
    import urllib.request

    from ballista_tpu.client.context import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.obs.hist import quantile_from_cumulative
    from ballista_tpu.scheduler.rest import (
        start_rest_server,
        stop_rest_server,
    )
    from ballista_tpu.tpch import gen_all

    sf = float(os.environ.get("BENCH_SERVE_SF", "0.05"))
    qps = float(os.environ.get("BENCH_SERVE_QPS", "6"))
    round_s = float(os.environ.get("BENCH_SERVE_SECONDS", "20"))
    n_hits = int(os.environ.get("BENCH_SERVE_HITS", "15"))
    sat_s = float(os.environ.get("BENCH_SERVE_SAT_SECONDS", "8"))
    n_workers = int(os.environ.get("BENCH_SERVE_WORKERS", "8"))
    data = gen_all(scale=sf)
    sql_q6 = (QDIR / "q6.sql").read_text()
    sql_q3 = (QDIR / "q3.sql").read_text()
    # the dashboard-shaped point query: single stage at 1 partition,
    # bypass-eligible, cache-hittable
    sql_point = (
        "select l_orderkey, l_partkey, l_extendedprice, l_discount "
        "from lineitem where l_orderkey = 1"
    )

    def base_cfg(**settings):
        cfg = BallistaConfig()
        for k, v in settings.items():
            cfg = cfg.with_setting(k.replace("__", "."), v)
        return cfg

    def boot(cfg):
        ctx = BallistaContext.standalone(cfg, n_executors=2)
        for name, t in data.items():
            ctx.register_table(name, t)
        return ctx

    out: dict = {
        "sf": sf,
        "qps": qps,
        "round_seconds": round_s,
        "point_sql": sql_point,
    }

    # -- (1) result cache: cold vs hit on q6 -------------------------------
    ctx = boot(base_cfg(
        **{"ballista.shuffle.partitions": "2",
           "ballista.tpu.result_cache_mb": "64"}
    ))
    sched = ctx._standalone_cluster.scheduler
    try:
        ctx.sql(sql_q6).collect()  # compile warmup — measure the engine,
        # not XLA; re-registering drops the warmup's cache entry so the
        # measured cold pass is a REAL miss + full execution
        ctx.register_table("lineitem", data["lineitem"].slice(0))
        t0 = time.time()
        cold_res = ctx.sql(sql_q6).collect()
        cold_s = time.time() - t0
        deadline = time.time() + 30
        while (time.time() < deadline
               and sched.result_cache.stats()["hits"] == 0):
            ctx.sql(sql_q6).collect()  # poll until population lands
            time.sleep(0.05)
        hit_lat = []
        for _ in range(n_hits):
            t0 = time.time()
            hit_res = ctx.sql(sql_q6).collect()
            hit_lat.append(time.time() - t0)
        assert hit_res.equals(cold_res), "cache hit not bit-exact"
        stats = sched.result_cache.stats()
        hit_med = statistics.median(hit_lat)
        out["result_cache"] = {
            "query": "q6",
            "cold_s": round(cold_s, 4),
            "hit_s": _percentiles(hit_lat),
            "speedup": round(cold_s / hit_med, 1),
            "cache_stats": stats,
            "hit_10x_ok": cold_s / hit_med >= 10.0,
        }
    finally:
        ctx.close()

    # -- (2) saturated closed-loop ablation: bypass + grant batching -------
    # n_workers threads submit a point lookup over a SMALL serving
    # table back-to-back: the executors never idle-sleep, so scheduler
    # event-loop hops and PollWork round-trips — what the bypass and
    # the batch remove — are the bottleneck being measured. (The
    # lineitem point query would scan sf*6M rows per rep and drown the
    # orchestration signal in compute; a serving-tier lookup table is
    # the workload these paths exist for.)
    import pyarrow as pa

    serve_tbl = pa.table({
        "a": list(range(20000)),
        "b": [float(i) for i in range(20000)],
    })
    sql_serve = "select a, b from serve_points where a < 100"

    def saturated(bypass: str, batch: str) -> dict:
        c = boot(base_cfg(
            **{"ballista.shuffle.partitions": "1",
               "ballista.tpu.single_stage_bypass": bypass,
               "ballista.tpu.task_grant_batch": batch}
        ))
        c.register_table("serve_points", serve_tbl)
        s = c._standalone_cluster.scheduler
        try:
            for _ in range(3):
                c.sql(sql_serve).collect()  # warmup
            ev0 = s._h_dispatch_lag.labels().snapshot()[2]
            lock = threading.Lock()
            lats: list = []
            stop_at = time.time() + sat_s
            t_start = time.time()

            def worker():
                while time.time() < stop_at:
                    t0 = time.time()
                    c.sql(sql_serve).collect()
                    with lock:
                        lats.append(time.time() - t0)

            ths = [
                threading.Thread(target=worker) for _ in range(n_workers)
            ]
            for th in ths:
                th.start()
            for th in ths:
                th.join()
            wall = time.time() - t_start
            ev = s._h_dispatch_lag.labels().snapshot()[2] - ev0
            bypassed = s.obs_bypass_total
            if bypass == "true":
                assert bypassed >= len(lats), (bypassed, len(lats))
            else:
                assert bypassed == 0, bypassed
            return {
                "n": len(lats),
                "queries_per_sec": round(len(lats) / wall, 1),
                "latency_s": _percentiles(lats),
                "sched_events": ev,
                "sched_events_per_query": round(ev / len(lats), 2),
            }
        finally:
            c.close()

    sat_base = saturated("true", "4")
    sat_no_bypass = saturated("false", "4")
    sat_batch_1 = saturated("true", "1")
    out["saturated"] = {
        "workers": n_workers,
        "window_s": sat_s,
        "sql": sql_serve,
        "base": sat_base,
        "bypass_off": sat_no_bypass,
        "batch_1": sat_batch_1,
        "bypass_speedup_p50": round(
            sat_no_bypass["latency_s"]["p50"]
            / sat_base["latency_s"]["p50"], 3
        ),
        "bypass_events_saved_per_query": round(
            sat_no_bypass["sched_events_per_query"]
            - sat_base["sched_events_per_query"], 2
        ),
        "batch_throughput_gain": round(
            sat_base["queries_per_sec"]
            / sat_batch_1["queries_per_sec"], 3
        ),
    }

    # -- (3) the open-loop mixed sweep, four arms --------------------------
    # arrival mix: dashboard-heavy — 3 point : 2 q6 : 1 q3
    mix = ("point", "point", "q6", "point", "q6", "large")
    sqls = {"point": sql_point, "q6": sql_q6, "large": sql_q3}

    def run_arm(cache_mb: str, bypass: str, batch: str) -> dict:
        cfg = base_cfg(
            **{"ballista.shuffle.partitions": "2",
               "ballista.tpu.result_cache_mb": cache_mb,
               "ballista.tpu.task_grant_batch": batch,
               "ballista.tpu.task_max_attempts": "4"}
        )
        c1 = boot(cfg)
        cluster = c1._standalone_cluster
        s = cluster.scheduler
        # the serving session: point queries plan to ONE partition
        # (bypass-eligible); its settings live in the cache key, so its
        # hits never collide with the default session's
        c2 = BallistaContext(
            f"localhost:{cluster.scheduler_port}",
            base_cfg(
                **{"ballista.shuffle.partitions": "1",
                   "ballista.tpu.single_stage_bypass": bypass}
            ),
        )
        for name, t in data.items():
            c2.register_table(name, t)
        httpd, rest_port = start_rest_server(s, "127.0.0.1", 0)
        try:
            # warmup both sessions (compile + classes)
            c1.sql(sql_q6).collect()
            c1.sql(sql_q3).collect()
            c2.sql(sql_point).collect()
            lock = threading.Lock()
            results: list = []
            threads: list = []

            def one(cls):
                submit_ctx = c2 if cls == "point" else c1
                t0 = time.time()
                ok = True
                try:
                    submit_ctx.sql(sqls[cls]).collect()
                except Exception:  # noqa: BLE001 — the artifact reports
                    ok = False  # failures; it must not die on one
                with lock:
                    results.append((cls, time.time() - t0, ok))

            ev_count_0 = s._h_dispatch_lag.labels().snapshot()[2]
            t_start = time.time()
            i = 0
            while time.time() - t_start < round_s:
                due = t_start + i / qps
                now = time.time()
                if due > now:
                    time.sleep(due - now)
                th = threading.Thread(
                    target=one, args=(mix[i % len(mix)],)
                )
                th.start()
                threads.append(th)
                i += 1
            for th in threads:
                th.join(timeout=300)
            wall = time.time() - t_start
            ev_count = (
                s._h_dispatch_lag.labels().snapshot()[2] - ev_count_0
            )
            with lock:
                got = list(results)
            completed = sum(1 for _c, _l, ok in got if ok)
            failed = sum(1 for _c, _l, ok in got if not ok)
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{rest_port}/api/metrics"
            ).read().decode()
            from ballista_tpu.obs.prometheus import validate_exposition

            validate_exposition(text)
            pairs = []
            for m in re.finditer(
                r"^ballista_event_dispatch_lag_seconds_bucket"
                r'\{le="([^"]+)"\} ([0-9.e+-]+)$',
                text, re.M,
            ):
                le = float("inf") if m.group(1) == "+Inf" else float(
                    m.group(1)
                )
                pairs.append((le, float(m.group(2))))
            cs = s.result_cache.stats()
            lookups = cs["hits"] + cs["misses"]
            arm = {
                "submitted": i,
                "completed": completed,
                "failed": failed,
                "queries_per_sec": round(completed / wall, 2),
                "sched_events_per_sec": round(ev_count / wall, 1),
                "dispatch_lag_p99_s": round(
                    quantile_from_cumulative(sorted(pairs), 0.99), 5
                ),
                "cache_hit_ratio": round(cs["hits"] / lookups, 3)
                if lookups else 0.0,
                "bypass_jobs": s.obs_bypass_total,
                "client_latency_s": _percentiles(
                    [l for _c, l, ok in got if ok]
                ),
            }
            return arm
        finally:
            stop_rest_server(httpd)
            c2.close()
            c1.close()

    out["sweep"] = {
        "mix": list(mix),
        "full": run_arm("64", "true", "4"),
        "cache_off": run_arm("0", "true", "4"),
        "bypass_off": run_arm("64", "false", "4"),
        "batch_1": run_arm("64", "true", "1"),
    }
    sw = out["sweep"]
    sat = out["saturated"]
    out["verdicts"] = {
        "cache_10x_ok": out["result_cache"]["hit_10x_ok"],
        # the bypass must cut saturated small-query latency (p50) AND
        # not lose throughput
        "bypass_faster_ok": (
            sat["bypass_speedup_p50"] > 1.0
            and sat["base"]["queries_per_sec"]
            >= sat["bypass_off"]["queries_per_sec"]
        ),
        # batched grants must raise sustained queries/sec vs batch=1
        "batch_throughput_ok": sat["batch_throughput_gain"] > 1.0,
        "cache_hit_ratio_full": sw["full"]["cache_hit_ratio"],
        "all_completed": all(
            sw[a]["failed"] == 0
            for a in ("full", "cache_off", "bypass_off", "batch_1")
        ),
    }
    out["verdicts"]["pass"] = (
        out["verdicts"]["cache_10x_ok"]
        and out["verdicts"]["bypass_faster_ok"]
        and out["verdicts"]["batch_throughput_ok"]
        and out["verdicts"]["all_completed"]
    )
    return out


def _aqe_tables(seed: int, n_fact: int, n_dim: int, n_keys: int) -> dict:
    """The seeded skewed/misestimated dataset (docs/aqe.md): Zipfian
    int keys (a hot-key groupby), string join keys (forcing the
    collect-mode join whose build side the query ORDER mis-places), a
    multi-hot-key int column (splittable skew — no single irreducible
    key), and two small dimensions (one string-keyed for the wrong-side
    build, one int-keyed for the broadcast rule)."""
    import numpy as np
    import pyarrow as pa

    rng = np.random.default_rng(seed)
    # Zipf ranks clipped to the key domain: rank 1 dominates (the
    # classic hot-key groupby), the tail is long
    ranks = rng.zipf(1.5, size=n_fact)
    key = np.minimum(ranks, n_keys).astype(np.int64)
    # moderate single-hot skew for the SPLIT arm: one key carries 15%
    # of the mass, the rest uniform — at 16 buckets the hot bucket trips
    # the skew ratio, and a split genuinely shrinks it (the hot key
    # keeps its 15%, but the uniform freight sharing its bucket spreads)
    hkey = np.where(
        rng.random(n_fact) < 0.15,
        np.int64(0),
        rng.integers(1, 1000, n_fact),
    ).astype(np.int64)
    skey = pa.array([f"s{int(k) % (n_dim * 20)}" for k in key])
    fact = pa.table(
        {
            "key": pa.array(key),
            "hkey": pa.array(hkey),
            "ikey": pa.array(
                rng.integers(0, n_dim, n_fact).astype(np.int64)
            ),
            "skey": skey,
            "v": pa.array(rng.uniform(0, 100, n_fact)),
        }
    )
    dim = pa.table(
        {
            "skey": pa.array([f"s{i}" for i in range(n_dim)]),
            "attr": pa.array((np.arange(n_dim) % 25).astype(np.int64)),
        }
    )
    dim2 = pa.table(
        {
            "ikey": pa.array(np.arange(n_dim, dtype=np.int64)),
            "iattr": pa.array((np.arange(n_dim) % 25).astype(np.int64)),
        }
    )
    hdim = pa.table(
        {
            "hkey": pa.array(np.arange(1000, dtype=np.int64)),
            "hattr": pa.array((np.arange(1000) % 25).astype(np.int64)),
        }
    )
    return {"fact": fact, "dim": dim, "dim2": dim2, "hdim": hdim}


# the AQE workload (docs/aqe.md): each query provokes one policy rule
_AQE_QUERIES = {
    # wrong-side build (dim JOIN fact puts the 2M-row fact on the build
    # side of the string-keyed collect join) + Zipf groupby -> FLIP (and
    # a coalesce of the tiny agg buckets rides along)
    "skewed_join": (
        "SELECT f.key, count(*) AS c, sum(f.v) AS s "
        "FROM dim d JOIN fact f ON d.skey = f.skey "
        "GROUP BY f.key ORDER BY s DESC LIMIT 100"
    ),
    # int-keyed partitioned join against a small dimension -> BROADCAST
    "broadcast_join": (
        "SELECT d2.iattr, count(*) AS c, sum(f.v) AS s "
        "FROM fact f JOIN dim2 d2 ON f.ikey = d2.ikey "
        "GROUP BY d2.iattr ORDER BY d2.iattr"
    ),
    # over-partitioned tiny aggregation -> COALESCE toward
    # aqe_target_partition_mb (ikey tiebreak keeps the LIMIT
    # deterministic across plans — counts tie)
    "tiny_parts": (
        "SELECT f.ikey, count(*) AS c, sum(f.v) AS s "
        "FROM fact f GROUP BY f.ikey ORDER BY c DESC, f.ikey LIMIT 20"
    ),
}

# the SPLIT arm runs in its own group: a hot-bucket ratio over the
# median is structurally unreachable at the default 4 buckets (a
# 4-sample median tracks the peak), so this group plans at 16 buckets
# with the broadcast rule silenced to isolate the split behavior
_AQE_SPLIT_QUERIES = {
    "skew_split": (
        "SELECT h.hattr, count(*) AS c, sum(f.v) AS s "
        "FROM fact f JOIN hdim h ON f.hkey = h.hkey "
        "GROUP BY h.hattr ORDER BY h.hattr"
    ),
}


def run_aqe_suite() -> dict:
    """BENCH_AQE=1: adaptive-vs-static on seeded skewed/misestimated
    data (docs/aqe.md). Two arms on identical 2-executor standalone
    clusters over the same seeded dataset:

    - **static** — ``ballista.tpu.aqe=false``: one warmup pass
      (compile caches), then ITERS measured warm passes.
    - **adaptive** — ``ballista.tpu.aqe=true`` with a FRESH strategy
      store: pass 1 observes and learns (its decisions are recorded as
      the learning trace), then ITERS measured warm passes that apply
      the learned strategies from submission — the fresh-process
      adaptive-planning story, measured.

    Per query the artifact records static/adaptive wall times, the
    speedup, per-outcome adaptation counts (applied/rejected/learned/
    reverted by op), and an arm-parity check (multiset-exact: float
    aggregates compare to 1e-9 relative — the certificate class).
    A TPC-H q1/q3/q5/q6/q18 warm guardrail (AQE on vs off) rides along:
    well-estimated plans must not regress.

    Env: BENCH_AQE_SEED (7), BENCH_AQE_FACT_ROWS (1.5M),
    BENCH_AQE_TPCH_SF (0.05), BENCH_ITERS. Writes BENCH_AQE.json.
    """
    import numpy as np  # noqa: F401 — dataset gen
    import pandas as pd

    from ballista_tpu.client.context import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.scheduler import aqe as aqe_mod
    from ballista_tpu.tpch import gen_all

    seed = int(os.environ.get("BENCH_AQE_SEED", "7"))
    n_fact = int(os.environ.get("BENCH_AQE_FACT_ROWS", "1500000"))
    tpch_sf = float(os.environ.get("BENCH_AQE_TPCH_SF", "0.05"))
    iters = max(2, ITERS)
    # hermetic strategy persistence: without this the suite would read
    # AND write the developer's real plan_hints.json — arms would
    # inherit each other's (and previous runs') learned strategies, and
    # bench-learned strategies for real TPC-H classes would silently
    # change later AQE-on runs in this environment
    import tempfile

    hint_dir = tempfile.mkdtemp(prefix="bench_aqe_hints_")
    prev_hint = os.environ.get("BALLISTA_TPU_HINT_CACHE")
    os.environ["BALLISTA_TPU_HINT_CACHE"] = hint_dir
    try:
        return _run_aqe_suite_hermetic(
            seed, n_fact, tpch_sf, iters, hint_dir
        )
    finally:
        # the env override must not outlive the suite even on an error
        # path — anything the process does afterward would otherwise
        # persist its real hints into the throwaway temp dir
        if prev_hint is None:
            os.environ.pop("BALLISTA_TPU_HINT_CACHE", None)
        else:
            os.environ["BALLISTA_TPU_HINT_CACHE"] = prev_hint


def _run_aqe_suite_hermetic(
    seed: int, n_fact: int, tpch_sf: float, iters: int, hint_dir: str
) -> dict:
    import tempfile

    import pandas as pd

    from ballista_tpu.client.context import BallistaContext
    from ballista_tpu.config import BallistaConfig
    from ballista_tpu.scheduler import aqe as aqe_mod
    from ballista_tpu.tpch import gen_all

    tables = _aqe_tables(seed, n_fact, n_dim=2000, n_keys=50000)

    def make_cfg(aqe_on: bool, extra: dict | None = None) -> BallistaConfig:
        cfg = (
            BallistaConfig()
            .with_setting("ballista.shuffle.partitions", "4")
            # shared with the skew monitor; 2 keeps the split rule
            # meaningful at moderate bucket counts (max > 4 x median is
            # nearly unreachable at small n)
            .with_setting("ballista.tpu.skew_ratio", "2")
            .with_setting(
                "ballista.tpu.aqe", "true" if aqe_on else "false"
            )
        )
        for k, v in (extra or {}).items():
            cfg = cfg.with_setting(k, v)
        for kv in os.environ.get("BENCH_CONFIG", "").split(","):
            if kv.strip():
                k, v = kv.split("=", 1)
                cfg = cfg.with_setting(k.strip(), v.strip())
        return cfg

    def outcome_counts(jobs) -> dict:
        agg: dict = {}
        for j in jobs:
            for d in j.aqe_decisions:
                agg.setdefault(d["outcome"], {})
                agg[d["outcome"]][d["op"]] = (
                    agg[d["outcome"]].get(d["op"], 0) + 1
                )
        return agg

    def run_arm(
        aqe_on: bool, queries: dict, data: dict, extra: dict | None = None
    ) -> dict:
        """One cluster, all queries: two warmup passes (for the adaptive
        arm: the learning pass, then the FIRST adapted pass — which pays
        the rewritten shapes' compiles exactly once), then measured warm
        passes. Both arms warm up twice so the comparison is steady
        state vs steady state. Returns per-query timings + decisions +
        results + the measured passes' retrace count (must be 0: an
        adapted query re-submitted must hit the closed compile
        vocabulary, never re-trace)."""
        from ballista_tpu.compilecache import metrics as cc_metrics

        # fresh persistence root per ARM (static ones too): the
        # in-memory store reset alone would reload a previous arm's
        # strategies from a shared hint file, and static arms must not
        # inherit an adaptive arm's executor plan hints either — every
        # arm starts from the same blank-hint state
        os.environ["BALLISTA_TPU_HINT_CACHE"] = tempfile.mkdtemp(
            dir=hint_dir
        )
        if aqe_on:
            aqe_mod.reset_store()
        ctx = BallistaContext.standalone(
            make_cfg(aqe_on, extra), n_executors=2
        )
        sched = ctx._standalone_cluster.scheduler
        # the adaptation tally below reads job.aqe_decisions after every
        # pass completed; the default obs-retention window (50 terminal
        # jobs) strips decision logs, which would silently zero the
        # counts at higher BENCH_ITERS
        sched.obs_retained_jobs = 100_000
        arm: dict = {}
        try:
            for name, t in data.items():
                ctx.register_table(name, t)
            for qn, sql in queries.items():
                jobs_of_q = []

                def one_pass():
                    t0 = time.perf_counter()
                    res = ctx.sql(sql).collect()
                    dt = time.perf_counter() - t0
                    with sched._lock:
                        job = max(
                            sched.jobs.values(),
                            key=lambda j: j.submitted_s,
                        )
                    jobs_of_q.append(job)
                    return dt, res
                learn_s, result = one_pass()
                # adaptive convergence: applying pass 1's strategies
                # re-shapes the plan, which can expose NEW signals
                # (different stages become observable) — keep passing
                # until the class's strategy set stops changing, so the
                # measured passes replay ONE stable adapted plan (and
                # its compiles happened in the convergence passes).
                # Static arms get the matching second warmup.
                adapted_first_s, result = one_pass()
                prev_specs = None
                for _ in range(5 if aqe_on else 0):
                    with sched._lock:
                        job = max(
                            sched.jobs.values(),
                            key=lambda j: j.submitted_s,
                        )
                    specs = aqe_mod.strategy_store().get(job.query_class)
                    if specs == prev_specs:
                        break
                    prev_specs = specs
                    _, result = one_pass()
                t_before = cc_metrics.snapshot().get("traces", 0)
                times = []
                for _ in range(iters):
                    dt, result = one_pass()
                    times.append(dt)
                retraces = cc_metrics.snapshot().get("traces", 0) - t_before
                arm[qn] = {
                    "first_pass_s": round(learn_s, 4),
                    "adapted_first_pass_s": round(adapted_first_s, 4),
                    "warm_s": round(sum(times) / len(times), 4),
                    "warm_best_s": round(min(times), 4),
                    "warm_retraces": int(retraces),
                    "adaptations": outcome_counts(jobs_of_q),
                    "rewrites_last_run": jobs_of_q[-1].total_rewrites,
                    "skew_flags_last_run": len(jobs_of_q[-1].skew_flags),
                    "_result": result.to_pandas(),
                }
        finally:
            ctx.close()
        return arm

    out: dict = {
        "seed": seed,
        "fact_rows": n_fact,
        "iters": iters,
        "queries": {},
    }
    split_extra = {
        "ballista.shuffle.partitions": "16",
        "ballista.tpu.aqe_broadcast_threshold_mb": "0",
    }
    static = run_arm(False, _AQE_QUERIES, tables)
    static.update(run_arm(False, _AQE_SPLIT_QUERIES, tables, split_extra))
    adaptive = run_arm(True, _AQE_QUERIES, tables)
    adaptive.update(run_arm(True, _AQE_SPLIT_QUERIES, tables, split_extra))
    for qn in list(_AQE_QUERIES) + list(_AQE_SPLIT_QUERIES):
        s, a = static[qn], adaptive[qn]
        sr, ar = s.pop("_result"), a.pop("_result")
        cols = list(sr.columns)
        sr = sr.sort_values(cols).reset_index(drop=True)
        ar = ar.sort_values(cols).reset_index(drop=True)
        parity = True
        try:
            pd.testing.assert_frame_equal(
                sr, ar, check_exact=False, rtol=1e-9
            )
        except AssertionError:
            parity = False
        out["queries"][qn] = {
            "static_warm_s": s["warm_s"],
            "adaptive_warm_s": a["warm_s"],
            "speedup": round(s["warm_s"] / max(a["warm_s"], 1e-9), 3),
            "static_best_s": s["warm_best_s"],
            "adaptive_best_s": a["warm_best_s"],
            "speedup_best": round(
                s["warm_best_s"] / max(a["warm_best_s"], 1e-9), 3
            ),
            "learning_pass_s": a["first_pass_s"],
            "adapted_first_pass_s": a["adapted_first_pass_s"],
            "adaptations": a["adaptations"],
            "rewrites_per_adapted_run": a["rewrites_last_run"],
            "skew_flags": a["skew_flags_last_run"],
            "warm_retraces": a["warm_retraces"],
            "parity_multiset_exact": parity,
        }
    out["skewed_join_speedup_ok"] = (
        out["queries"]["skewed_join"]["speedup"] >= 1.2
    )

    # -- TPC-H guardrail: well-estimated plans must not regress --------------
    tpch = gen_all(scale=tpch_sf)
    tq = {
        qn: (QDIR / f"{qn}.sql").read_text()
        for qn in ("q1", "q3", "q5", "q6", "q18")
    }
    g_static = run_arm(False, tq, tpch)
    g_adapt = run_arm(True, tq, tpch)
    guard: dict = {}
    for qn in tq:
        s, a = g_static[qn], g_adapt[qn]
        s.pop("_result"), a.pop("_result")
        guard[qn] = {
            "aqe_off_warm_s": s["warm_s"],
            "aqe_on_warm_s": a["warm_s"],
            "ratio_on_over_off": round(
                a["warm_s"] / max(s["warm_s"], 1e-9), 3
            ),
            "adaptations": a["adaptations"],
            # closed-vocabulary proof: repeat submissions of the
            # adapted query must not re-trace
            "warm_retraces": a["warm_retraces"],
        }
    out["tpch_guardrail"] = {
        "sf": tpch_sf,
        "queries": guard,
        # pass = AQE on is never a real regression (>15% slower) on any
        # tracked well-estimated query; faster is fine (tiny-SF buckets
        # legitimately coalesce)
        "no_regression": all(
            g["ratio_on_over_off"] <= 1.15 for g in guard.values()
        ),
    }
    return out


def _scrape_hist_quantiles(text: str, class_token: dict, qfn) -> dict:
    """p50/p90/p99 per query class from scraped ``_bucket`` samples —
    computed with the same interpolation the in-process histograms use."""
    import math
    import re

    bucket_re = re.compile(
        r"^(ballista_[a-z_]+_seconds)_bucket\{([^}]*)\} ([0-9.e+-]+|\+?Inf)$",
        re.M,
    )
    series: dict = {}
    for m in bucket_re.finditer(text):
        name, labels, value = m.group(1), m.group(2), float(m.group(3))
        lab = dict(
            kv.split("=", 1) for kv in labels.split(",") if "=" in kv
        )
        le_raw = lab.get("le", "").strip('"')
        le = math.inf if le_raw == "+Inf" else float(le_raw)
        cls = lab.get("class", "").strip('"')
        series.setdefault((name, cls), []).append((le, value))
    token_class = {v: k for k, v in class_token.items()}
    out: dict = {"job_latency": {}, "queue_wait": {}}
    for (name, cls), pairs in sorted(series.items()):
        if name == "ballista_job_latency_seconds":
            label = token_class.get(cls)
            if label:
                out["job_latency"][label] = {
                    "p50": round(qfn(pairs, 0.50), 4),
                    "p99": round(qfn(pairs, 0.99), 4),
                    "count": int(max(v for _le, v in pairs)),
                }
        elif name == "ballista_queue_wait_seconds":
            merged = out["queue_wait"].setdefault("_pairs", {})
            for le, v in pairs:
                merged[le] = merged.get(le, 0.0) + v
    merged = out["queue_wait"].pop("_pairs", {})
    pairs = sorted(merged.items())
    out["queue_wait"]["all"] = {
        "p50": round(qfn(pairs, 0.50), 4),
        "p90": round(qfn(pairs, 0.90), 4),
        "p99": round(qfn(pairs, 0.99), 4),
        "count": int(max((v for _le, v in pairs), default=0)),
    }
    return out


def run_compile_suite() -> dict:
    """BENCH_COMPILE=1: the cold-start suite (ISSUE 7 /
    docs/compile_cache.md). Measures, per tracked query and for the whole
    subset, what a FRESH PROCESS pays before its first result with the
    compile-latency subsystem on (prewarm + persistent XLA cache + shared
    trace cache), against three baselines:

    - **cold_first** — empty persistent cache, prewarm on: the first-ever
      run on a machine (every XLA compile real). Queries share one cache
      dir, in order, so later queries already benefit from overlapping
      programs — exactly as a fresh deployment would.
    - **cold_warm_cache** — same cache kept, fresh process per the whole
      subset: cold_s is trace + persistent-cache retrieval only (the
      production executor-restart story), warm_s the in-process steady
      state. The headline acceptance ratio is
      ``sum(cold_s) / sum(warm_best_s)``.
    - **vocabulary** — distinct-signature counts: fresh-process subset
      trace count under the default capacity ladder vs a coarser
      ``2048:4`` ladder (shape canonicalization shrinking the compiled
      vocabulary), and first-pass vs repeat-pass trace counts at git HEAD
      vs this tree (the shared trace cache killing repeat-submission
      re-traces).

    Env: BENCH_SF (default 1), BENCH_QUERIES, BENCH_ITERS,
    BENCH_COMPILE_TIMEOUT (default 1800 per child),
    BENCH_COMPILE_SKIP_HEAD=1. Writes BENCH_COMPILE.json.
    """
    import shutil

    cache_root = HERE / ".bench_compile_cache"
    timeout = int(os.environ.get("BENCH_COMPILE_TIMEOUT", 1800))

    base_env = dict(os.environ)
    base_env["PYTHONPATH"] = os.pathsep.join(
        [str(HERE)]
        + ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH") else [])
    )
    base_env.pop("BENCH_COMPILE", None)
    # parquet tables: generated once, shared by every child (registration
    # and file generation are outside the query timings)
    base_env["BENCH_PARQUET"] = "1"

    def child(cache_dir, iters, extra_cfg="", queries=QUERIES, label=""):
        env = dict(base_env)
        env["BALLISTA_TPU_JAX_CACHE"] = str(cache_dir)
        cfg = "ballista.tpu.prewarm=on"
        if os.environ.get("BENCH_CONFIG"):
            cfg = os.environ["BENCH_CONFIG"] + "," + cfg
        if extra_cfg:
            cfg += "," + extra_cfg
        env["BENCH_CONFIG"] = cfg
        env["BENCH_QUERIES"] = ",".join(queries)
        return _run_child(env, iters, timeout, label or "compile")

    subset_dir = cache_root / "subset"
    shutil.rmtree(subset_dir, ignore_errors=True)
    subset_dir.mkdir(parents=True, exist_ok=True)

    out = {
        "sf": SF,
        "queries": list(QUERIES),
        "iters": ITERS,
        "head_reference": {
            # the motivating numbers (BENCH_r04, tunnelled TPU, SF=1):
            # compile latency dominated cold runs before this subsystem
            "q18_cold_s": 42.1342,
            "q18_warm_s": 1.6501,
            "ratio": 25.5,
        },
    }

    # -- phase A: first-ever run, empty cache --------------------------------
    first = child(subset_dir, 1, label="compile cold-first")
    if first is None:
        raise SystemExit(1)
    out["backend"] = first["backend"]
    out["cold_first"] = {
        qn: {
            "cold_s": q["cold_s"],
            "n_signatures": q.get("n_signatures"),
            "compile_seconds": q.get("compile_seconds"),
        }
        for qn, q in first["queries"].items()
    }
    out["cold_first"]["total_cold_s"] = round(
        sum(q["cold_s"] for q in first["queries"].values()), 4
    )
    out["cold_first"]["persistent_cache_misses"] = first.get(
        "persistent_cache_misses"
    )

    # -- phase B: fresh process, kept cache (executor restart) ---------------
    warm = child(subset_dir, ITERS, label="compile warm-cache")
    if warm is None:
        raise SystemExit(1)
    qsec = {}
    for qn, q in warm["queries"].items():
        qsec[qn] = {
            "cold_s": q["cold_s"],
            "warm_s": q["warm_s"],
            "warm_best_s": q["warm_best_s"],
            "ratio": round(q["cold_s"] / max(q["warm_best_s"], 1e-9), 3),
            "n_signatures": q.get("n_signatures"),
            "compile_seconds": q.get("compile_seconds"),
            "warm_retraces": q.get("warm_retraces"),
            # tracked cost fields (docs/observability.md)
            "cpu_seconds": q.get("cpu_seconds"),
            "shuffle_bytes": q.get("shuffle_bytes"),
            "spill_bytes": q.get("spill_bytes"),
        }
    cold_total = round(
        sum(q["cold_s"] for q in warm["queries"].values()), 4
    )
    warm_total = round(
        sum(q["warm_best_s"] for q in warm["queries"].values()), 4
    )
    out["cold_warm_cache"] = qsec
    out["aggregate"] = {
        "cold_total_s": cold_total,
        "warm_total_s": warm_total,
        "ratio": round(cold_total / max(warm_total, 1e-9), 3),
        "persistent_cache_hits": warm.get("persistent_cache_hits"),
        "persistent_cache_misses": warm.get("persistent_cache_misses"),
    }

    # -- vocabulary: canonicalization + trace-cache A/Bs ---------------------
    # per-query sums (NOT the child's process total, which also counts the
    # prewarm pass's own traces — reported separately)
    n_sub = sum(
        q.get("n_signatures", 0) for q in first["queries"].values()
    )
    vocab = {
        "n_signatures_subset": n_sub,
        # process total minus per-query cold sums: prewarm plus table
        # registration/upload plus phase-A warm-pass traces — everything
        # the child traced OUTSIDE the tracked cold passes
        "non_query_traces": max(0, first.get("n_signatures", 0) - n_sub),
        "warm_retraces_subset": sum(
            q.get("warm_retraces", 0) for q in warm["queries"].values()
        ),
    }
    coarse_dir = cache_root / "coarse"
    shutil.rmtree(coarse_dir, ignore_errors=True)
    coarse_dir.mkdir(parents=True, exist_ok=True)
    coarse = child(
        coarse_dir, 1,
        extra_cfg="ballista.tpu.capacity_buckets=2048:4",
        label="compile coarse-ladder",
    )
    if coarse is not None:
        vocab["n_signatures_subset_coarse_ladder"] = sum(
            q.get("n_signatures", 0)
            for q in coarse["queries"].values()
        )
        vocab["coarse_ladder"] = "2048:4"

    # HEAD comparison: the same subset through the PR-base tree, counting
    # first-pass and repeat-pass traces — repeat-pass is what the shared
    # trace cache eliminates (fresh plan instances used to re-trace every
    # instance-held jit on every submission)
    if not os.environ.get("BENCH_COMPILE_SKIP_HEAD"):
        head = _head_trace_counts(base_env, subset_dir, timeout)
        if head is not None:
            vocab["head"] = head
            # per-query subset sum, NOT the child's process total: head
            # runs without prewarm, so including the prewarm pass's own
            # traces here would misread as a vocabulary regression
            vocab["tree"] = {
                "first_pass_traces": n_sub,
                "repeat_pass_traces": vocab["warm_retraces_subset"],
            }
    out["vocabulary"] = vocab
    return out


_HEAD_TRACE_SCRIPT = r"""
import json, os, sys, time, pathlib
import jax.monitoring
counts = {"traces": 0}
def _on(event, duration, **kw):
    if event == "/jax/core/compile/jaxpr_trace_duration":
        counts["traces"] += 1
jax.monitoring.register_event_duration_secs_listener(_on)
from ballista_tpu.exec.context import TpuContext
from ballista_tpu.config import BallistaConfig
here = pathlib.Path(os.environ["BENCH_HERE"])
qdir = here / "benchmarks" / "queries"
pdir = pathlib.Path(os.environ["BENCH_PARQUET_DIR_ABS"])
cfg = BallistaConfig().with_setting("ballista.shuffle.partitions", "1")
ctx = TpuContext(cfg)
from ballista_tpu.tpch import all_schemas
for name in all_schemas():
    ctx.register_parquet(name, str(pdir / f"{name}.parquet"))
queries = os.environ["BENCH_QUERIES"].split(",")
first = repeat = 0
for qn in queries:
    sql = (qdir / f"{qn}.sql").read_text()
    b = counts["traces"]; ctx.sql(sql).collect()
    first += counts["traces"] - b
    b = counts["traces"]; ctx.sql(sql).collect()
    repeat += counts["traces"] - b
print(json.dumps({"first_pass_traces": first,
                  "repeat_pass_traces": repeat}))
"""


def _head_trace_counts(base_env, cache_dir, timeout):
    """Trace counts for the subset at git HEAD (the PR base), measured in
    a worktree inside the repo — best-effort: None on any failure."""
    wt = HERE / ".bench_head_worktree"
    try:
        # a killed prior run can leave the path registered (its `finally`
        # never ran), which makes a plain `worktree add` fail — clear any
        # stale registration first
        subprocess.run(
            ["git", "-C", str(HERE), "worktree", "remove", "--force",
             str(wt)],
            capture_output=True, timeout=120,
        )
        subprocess.run(
            ["git", "-C", str(HERE), "worktree", "prune"],
            capture_output=True, timeout=120,
        )
        subprocess.run(
            ["git", "-C", str(HERE), "worktree", "add", "--force",
             str(wt), "HEAD"],
            capture_output=True, text=True, timeout=120, check=True,
        )
        env = dict(base_env)
        env["PYTHONPATH"] = os.pathsep.join(
            [str(wt)]
            + ([base_env["PYTHONPATH"]]
               if base_env.get("PYTHONPATH") else [])
        )
        env["BALLISTA_TPU_JAX_CACHE"] = str(cache_dir)
        env["BENCH_QUERIES"] = ",".join(QUERIES)
        env["BENCH_HERE"] = str(HERE)
        env["BENCH_PARQUET_DIR_ABS"] = str(
            pathlib.Path(
                os.environ.get("BENCH_PARQUET_DIR", HERE / "bench_data")
            ) / f"sf{SF:g}"
        )
        proc = subprocess.run(
            [sys.executable, "-c", _HEAD_TRACE_SCRIPT],
            env=env, capture_output=True, text=True, timeout=timeout,
            cwd=str(wt),
        )
        if proc.returncode != 0:
            print(
                f"head trace measurement failed:\n{proc.stderr[-2000:]}",
                file=sys.stderr,
            )
            return None
        for line in reversed(proc.stdout.strip().splitlines()):
            if line.startswith("{"):
                return json.loads(line)
        return None
    except Exception as e:  # noqa: BLE001 — strictly best-effort
        print(f"head trace measurement skipped: {e}", file=sys.stderr)
        return None
    finally:
        subprocess.run(
            ["git", "-C", str(HERE), "worktree", "remove", "--force",
             str(wt)],
            capture_output=True, timeout=120,
        )


def _run_child(env: dict, iters: int, timeout: int, label: str):
    """Run one suite in a child process, returning its parsed result dict
    or None. Shared by the device and CPU phases; captures partial output
    on timeout (the wedged-TPU diagnosis) and tolerates trailing non-JSON
    stdout noise from library atexit handlers."""
    env = dict(env)
    env.update(
        {
            "BENCH_CHILD": "1",
            "BENCH_SF": str(SF),
            "BENCH_ITERS": str(iters),
        }
    )
    # callers (run_compile_suite's child()) may pre-set a query subset;
    # only default it so that actually takes effect
    env.setdefault("BENCH_QUERIES", ",".join(QUERIES))
    try:
        proc = subprocess.run(
            [sys.executable, str(HERE / "bench.py")],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        tail = e.stderr or ""
        if isinstance(tail, bytes):
            tail = tail.decode(errors="replace")
        print(
            f"{label} suite exceeded {timeout}s (wedged TPU runtime?); "
            f"partial stderr:\n{tail[-3000:]}",
            file=sys.stderr,
        )
        return None
    if proc.returncode != 0:
        print(f"{label} suite failed:\n{proc.stderr[-4000:]}", file=sys.stderr)
        return None
    for line in reversed(proc.stdout.strip().splitlines() or [""]):
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    print(f"{label} suite produced no JSON:\n{proc.stdout[-2000:]}",
          file=sys.stderr)
    return None


def main() -> None:
    if os.environ.get("BENCH_AQE"):
        # adaptive-vs-static on seeded skewed/misestimated data
        # (docs/aqe.md): in-process standalone clusters, one arm each
        sys.path.insert(0, str(HERE))
        res = run_aqe_suite()
        (HERE / "BENCH_AQE.json").write_text(json.dumps(res, indent=2))
        print(json.dumps(res, indent=2), file=sys.stderr)
        print(json.dumps({
            "metric": f"aqe_skewed_join_speedup_seed{res['seed']}",
            "value": res["queries"]["skewed_join"]["speedup"],
            "unit": "x",
            "skewed_join_speedup_ok": res["skewed_join_speedup_ok"],
            "tpch_no_regression": res["tpch_guardrail"]["no_regression"],
            "adaptations": res["queries"]["skewed_join"]["adaptations"],
        }))
        return
    if os.environ.get("BENCH_SERVE"):
        # serving fast-path suite (docs/serving.md): result cache,
        # single-stage bypass, batched grants — each alone + the
        # open-loop mixed sweep with ablation arms
        sys.path.insert(0, str(HERE))
        res = run_serve_suite()
        (HERE / "BENCH_SERVE.json").write_text(json.dumps(res, indent=2))
        print(json.dumps(res, indent=2), file=sys.stderr)
        print(json.dumps({
            "metric": f"serve_sf{res['sf']:g}_qps{res['qps']:g}",
            "value": res["result_cache"]["speedup"],
            "unit": "cache_hit_speedup_x",
            "pass": res["verdicts"]["pass"],
            "bypass_speedup_p50": res["saturated"]["bypass_speedup_p50"],
            "sat_qps": res["saturated"]["base"]["queries_per_sec"],
            "sat_batch1_qps": res["saturated"]["batch_1"][
                "queries_per_sec"
            ],
            "full_qps": res["sweep"]["full"]["queries_per_sec"],
            "dispatch_lag_p99_s": res["sweep"]["full"][
                "dispatch_lag_p99_s"
            ],
            "cache_hit_ratio": res["verdicts"]["cache_hit_ratio_full"],
        }))
        return
    if os.environ.get("BENCH_SLO"):
        # sustained-QPS SLO harness (docs/observability.md): in-process
        # standalone cluster + open-loop load + /api/metrics verdicts
        sys.path.insert(0, str(HERE))
        res = run_slo_suite()
        (HERE / "BENCH_SLO.json").write_text(json.dumps(res, indent=2))
        print(json.dumps(res, indent=2), file=sys.stderr)
        print(json.dumps({
            "metric": (
                f"slo_sf{res['sf']:g}_qps{res['qps']:g}_"
                f"{res['mix']['small']}_{res['mix']['large']}"
            ),
            "value": res["slo"]["large_p99_s"],
            "unit": "p99_seconds",
            "slo_pass": res["slo"]["pass"],
            "queue_wait_p90_s": res["slo"]["queue_wait_p90_s"],
            "spans_dropped_total": res["spans_dropped_total"],
        }))
        return
    if os.environ.get("BENCH_SF100"):
        # the flagship artifact toward the SF100 north-star: headline
        # queries/sec + achieved shuffle GB/s + push-vs-pull wire A/B
        sys.path.insert(0, str(HERE))
        res = run_sf100_suite()
        (HERE / "BENCH_SF100.json").write_text(json.dumps(res, indent=2))
        print(json.dumps(res, indent=2), file=sys.stderr)
        print(json.dumps({
            "metric": f"tpch_sf{res['sf']:g}_flagship_queries_per_sec",
            "value": res["headline"]["queries_per_sec"],
            "unit": "queries/s",
            "push_vs_pull_dataplane_speedup": res["push_vs_pull_dataplane"][
                "speedup"
            ],
            "shuffle_gb_s_achieved": res["shuffle_gb_s"][
                "achieved_during_headline"
            ],
        }))
        return
    if os.environ.get("BENCH_SHUFFLE"):
        # shuffle data-plane suite: self-contained, host-path dominated —
        # runs in-process and writes its own artifact
        sys.path.insert(0, str(HERE))
        res = run_shuffle_suite()
        (HERE / "BENCH_SHUFFLE.json").write_text(json.dumps(res, indent=2))
        print(json.dumps(res, indent=2), file=sys.stderr)
        best_q = max(
            res["query_ab"], key=lambda q: res["query_ab"][q]["speedup"]
        )
        print(json.dumps({
            "metric": (
                f"shuffle_pipeline_speedup_{best_q}_"
                f"nic{res['emulated_nic_gbps']:g}gbps"
            ),
            "value": res["query_ab"][best_q]["speedup"],
            "unit": "x",
            "shuffle_gb_s_fanin": res["reader_fanin"]["overlapped_none"][
                "shuffle_gb_s"
            ],
        }))
        return
    if os.environ.get("BENCH_CHILD"):
        print(json.dumps(run_suite()))
        return
    if os.environ.get("BENCH_COMPILE"):
        # cold-start suite: subprocess-per-phase (cold = a fresh process
        # by definition), writes its own artifact
        res = run_compile_suite()
        (HERE / "BENCH_COMPILE.json").write_text(json.dumps(res, indent=2))
        print(json.dumps(res, indent=2), file=sys.stderr)
        print(json.dumps({
            "metric": (
                f"tpch_sf{res['sf']:g}_cold_over_warm_"
                + "_".join(res["queries"]) + f"_{res['backend']}"
            ),
            "value": res["aggregate"]["ratio"],
            "unit": "x",
            "cold_total_s": res["aggregate"]["cold_total_s"],
            "warm_total_s": res["aggregate"]["warm_total_s"],
            "n_signatures": res["vocabulary"]["n_signatures_subset"],
        }))
        return

    # The device suite runs in a SUBPROCESS with a hard timeout: a wedged
    # TPU tunnel (observed: any device op hanging indefinitely) must fail
    # this harness loudly instead of hanging the driver forever.
    device_env = dict(os.environ)
    # PREPEND to PYTHONPATH: clobbering it would break the axon platform
    # plugin the site config registers from it
    device_env["PYTHONPATH"] = os.pathsep.join(
        [str(HERE)]
        + ([os.environ["PYTHONPATH"]] if os.environ.get("PYTHONPATH") else [])
    )
    # Parallel compile prewarm: one subprocess per query, concurrently.
    # Best-effort — failures fall through to the (slower, serial) cold
    # pass of the measured suite. Gated to modest SF: each child
    # regenerates the dataset in memory. A sentinel keyed by (code
    # revision, SF, query set) skips the whole phase on hot-cache
    # re-runs, where it could do no useful work.
    sentinel = None
    cache_dir = os.environ.get(
        "BALLISTA_TPU_JAX_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "ballista_tpu_jax"),
    )
    if cache_dir != "off":
        rev = ""
        try:
            rev = subprocess.run(
                ["git", "-C", str(HERE), "rev-parse", "HEAD"],
                capture_output=True, text=True, timeout=10,
            ).stdout.strip()
        except Exception:
            pass
        sentinel = pathlib.Path(cache_dir) / (
            f"prewarmed_{rev[:12]}_{SF}_{'_'.join(QUERIES)}"
        )
    if (
        os.environ.get("BENCH_PREWARM", "1") != "0"
        and SF <= 2
        and not (sentinel is not None and sentinel.exists())
    ):
        t0 = time.time()
        procs = []
        for qn in QUERIES:
            env = dict(device_env)
            env.update(
                {
                    "BENCH_CHILD": "1",
                    "BENCH_PREWARM_CHILD": "1",
                    "BENCH_SF": str(SF),
                    "BENCH_QUERIES": qn,
                    "BENCH_ITERS": "0",
                }
            )
            procs.append(
                subprocess.Popen(
                    [sys.executable, str(HERE / "bench.py")],
                    env=env,
                    stdout=subprocess.DEVNULL,
                    stderr=subprocess.DEVNULL,
                )
            )
        deadline = time.time() + int(
            os.environ.get("BENCH_PREWARM_TIMEOUT", 1800)
        )
        for p in procs:
            try:
                p.wait(timeout=max(1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        print(
            f"prewarm: {len(procs)} queries compiled in "
            f"{time.time() - t0:.0f}s",
            file=sys.stderr,
        )
        if sentinel is not None:
            try:
                sentinel.parent.mkdir(parents=True, exist_ok=True)
                sentinel.touch()
            except OSError:
                pass

    device_run = _run_child(
        device_env,
        ITERS,
        int(os.environ.get("BENCH_DEVICE_TIMEOUT", 2700)),
        "device",
    )
    if device_run is None:
        raise SystemExit(1)

    cpu_run = None
    if not os.environ.get("BENCH_SKIP_CPU"):
        env = {
            k: v
            for k, v in os.environ.items()
            if not k.startswith(("PALLAS_AXON", "AXON"))
        }
        env.update({"JAX_PLATFORMS": "cpu", "PYTHONPATH": str(HERE)})
        # CPU baseline is best-effort: a failure degrades vs_baseline to 0.
        # Same warm-iteration count as the device so best-of-N variance
        # treats both backends identically.
        cpu_run = _run_child(
            env, ITERS, int(os.environ.get("BENCH_CPU_TIMEOUT", 3600)),
            "cpu",
        )

    detail = {"device": device_run, "cpu": cpu_run}

    # Pinned denominator: a frozen, committed CPU-baseline artifact so
    # round-over-round ratios measure the DEVICE, not drift in a shared
    # host's CPU timings (observed ±30% swings across rounds). Freeze the
    # current live CPU suite with BENCH_FREEZE=1. Frozen baselines are
    # KEYED BY SCALE FACTOR (one file per SF) so SF=10/SF=100 runs report
    # vs_frozen_cpu against their own denominator instead of silently
    # falling back to the live CPU ratio; the legacy un-keyed file is
    # still honored for SF=1 readers of old artifacts.
    frozen_path = HERE / f"BENCH_BASELINE_SF{SF:g}.json"
    legacy_path = HERE / "BENCH_BASELINE.json"
    vs_frozen = None
    if cpu_run is not None and os.environ.get("BENCH_FREEZE"):
        frozen_path.write_text(
            json.dumps(
                {"sf": SF, "queries": sorted(QUERIES), "cpu": cpu_run},
                indent=2,
            )
        )
    for path in (frozen_path, legacy_path):
        if not path.exists():
            continue
        try:
            frozen = json.loads(path.read_text())
            if frozen.get("sf") == SF and frozen.get("queries") == sorted(
                QUERIES
            ):
                ft = sum(
                    q["warm_best_s"]
                    for q in frozen["cpu"]["queries"].values()
                )
                vs_frozen = round(ft / device_run["warm_total_s"], 3)
                detail["frozen_cpu_total_s"] = round(ft, 4)
                break
        except (json.JSONDecodeError, KeyError, TypeError):
            pass

    detail_path = HERE / (
        "BENCH_DETAIL.json" if SF == 1 else f"BENCH_SF{SF:g}_DETAIL.json"
    )
    detail_path.write_text(json.dumps(detail, indent=2))
    print(json.dumps(detail, indent=2), file=sys.stderr)

    vs = 0.0
    if cpu_run is not None:
        # speedup on identical warm work: cpu_total / device_total
        cpu_total = sum(q["warm_best_s"] for q in cpu_run["queries"].values())
        vs = round(cpu_total / device_run["warm_total_s"], 3)
    line = {
        "metric": (
            f"tpch_sf{SF}_warm_throughput_"
            + "_".join(QUERIES)
            + f"_{device_run['backend']}"
        ),
        "value": device_run["queries_per_s"],
        "unit": "queries/sec",
        "vs_baseline": vs,
    }
    if vs_frozen is not None:
        line["vs_frozen_cpu"] = vs_frozen
    print(json.dumps(line))


if __name__ == "__main__":
    main()
