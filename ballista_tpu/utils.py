"""Plan/stage visualization helpers.

ref ballista/rust/core/src/utils.rs:105-220 — ``produce_diagram`` writes a
Graphviz dot file with one cluster per query stage and edges from each
stage's UnresolvedShuffleExec leaves to the producing stage's writer node.
"""

from __future__ import annotations

from ballista_tpu.distributed_plan import UnresolvedShuffleExec
from ballista_tpu.executor.shuffle import ShuffleWriterExec


def _node_label(plan) -> str:
    name = type(plan).__name__
    extra = ""
    if isinstance(plan, ShuffleWriterExec):
        keys = ", ".join(str(k) for k in plan.partition_keys)
        extra = (
            f" hash[{keys}] x{plan.output_partitions}"
            if plan.partition_keys
            else f" x{plan.output_partitions}"
        )
    elif isinstance(plan, UnresolvedShuffleExec):
        extra = f" stage={plan.stage_id}"
    return name + extra


def produce_diagram(stages: list[ShuffleWriterExec]) -> str:
    """Render a stage DAG as Graphviz dot text (ref utils.rs:105-142; the
    reference writes to a file — see :func:`write_diagram`)."""
    lines = ["digraph G {"]
    # stage-local operator trees (one cluster per stage, ref :111-123)
    node_ids: dict[tuple[int, int], str] = {}  # (stage, seq) -> dot id
    readers: list[tuple[str, int]] = []  # (dot id, producing stage)
    writers: dict[int, str] = {}  # stage -> writer dot id

    for stage in stages:
        sid = stage.stage_id
        lines.append(f"\tsubgraph cluster{sid} {{")
        lines.append(f'\t\tlabel = "Stage {sid}";')
        counter = [0]

        def draw(plan, parent_id: str | None, sid=sid, counter=counter):
            nid = f"stage_{sid}_{counter[0]}"
            counter[0] += 1
            lines.append(f'\t\t{nid} [shape=box, label="{_node_label(plan)}"];')
            if parent_id is not None:
                lines.append(f"\t\t{nid} -> {parent_id};")
            if isinstance(plan, ShuffleWriterExec):
                writers[sid] = nid
            if isinstance(plan, UnresolvedShuffleExec):
                readers.append((nid, plan.stage_id))
            for child in plan.children():
                draw(child, nid)

        draw(stage, None)
        lines.append("\t}")

    # cross-stage edges: producing stage's writer -> consuming reader leaf
    # (ref :125-137 second pass)
    for reader_id, produced_by in readers:
        w = writers.get(produced_by)
        if w is not None:
            lines.append(f"\t{w} -> {reader_id} [style=dashed];")
    lines.append("}")
    return "\n".join(lines)


def write_diagram(filename: str, stages: list[ShuffleWriterExec]) -> None:
    """File-writing variant matching the reference signature (utils.rs:105)."""
    with open(filename, "w") as f:
        f.write(produce_diagram(stages))
