"""stalelint: cache-coherence static analysis over the declared registry.

Four rule families, proven over the AST of the whole package (same
engine style as racelint/lifelint; ``# stalelint: disable=<rule>``
suppressions are honored on the flagged line, its enclosing statement,
or the enclosing ``def`` line, and count against the shared
``analysis/budget.py`` ledger):

- **undeclared-cache** — a dict/LRU-shaped instance attribute, module
  global, or ``lru_cache`` decorator whose name or constructor matches
  the cache idiom (``*_cache``/``*Cache``, pool, registry, snapshot,
  hint, memo, LUT) must resolve to a declared
  :class:`~ballista_tpu.analysis.cachereg.CacheEntry` (or a written
  :class:`~ballista_tpu.analysis.cachereg.Exempt`). New caches cannot
  land without writing down their key composition, scope, coherence
  class, and invalidation sites.
- **missing-invalidation** — every mutator named in a declared
  :class:`~ballista_tpu.analysis.cachereg.InvalidationContract` must
  contain a call whose dotted name ends with each required invalidation
  suffix. Dropping ``self._plan_cache.clear()`` from ``register_table``,
  or ``job.eager_plan_bytes.pop(...)`` from ``apply_certified_rewrite``,
  is a gate failure — the contract the JobInfo comments used to carry in
  prose.
- **snapshot-escape** — ``snapshot``-class caches may only be READ
  through their declared seam (``Executor._job_snapshot``). Any other
  load of the live anchor from its owning file — passing
  ``self._plan_cache`` itself into a task attempt instead of the frozen
  copy is the exact q15 warm-drift bug — is an error. Writes (commit
  merges, invalidation pops) and declared persistence sinks
  (``ok_calls``) stay legal: learning still lands, it just cannot be
  adopted mid-job.
- **unvalidated-speculation** — operator code (``exec/``, ``ops/``,
  outside the ``exec/base.py`` seam itself) may only write to the
  speculative plan cache (``ctx.plan_cache`` and its local aliases) from
  a function that is wired into the validation seam — i.e. one that also
  calls ``defer_speculation``/``defer_learn``/``defer_commit``. A bare
  write with no validation path is a guess no future run ever checks.

Runtime counterpart: :mod:`ballista_tpu.analysis.stalewitness`
(``BALLISTA_CACHE_WITNESS=1``) — sampled cache hits must hash-match a
fresh re-derivation, the staleness analogue of the replay witness.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

from ballista_tpu.analysis import cachereg

_SUPPRESS_RE = re.compile(r"#\s*stalelint:\s*disable=([A-Za-z0-9_,\- ]+)")

RULES = {
    "undeclared-cache": "cache-shaped state not declared in "
    "analysis/cachereg.py",
    "missing-invalidation": "version-source mutator dropped a declared "
    "invalidation call",
    "snapshot-escape": "live snapshot-class state read outside its "
    "declared seam",
    "unvalidated-speculation": "speculative cache written outside the "
    "validation seam",
}

# Directories + top-level modules swept by the undeclared-cache rule
# (analysis/, testing/, proto/, tpch are out: witness record maps and
# test fixtures are not product caches).
TARGET_DIRS = (
    "client", "columnar", "compilecache", "exec", "executor", "expr",
    "obs", "ops", "parallel", "plan", "scheduler", "sql",
)
TARGET_MODULES = (
    "avro.py", "cli.py", "config.py", "datatypes.py",
    "distributed_plan.py", "errors.py", "event_loop.py", "functions.py",
    "plugin.py", "rewrite.py", "scheduler_types.py", "serde.py",
    "standalone.py", "utils.py",
)

# name fragments that mark a binding as cache-idiomatic
_NAME_HINTS = ("cache", "pool", "registry", "snapshot", "hint", "memo",
               "lut")
# constructor names that mark a value as cache-idiomatic regardless of
# the binding name
_CLASS_SUFFIXES = ("Cache", "Registry", "Pool", "Store", "Ladder")
_DICTISH_CALLS = ("dict", "OrderedDict", "defaultdict",
                  "WeakValueDictionary")

# rule 4: the speculative plan cache as operator code sees it
_SPEC_ATTR = "plan_cache"
_RULE4_DIRS = ("exec", "ops")
_RULE4_SEAM_FILES = ("ballista_tpu/exec/base.py",)
_VALIDATION_CALLS = ("defer_speculation", "defer_learn", "defer_commit")

_WRITE_METHODS = ("update", "pop", "clear", "setdefault", "popitem")


@dataclasses.dataclass(frozen=True)
class StaleDiagnostic:
    file: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


def _package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def target_files() -> list[pathlib.Path]:
    root = _package_root() / "ballista_tpu"
    files: list[pathlib.Path] = []
    for d in TARGET_DIRS:
        files += sorted((root / d).rglob("*.py"))
    files += [root / m for m in TARGET_MODULES if (root / m).exists()]
    return files


def _suppressed(source_lines: list[str], line: int, rule: str) -> bool:
    if line < 1 or line > len(source_lines):
        return False
    m = _SUPPRESS_RE.search(source_lines[line - 1])
    return bool(m) and rule in [
        s.strip() for s in m.group(1).split(",")
    ]


class _Marked:
    """Suppression lookup honoring the flagged line, its enclosing
    statement's first line, and the enclosing def line (detlint's
    contract)."""

    def __init__(self, source: str, tree: ast.Module):
        self.lines = source.splitlines()
        self._def_line: dict[int, int] = {}
        self._stmt_line: dict[int, int] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    ln = getattr(sub, "lineno", None)
                    if ln is not None and ln not in self._def_line:
                        self._def_line[ln] = node.lineno
            if isinstance(node, ast.stmt):
                for sub in ast.walk(node):
                    ln = getattr(sub, "lineno", None)
                    if ln is not None and ln not in self._stmt_line:
                        self._stmt_line[ln] = node.lineno

    def __call__(self, line: int, rule: str) -> bool:
        for ln in {line, self._stmt_line.get(line), self._def_line.get(line)}:
            if ln is not None and _suppressed(self.lines, ln, rule):
                return True
        return False


def _name_hit(name: str) -> bool:
    low = name.lower()
    return any(h in low for h in _NAME_HINTS)


def _call_name(func: ast.expr) -> str:
    if isinstance(func, ast.Name):
        return func.id
    if isinstance(func, ast.Attribute):
        return func.attr
    return ""


def _dotted(node: ast.expr) -> str:
    """Best-effort dotted rendering: ``job.eager_plan_bytes.pop`` ->
    'job.eager_plan_bytes.pop' (call suffix matching)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


def _cacheish_value(value: ast.expr | None) -> bool:
    if value is None:
        return False
    if isinstance(value, (ast.Dict, ast.DictComp)):
        return True
    if isinstance(value, ast.Call):
        name = _call_name(value.func)
        if name in _DICTISH_CALLS:
            return True
        if any(name.endswith(sfx) for sfx in _CLASS_SUFFIXES):
            return True
        if name == "field":
            # dataclasses.field(default_factory=dict/OrderedDict/...)
            for kw in value.keywords:
                if kw.arg == "default_factory" and isinstance(
                    kw.value, ast.Name
                ) and kw.value.id in _DICTISH_CALLS + ("dict",):
                    return True
    return False


def _cache_class_call(value: ast.expr | None) -> bool:
    return isinstance(value, ast.Call) and any(
        _call_name(value.func).endswith(sfx) for sfx in _CLASS_SUFFIXES
    )


# ---------------------------------------------------------------------------
# rule 1: undeclared-cache
# ---------------------------------------------------------------------------

def _rule_undeclared(
    tree: ast.Module, filename: str, marked: _Marked, index: dict[str, str]
) -> list[StaleDiagnostic]:
    out: list[StaleDiagnostic] = []
    flagged: set[tuple[str, int]] = set()

    def check(qual: str, value: ast.expr | None, line: int) -> None:
        name = qual.rsplit(".", 1)[-1]
        if not _cacheish_value(value):
            return
        if not (_name_hit(name) or _cache_class_call(value)):
            return
        anchor = f"{filename}::{qual}"
        if anchor in index or (qual, line) in flagged:
            return
        flagged.add((qual, line))
        if marked(line, "undeclared-cache"):
            return
        out.append(StaleDiagnostic(
            filename, line, "undeclared-cache",
            f"`{qual}` looks like a cache but has no CacheEntry — "
            f"declare anchor '{anchor}' (or an Exempt with a reason) in "
            "analysis/cachereg.py",
        ))

    def split(node: ast.stmt) -> tuple[list[ast.expr], ast.expr | None]:
        if isinstance(node, ast.Assign):
            return node.targets, node.value
        if isinstance(node, ast.AnnAssign):
            return [node.target], node.value
        return [], None

    # module globals: Name targets at module level only (locals inside
    # functions are attempt-scoped, not shared caches)
    for node in tree.body:
        for t, value in [(t, v) for ts, v in [split(node)] for t in ts]:
            if isinstance(t, ast.Name):
                check(t.id, value, node.lineno)
        if not isinstance(node, ast.ClassDef):
            continue
        cls = node
        # class-body fields (dataclass fields included)
        for sub in cls.body:
            for t, value in [(t, v) for ts, v in [split(sub)] for t in ts]:
                if isinstance(t, ast.Name):
                    check(f"{cls.name}.{t.id}", value, sub.lineno)
        # instance attributes anywhere in the class's methods
        for sub in ast.walk(cls):
            for t, value in [(t, v) for ts, v in [split(sub)] for t in ts]:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    check(f"{cls.name}.{t.attr}", value, sub.lineno)
    # lru_cache / functools.cache decorators are caches with no explicit
    # invalidation story at all: they must be declared or exempted
    for node in ast.walk(tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for dec in node.decorator_list:
            base = dec.func if isinstance(dec, ast.Call) else dec
            if _call_name(base) in ("lru_cache", "cache") or (
                isinstance(base, ast.Attribute)
                and base.attr in ("lru_cache", "cache")
            ):
                anchor = f"{filename}::{node.name}"
                if anchor in index or marked(
                    node.lineno, "undeclared-cache"
                ):
                    continue
                out.append(StaleDiagnostic(
                    filename, node.lineno, "undeclared-cache",
                    f"`@{_call_name(base)}` on `{node.name}` is an "
                    f"undeclared cache — declare anchor '{anchor}' in "
                    "analysis/cachereg.py",
                ))
    return out


# ---------------------------------------------------------------------------
# rule 2: missing-invalidation
# ---------------------------------------------------------------------------

def _rule_missing_invalidation(
    tree: ast.Module, filename: str, marked: _Marked
) -> list[StaleDiagnostic]:
    out: list[StaleDiagnostic] = []
    contracts = [c for c in cachereg.CONTRACTS if c.file == filename]
    if not contracts:
        return out
    funcs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            funcs.setdefault(node.name, node)
    for c in contracts:
        for mut in c.mutators:
            fn = funcs.get(mut)
            if fn is None:
                out.append(StaleDiagnostic(
                    filename, 1, "missing-invalidation",
                    f"contract '{c.source}': mutator `{mut}` not found "
                    "(renamed? update analysis/cachereg.py)",
                ))
                continue
            calls = {
                _dotted(sub.func)
                for sub in ast.walk(fn)
                if isinstance(sub, ast.Call)
            }
            for suffix in c.must_call:
                if any(d.endswith(suffix) for d in calls):
                    continue
                if marked(fn.lineno, "missing-invalidation"):
                    continue
                out.append(StaleDiagnostic(
                    filename, fn.lineno, "missing-invalidation",
                    f"`{mut}` mutates version source '{c.source}' but "
                    f"never calls `...{suffix}(...)` — dependent caches "
                    f"{', '.join(c.caches)} would serve stale state",
                ))
    return out


# ---------------------------------------------------------------------------
# rule 3: snapshot-escape
# ---------------------------------------------------------------------------

def _enclosing_funcs(tree: ast.Module) -> dict[ast.AST, list[str]]:
    """node -> names of every enclosing function (innermost last)."""
    chains: dict[ast.AST, list[str]] = {}

    def walk(node: ast.AST, stack: list[str]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walk(child, stack + [child.name])
            else:
                chains[child] = stack
                walk(child, stack)

    walk(tree, [])
    return chains


def _rule_snapshot_escape(
    tree: ast.Module, filename: str, marked: _Marked
) -> list[StaleDiagnostic]:
    entries = [
        (e, a.split("::", 1)[1])
        for e in cachereg.CACHES
        if e.coherence == "snapshot"
        for a in e.anchors
        if a.startswith(filename + "::")
    ]
    if not entries:
        return []
    out: list[StaleDiagnostic] = []
    parents: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    chains = _enclosing_funcs(tree)

    for e, qual in entries:
        attr = qual.rsplit(".", 1)[-1]
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.Attribute)
                and node.attr == attr
                and isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                continue
            if any(fn in e.seam for fn in chains.get(node, [])):
                continue
            if isinstance(node.ctx, (ast.Store, ast.Del)):
                continue
            parent = parents.get(node)
            # receiver of a mutation method: self.X.update(...) etc.
            if (
                isinstance(parent, ast.Attribute)
                and parent.attr in _WRITE_METHODS
                and isinstance(parents.get(parent), ast.Call)
                and parents[parent].func is parent
            ):
                continue
            # store-subscript: self.X[k] = v
            if isinstance(parent, ast.Subscript) and isinstance(
                parent.ctx, ast.Store
            ):
                continue
            # argument to a declared persistence sink
            if isinstance(parent, ast.Call) and node in (
                list(parent.args) + [kw.value for kw in parent.keywords]
            ):
                callee = _dotted(parent.func).rsplit(".", 1)[-1]
                if callee in e.ok_calls:
                    continue
            if marked(node.lineno, "snapshot-escape"):
                continue
            out.append(StaleDiagnostic(
                filename, node.lineno, "snapshot-escape",
                f"live read of snapshot-class `{e.name}` "
                f"(self.{attr}) outside its seam "
                f"{e.seam} — task paths must go through the frozen "
                "job-snapshot copy (the q15 warm-drift shape)",
            ))
    return out


# ---------------------------------------------------------------------------
# rule 4: unvalidated-speculation
# ---------------------------------------------------------------------------

def _rule4_applies(filename: str) -> bool:
    if filename in _RULE4_SEAM_FILES:
        return False
    return any(
        filename.startswith(f"ballista_tpu/{d}/") for d in _RULE4_DIRS
    )


def _rule_unvalidated_speculation(
    tree: ast.Module, filename: str, marked: _Marked
) -> list[StaleDiagnostic]:
    if not _rule4_applies(filename):
        return []
    out: list[StaleDiagnostic] = []

    def outermost_funcs(node: ast.AST):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child
            else:
                yield from outermost_funcs(child)

    def contains_spec_attr(expr: ast.expr) -> bool:
        return any(
            isinstance(n, ast.Attribute) and n.attr == _SPEC_ATTR
            for n in ast.walk(expr)
        )

    for fn in outermost_funcs(tree):
        validated = any(
            isinstance(n, ast.Call)
            and _call_name(n.func) in _VALIDATION_CALLS
            for n in ast.walk(fn)
        )
        aliases: set[str] = set()
        for n in ast.walk(fn):
            if isinstance(n, ast.Assign) and contains_spec_attr(n.value):
                for t in n.targets:
                    if isinstance(t, ast.Name):
                        aliases.add(t.id)
            elif isinstance(n, ast.AnnAssign) and n.value is not None \
                    and contains_spec_attr(n.value):
                if isinstance(n.target, ast.Name):
                    aliases.add(n.target.id)

        def is_spec_ref(base: ast.expr) -> bool:
            if isinstance(base, ast.Attribute) and base.attr == _SPEC_ATTR:
                return True
            return isinstance(base, ast.Name) and base.id in aliases

        writes: list[int] = []
        for n in ast.walk(fn):
            if (
                isinstance(n, ast.Subscript)
                and isinstance(n.ctx, ast.Store)
                and is_spec_ref(n.value)
            ):
                writes.append(n.lineno)
            elif (
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in ("update", "setdefault")
                and is_spec_ref(n.func.value)
            ):
                writes.append(n.lineno)
        if not writes or validated:
            continue
        for line in writes:
            if marked(line, "unvalidated-speculation"):
                continue
            out.append(StaleDiagnostic(
                filename, line, "unvalidated-speculation",
                f"`{fn.name}` writes the speculative plan cache but "
                "never wires a validation path "
                "(defer_speculation/defer_learn/defer_commit) — a guess "
                "no future run ever checks",
            ))
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def lint_source(source: str, filename: str) -> list[StaleDiagnostic]:
    tree = ast.parse(source, filename=filename)
    marked = _Marked(source, tree)
    index = cachereg.anchor_index()
    diags = (
        _rule_undeclared(tree, filename, marked, index)
        + _rule_missing_invalidation(tree, filename, marked)
        + _rule_snapshot_escape(tree, filename, marked)
        + _rule_unvalidated_speculation(tree, filename, marked)
    )
    return sorted(diags, key=lambda d: (d.file, d.line, d.rule))


def lint_paths(paths=None) -> list[StaleDiagnostic]:
    root = _package_root()
    files = (
        [pathlib.Path(p) for p in paths] if paths else target_files()
    )
    diags: list[StaleDiagnostic] = []
    seen: set[str] = set()
    for path in files:
        rel = str(path.relative_to(root)) if path.is_absolute() else str(path)
        seen.add(rel)
        diags += lint_source(path.read_text(), rel)
    if paths is None:
        # contracts over files outside the sweep would silently never run
        for c in cachereg.CONTRACTS:
            if c.file not in seen:
                diags.append(StaleDiagnostic(
                    c.file, 1, "missing-invalidation",
                    f"contract '{c.source}' targets a file outside the "
                    "stalelint sweep",
                ))
    return sorted(set(diags), key=lambda d: (d.file, d.line, d.rule))


def suppression_count(paths=None) -> int:
    root = _package_root()
    files = (
        [pathlib.Path(p) for p in paths] if paths else target_files()
    )
    n = 0
    for path in files:
        for line in path.read_text().splitlines():
            if _SUPPRESS_RE.search(line):
                n += 1
    return n
