"""The declared durability registry: every piece of scheduler state is
a recovery contract.

The ROADMAP's elastic-fleet item (scheduler HA, rolling restarts as
routine operations) rests on one assumption: every mutable control-plane
field either survives a restart through
:class:`~ballista_tpu.scheduler.persistent_state.PersistentSchedulerState`
or is legitimately rebuildable. Today ``_recover_state`` recovers
whatever someone remembered to persist — nothing fails when a new
mutable field lands on ``SchedulerServer``/``StageManager``/``JobInfo``
with no recovery story. This module closes the class the way
:mod:`ballista_tpu.analysis.cachereg` closed cache coherence: state may
only exist if it is DECLARED here with a durability class, and
:mod:`ballista_tpu.analysis.durlint` proves the tree against the
declarations while :mod:`ballista_tpu.analysis.durwitness` proves the
running system (restart + failover) against them.

Durability classes (what a scheduler restart does to the field):

- ``persisted`` — written through ``PersistentSchedulerState`` and read
  back in ``_recover_state``; the entry names its save/load pair and
  durlint's recovery-gap rule proves the load actually runs (write-only
  durability is the silent failure mode).
- ``rebuilt`` — reconstructed from a declared source after restart:
  executor re-registration/heartbeats, a backend prefix scan, or
  derivation from other declared state. The witness asserts these start
  empty and converge once the source replays.
- ``ephemeral`` — deliberately lost on restart. Must either cross-link
  a declared cachereg entry (restart-cold caches) or carry a written
  justification naming where the durable record lives instead (usually
  the append-only HistoryStore).

Anchors are ``"relative/path.py::Class.attr"`` (instance attribute or
dataclass field) — :func:`verify_anchors` proves every anchor still
resolves against the live tree, so a rename goes red in the gate
instead of silently orphaning the declaration. The reverse direction —
no mutable control-plane field left undeclared — is durlint's
``undeclared-state`` rule over :data:`CONTROL_CLASSES`.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib

from ballista_tpu.analysis import cachereg


@dataclasses.dataclass(frozen=True)
class StateEntry:
    """One declared state-field group. ``save``/``load`` name the
    ``PersistentSchedulerState`` method pair for ``persisted`` entries;
    ``recovery`` carries the rebuild source for ``rebuilt`` entries and
    the written justification for ``ephemeral`` ones; ``cache_link``
    cross-links restart-cold caches to their cachereg declarations."""

    name: str
    anchors: tuple[str, ...]
    durability: str  # persisted | rebuilt | ephemeral
    contents: str
    save: str | None = None
    load: str | None = None
    recovery: str = ""
    cache_link: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class PersistenceContract:
    """Machine-checked mutator→must-persist obligation: every
    ``mutators`` function in ``file`` must contain a call whose dotted
    name ends with each ``must_call`` suffix — durlint's
    unpersisted-mutation rule. This is how "every terminal job
    transition reaches save_job" stops being reviewer folklore and
    becomes a gate failure when the call is dropped."""

    source: str
    file: str
    mutators: tuple[str, ...]
    must_call: tuple[str, ...]
    fields: tuple[str, ...]


@dataclasses.dataclass(frozen=True)
class WriteSeam:
    """A declared exception to the backend-write lock discipline:
    functions in ``file`` that may call ``backend.put``/``backend.delete``
    outside ``with backend.lock():``, with the reasoning written down.
    Everything else is durlint's unguarded-backend-write rule — the
    split-brain shape that breaks two-scheduler etcd deployments."""

    file: str
    functions: tuple[str, ...]
    reason: str


DURABILITY = ("persisted", "rebuilt", "ephemeral")

STATE: tuple[StateEntry, ...] = (
    # -- persisted: the PersistentSchedulerState backbone -------------------
    StateEntry(
        name="job-map",
        anchors=("ballista_tpu/scheduler/server.py::SchedulerServer.jobs",),
        durability="persisted",
        contents="job_id -> JobInfo, the scheduler's job table",
        save="save_job",
        load="load_jobs",
    ),
    StateEntry(
        name="job-record",
        anchors=(
            "ballista_tpu/scheduler/server.py::JobInfo.job_id",
            "ballista_tpu/scheduler/server.py::JobInfo.session_id",
            "ballista_tpu/scheduler/server.py::JobInfo.status",
            "ballista_tpu/scheduler/server.py::JobInfo.error",
            "ballista_tpu/scheduler/server.py::JobInfo.final_stage_id",
            "ballista_tpu/scheduler/server.py::JobInfo.dependencies",
        ),
        durability="persisted",
        contents="the durable job record: identity, session, status, "
        "error, final stage, stage dependency graph",
        save="save_job",
        load="load_jobs",
    ),
    StateEntry(
        name="completed-locations",
        anchors=(
            "ballista_tpu/scheduler/server.py::"
            "JobInfo.completed_locations",
        ),
        durability="persisted",
        contents="a completed job's committed partition locations — the "
        "payload GetJobStatus serves after a restart",
        save="save_job",
        load="load_jobs",
    ),
    StateEntry(
        name="stage-plans",
        anchors=("ballista_tpu/scheduler/server.py::JobInfo.stages",),
        durability="persisted",
        contents="stage id -> pristine QueryStage templates (serialized "
        "per stage; recovery rebuilds the QueryStage objects)",
        save="save_stage_plan",
        load="load_stage_plans",
    ),
    StateEntry(
        name="sessions",
        anchors=(
            "ballista_tpu/scheduler/server.py::SchedulerServer.sessions",
        ),
        durability="persisted",
        contents="session_id -> BallistaConfig settings snapshot",
        save="save_session",
        load="load_sessions",
    ),
    StateEntry(
        name="executor-metadata",
        anchors=(
            "ballista_tpu/scheduler/executor_manager.py::"
            "ExecutorManager._metadata",
        ),
        durability="persisted",
        contents="executor_id -> host/ports/specification; kept past "
        "deregistration because shuffle locations reference the host",
        save="save_executor_metadata",
        load="load_executors",
    ),
    # -- rebuilt: reconstructed from a declared source ----------------------
    StateEntry(
        name="executor-heartbeats",
        anchors=(
            "ballista_tpu/scheduler/executor_manager.py::"
            "ExecutorManager._heartbeats",
        ),
        durability="rebuilt",
        contents="executor_id -> last heartbeat timestamp",
        recovery="executor re-registration and heartbeat RPCs repopulate "
        "it; until then the expiry sweep treats unseen executors as "
        "expired, which is the safe default",
    ),
    StateEntry(
        name="executor-slots",
        anchors=(
            "ballista_tpu/scheduler/executor_manager.py::"
            "ExecutorManager._data",
        ),
        durability="rebuilt",
        contents="executor_id -> live slot accounting (ExecutorData)",
        recovery="re-registration/PollWork grant a fresh full-slot "
        "record; pre-restart in-flight tasks queue behind the executor's "
        "runner pool (bounded oversubscription, see RegisterExecutor)",
    ),
    StateEntry(
        name="executor-metrics",
        anchors=(
            "ballista_tpu/scheduler/executor_manager.py::"
            "ExecutorManager._metrics",
        ),
        durability="rebuilt",
        contents="executor_id -> latest shipped metrics snapshot",
        recovery="overwritten wholesale by the next heartbeat/poll",
    ),
    StateEntry(
        name="executor-clients",
        anchors=(
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer.executor_clients",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._executor_channels",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._launch_failures",
        ),
        durability="rebuilt",
        contents="push-mode gRPC channels/stubs back to executors plus "
        "consecutive launch-failure counts",
        recovery="re-dialed lazily at registration/offer time; failure "
        "counts restart at zero (an executor only pays for failures the "
        "CURRENT scheduler observed)",
    ),
    StateEntry(
        name="stage-state",
        anchors=(
            "ballista_tpu/scheduler/stage_manager.py::StageManager._stages",
            "ballista_tpu/scheduler/stage_manager.py::StageManager._running",
            "ballista_tpu/scheduler/stage_manager.py::StageManager._pending",
            "ballista_tpu/scheduler/stage_manager.py::"
            "StageManager._completed",
            "ballista_tpu/scheduler/stage_manager.py::"
            "StageManager._dependencies",
            "ballista_tpu/scheduler/stage_manager.py::"
            "StageManager._final_stage",
        ),
        durability="rebuilt",
        contents="the live stage DAG: per-stage task tables, "
        "running/pending/completed membership, dependency edges, final "
        "stage ids",
        recovery="deliberately NOT persisted (matches the reference "
        "persistent_state.rs): _recover_state closes every in-flight "
        "job as failed — clients resubmit and stages regenerate from "
        "the persisted stage plans",
    ),
    StateEntry(
        name="trace-index",
        anchors=(
            "ballista_tpu/scheduler/server.py::SchedulerServer._traces",
        ),
        durability="rebuilt",
        contents="trace_id -> job_id for executor span ingestion",
        recovery="derived from the jobs map at submission; recovered "
        "jobs are terminal, so no further span ingestion is expected "
        "for them",
    ),
    # -- ephemeral: deliberately lost, with the durable record named --------
    StateEntry(
        name="resolved-plan-bytes",
        anchors=(
            "ballista_tpu/scheduler/server.py::JobInfo.resolved_plan_bytes",
        ),
        durability="ephemeral",
        contents="stage id -> shuffle-patched serialized plans",
        recovery="derived cache over stage-plans + live locations; "
        "re-resolved on demand after recovery",
        cache_link=("resolved-plan-bytes",),
    ),
    StateEntry(
        name="eager-plan-bytes",
        anchors=(
            "ballista_tpu/scheduler/server.py::JobInfo.eager",
            "ballista_tpu/scheduler/server.py::JobInfo.eager_plan_bytes",
        ),
        durability="ephemeral",
        contents="eager-shuffle session flag snapshot + per-stage eager "
        "resolutions",
        recovery="derived cache over the pristine stage templates; "
        "re-derived on demand",
        cache_link=("eager-plan-bytes",),
    ),
    StateEntry(
        name="result-cache-state",
        anchors=(
            "ballista_tpu/scheduler/server.py::SchedulerServer.result_cache",
            "ballista_tpu/scheduler/server.py::JobInfo.cache_key",
            "ballista_tpu/scheduler/server.py::JobInfo.result_ipc",
        ),
        durability="ephemeral",
        contents="the serving-path result cache plus the per-job cache "
        "key / served-payload fields",
        recovery="in-memory only BY DESIGN: a restarted scheduler starts "
        "cold, which is the no-stale-serve-after-recovery contract "
        "(the witness asserts emptiness post-restart)",
        cache_link=("result-cache",),
    ),
    StateEntry(
        name="bypass-state",
        anchors=(
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._bypass_pending",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._bypass_running",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._bypass_attempts",
            "ballista_tpu/scheduler/server.py::JobInfo.bypass",
        ),
        durability="ephemeral",
        contents="single-stage-bypass grant queue, running map, attempt "
        "counts, and the per-job bypass flag",
        recovery="grants die with the scheduler: bypass jobs are "
        "in-flight jobs, so _recover_state closes them as failed and "
        "clients resubmit (same contract as stage-state)",
    ),
    StateEntry(
        name="job-run-counters",
        anchors=(
            "ballista_tpu/scheduler/server.py::JobInfo.max_attempts",
            "ballista_tpu/scheduler/server.py::JobInfo.total_retries",
            "ballista_tpu/scheduler/server.py::JobInfo.total_recomputes",
            "ballista_tpu/scheduler/server.py::JobInfo.total_rewrites",
            "ballista_tpu/scheduler/server.py::"
            "JobInfo.total_rewrite_rejects",
            "ballista_tpu/scheduler/server.py::JobInfo.rewrite_log",
            "ballista_tpu/scheduler/server.py::JobInfo.rewritten_stages",
            "ballista_tpu/scheduler/server.py::JobInfo.aqe_decisions",
        ),
        durability="ephemeral",
        contents="retry-policy snapshot plus retry/recompute/rewrite "
        "visibility counters and decision logs",
        recovery="the durable record is the HistoryStore terminal row "
        "(obs/history.py record_terminal carries the counters); the "
        "live fields only feed /api/job for running jobs",
    ),
    StateEntry(
        name="job-obs-payloads",
        anchors=(
            "ballista_tpu/scheduler/server.py::JobInfo.trace_id",
            "ballista_tpu/scheduler/server.py::JobInfo.root_span_id",
            "ballista_tpu/scheduler/server.py::JobInfo.stage_spans",
            "ballista_tpu/scheduler/server.py::JobInfo.spans",
            "ballista_tpu/scheduler/server.py::JobInfo.op_metrics",
            "ballista_tpu/scheduler/server.py::JobInfo.stage_stats",
            "ballista_tpu/scheduler/server.py::JobInfo.root_span",
            "ballista_tpu/scheduler/server.py::JobInfo.query_class",
            "ballista_tpu/scheduler/server.py::JobInfo.submitted_s",
            "ballista_tpu/scheduler/server.py::JobInfo.first_assign_s",
            "ballista_tpu/scheduler/server.py::JobInfo.skew_flags",
            "ballista_tpu/scheduler/server.py::JobInfo.cost",
        ),
        durability="ephemeral",
        contents="per-job observability payloads: trace/span state, "
        "operator metrics, stage stats, query class, timing, skew "
        "flags, cost vector",
        recovery="the durable record is the HistoryStore terminal row "
        "(latency, queue wait, cost, class); live spans/metrics are "
        "scrape-time state that dies with the run",
    ),
    StateEntry(
        name="scheduler-obs-counters",
        anchors=(
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer.obs_task_counters",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._obs_retained",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer.obs_straggler_total",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer.obs_skew_total",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._recent_queue_waits",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer._known_classes",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer.obs_class_cost",
            "ballista_tpu/scheduler/server.py::"
            "SchedulerServer.obs_aqe_total",
        ),
        durability="ephemeral",
        contents="cross-job metrics aggregations: task counters, "
        "retained terminal-job payload ring, straggler/skew counters, "
        "recent queue-wait window, query-class cardinality set, "
        "per-class cost rollup, AQE counters",
        recovery="metrics sinks restart at zero like any process "
        "counter (prometheus counters are resets-tolerant by "
        "convention); the durable analog is the HistoryStore query log",
    ),
)

CONTROL_CLASSES: dict[str, str] = {
    # class anchor -> sweep mode for durlint's undeclared-state rule:
    # "init-containers" flags every `self.x = <mutable container>` in the
    # class with no registry anchor; "dataclass-fields" requires EVERY
    # dataclass field to be anchored (scalars included — a scalar status
    # field is exactly the state a restart loses).
    "ballista_tpu/scheduler/server.py::SchedulerServer": "init-containers",
    "ballista_tpu/scheduler/server.py::JobInfo": "dataclass-fields",
    "ballista_tpu/scheduler/stage_manager.py::StageManager":
        "init-containers",
    "ballista_tpu/scheduler/executor_manager.py::ExecutorManager":
        "init-containers",
}

# Machine-checked persistence obligations (durlint unpersisted-mutation).
CONTRACTS: tuple[PersistenceContract, ...] = (
    PersistenceContract(
        source="job-terminal",
        file="ballista_tpu/scheduler/server.py",
        mutators=(
            "_on_job_finished", "_on_job_failed", "_finish_bypass_job",
            "_recover_state",
        ),
        must_call=("save_job",),
        fields=("job-record", "completed-locations"),
    ),
    PersistenceContract(
        source="job-submit",
        file="ballista_tpu/scheduler/server.py",
        mutators=("submit_physical",),
        must_call=("save_job",),
        fields=("job-record",),
    ),
    PersistenceContract(
        source="stage-generation",
        file="ballista_tpu/scheduler/server.py",
        mutators=("_generate_stages",),
        must_call=("save_stage_plan", "save_job"),
        fields=("stage-plans", "job-record"),
    ),
    PersistenceContract(
        source="rewrite-acceptance",
        file="ballista_tpu/scheduler/server.py",
        mutators=("apply_certified_rewrite",),
        must_call=("save_stage_plan",),
        fields=("stage-plans",),
    ),
    PersistenceContract(
        source="bypass-submit",
        file="ballista_tpu/scheduler/server.py",
        mutators=("_submit_bypass",),
        must_call=("save_stage_plan", "save_job"),
        fields=("stage-plans", "job-record"),
    ),
    PersistenceContract(
        source="session-create",
        file="ballista_tpu/scheduler/server.py",
        mutators=("get_or_create_session",),
        must_call=("save_session",),
        fields=("sessions",),
    ),
    PersistenceContract(
        source="executor-register",
        file="ballista_tpu/scheduler/server.py",
        mutators=("persist_executor",),
        must_call=("save_executor_metadata",),
        fields=("executor-metadata",),
    ),
)

# Declared exceptions to the backend-write lock discipline (durlint
# unguarded-backend-write). The history log is append-only with unique
# stamped keys and a single logical writer per job, so its puts need no
# global lock — taking it would serialize the observability plane behind
# persistence. Everything else must write under `with backend.lock():`.
WRITE_SEAMS: tuple[WriteSeam, ...] = (
    WriteSeam(
        file="ballista_tpu/obs/history.py",
        functions=(
            "record_submit", "record_terminal", "record_attempt",
            "_enforce_retention",
        ),
        reason="append-only log: keys are uniquely stamped per "
        "(job, kind), each record is written once by the single "
        "scheduler that owns the job, and retention only deletes keys "
        "it stamped — no read-modify-write to race",
    ),
)


def _package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def anchor_index() -> dict[str, str]:
    """anchor -> declared entry name; duplicate anchors are a registry
    bug caught here."""
    idx: dict[str, str] = {}
    for e in STATE:
        for a in e.anchors:
            assert a not in idx, f"anchor declared twice: {a}"
            idx[a] = e.name
    return idx


def entry(name: str) -> StateEntry:
    for e in STATE:
        if e.name == name:
            return e
    raise KeyError(name)


def entries(durability: str) -> tuple[StateEntry, ...]:
    return tuple(e for e in STATE if e.durability == durability)


def verify_anchors() -> list[str]:
    """Every declared anchor must resolve against the live tree, every
    durability class must be legal and carry its required story
    (save/load pair, rebuild source, or justification/cache link), and
    every contract/cache-link reference must resolve."""
    root = _package_root()
    problems: list[str] = []
    trees: dict[str, ast.Module] = {}

    def tree_for(rel: str) -> ast.Module | None:
        if rel not in trees:
            path = root / rel
            if not path.exists():
                return None
            trees[rel] = ast.parse(path.read_text(), filename=rel)
        return trees[rel]

    anchors = [(a, e.name) for e in STATE for a in e.anchors]
    anchors += [(a, "control-class") for a in CONTROL_CLASSES]
    for anchor, owner in anchors:
        rel, _, qual = anchor.partition("::")
        t = tree_for(rel)
        if t is None:
            problems.append(f"{owner}: anchor file missing: {rel}")
        elif not cachereg._resolve_anchor(t, qual) and not _class_exists(
            t, qual
        ):
            problems.append(
                f"{owner}: anchor does not resolve: {anchor} "
                "(renamed attribute? update analysis/durreg.py)"
            )
    # the persistence layer itself: every persisted entry's save/load
    # pair must be real methods of PersistentSchedulerState
    ps = tree_for("ballista_tpu/scheduler/persistent_state.py")
    for e in STATE:
        if e.durability not in DURABILITY:
            problems.append(f"{e.name}: unknown durability {e.durability!r}")
        if e.durability == "persisted":
            if not (e.save and e.load):
                problems.append(
                    f"{e.name}: persisted entries must name their "
                    "save/load pair"
                )
            else:
                for fn in (e.save, e.load):
                    if ps is not None and not cachereg._resolve_anchor(
                        ps, f"PersistentSchedulerState.{fn}"
                    ):
                        problems.append(
                            f"{e.name}: PersistentSchedulerState.{fn} "
                            "does not exist (renamed? update "
                            "analysis/durreg.py)"
                        )
        elif e.durability == "rebuilt":
            if not e.recovery:
                problems.append(
                    f"{e.name}: rebuilt entries must name their recovery "
                    "source"
                )
        elif e.durability == "ephemeral":
            if not (e.cache_link or e.recovery):
                problems.append(
                    f"{e.name}: ephemeral entries must cross-link a "
                    "cachereg entry or carry a written justification"
                )
        for c in e.cache_link:
            try:
                cachereg.entry(c)
            except KeyError:
                problems.append(
                    f"{e.name}: cache_link {c!r} is not a declared "
                    "cachereg entry"
                )
    for c in CONTRACTS:
        for name in c.fields:
            try:
                entry(name)
            except KeyError:
                problems.append(
                    f"contract {c.source}: unknown state entry {name!r}"
                )
    for mode in CONTROL_CLASSES.values():
        if mode not in ("init-containers", "dataclass-fields"):
            problems.append(f"unknown control-class mode {mode!r}")
    return problems


def _class_exists(tree: ast.Module, qual: str) -> bool:
    """CONTROL_CLASSES anchors name a bare class."""
    return "." not in qual and any(
        isinstance(n, ast.ClassDef) and n.name == qual for n in tree.body
    )


def render_inventory() -> str:
    """The durability inventory as a markdown table — embedded verbatim
    in docs/analysis.md and checked by the gate (docs_in_sync), the same
    generated-docs discipline as the cachereg inventory."""
    lines = [
        "| state | durability | persistence | recovery story |",
        "|---|---|---|---|",
    ]
    for e in STATE:
        if e.durability == "persisted":
            persist = f"`{e.save}` / `{e.load}`"
        elif e.cache_link:
            persist = "cachereg: " + ", ".join(
                f"`{c}`" for c in e.cache_link
            )
        else:
            persist = "—"
        story = e.recovery or "round-trips through the state backend"
        lines.append(
            f"| `{e.name}` | {e.durability} | {persist} | {story} |"
        )
    return "\n".join(lines)


def docs_path() -> pathlib.Path:
    return _package_root() / "docs" / "analysis.md"


def docs_in_sync() -> str | None:
    """None when docs/analysis.md embeds the generated inventory table
    verbatim, else the failure message."""
    try:
        text = docs_path().read_text()
    except OSError as e:
        return f"docs/analysis.md unreadable: {e}"
    if render_inventory() not in text:
        return (
            "docs/analysis.md durability inventory is out of sync with "
            "analysis/durreg.py (paste render_inventory() output)"
        )
    return None
