"""eqlint: the no-uncertified-mutation closure over physical plans.

``ballista_tpu/rewrite.py`` is the certified plan-rewrite API — the ONLY
sanctioned way to change the structure of an ``ExecutionPlan`` tree or a
stage plan after construction. This AST lint is what makes that claim
load-bearing rather than advisory (the same move racelint made for
status writes with its undeclared-transition rule): a direct write to a
structural plan field anywhere else in the tree is a finding.

==========================  ================================================
rule                        rationale
==========================  ================================================
uncertified-plan-write      ``node.input = x`` / ``join.join_type = ...`` /
                            ``writer.output_partitions = n`` outside
                            rewrite.py mutates a plan with NO certificate:
                            no schema-equivalence proof, no bucket-compat
                            proof, no vocabulary gate. Adaptive execution
                            built on ad-hoc attribute surgery is exactly
                            the silent-wrong-answer source the AQE
                            literature documents (PAPERS.md). Constructors
                            (``self.field = ...`` inside ``__init__`` /
                            ``__post_init__``) are the sanctioned way to
                            BUILD plans; ``exec.base.replace_children`` is
                            the one sanctioned child-rebind primitive.
uncertified-stage-write     ``stage.plan = x`` where the receiver is a
                            ``QueryStage``: swapping a stage's pristine
                            template bypasses the scheduler's certificate
                            gate (SchedulerServer.apply_certified_rewrite
                            is the sanctioned swap point).
==========================  ================================================

Suppression: ``# eqlint: disable=<rule>`` on the offending line or the
enclosing ``def`` line; the shared budget ledger (analysis/budget.py)
bounds tree-wide suppressions.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

RULES: dict[str, str] = {
    "uncertified-plan-write": "direct write to a structural ExecutionPlan "
    "field outside rewrite.py / sanctioned constructors",
    "uncertified-stage-write": "direct swap of a QueryStage's plan "
    "template outside the certified rewrite path",
}

_SUPPRESS_RE = re.compile(r"#\s*eqlint:\s*disable=([A-Za-z0-9_,\- ]+)")

# Child slots + structure-defining fields of the physical-plan node
# vocabulary (exec/, executor/shuffle.py, distributed_plan.py). Writing
# any of these changes what a plan COMPUTES — exactly what a rewrite
# certificate exists to prove safe. Deliberately excludes runtime-state
# fields (metrics, caches, learned flags): mutating those changes cost,
# not semantics.
CHILD_SLOTS = frozenset({"input", "left", "right", "inputs"})
STRUCT_FIELDS = frozenset(
    {
        "on",
        "join_type",
        "partition_mode",
        "partition_keys",
        "output_partitions",
        "predicate",
        "exprs",
        "sort_exprs",
        "agg_exprs",
        "group_exprs",
        "window_exprs",
        "output_partition_count",
        "input_partition_count",
    }
)

# Files where structural writes are the sanctioned mechanism itself.
SANCTIONED_FILES = frozenset({"rewrite.py"})
# (file basename, function) pairs sanctioned individually: the single
# child-rebind primitive every copy-on-write path routes through.
SANCTIONED_FUNCTIONS = frozenset({("base.py", "replace_children")})

# Default lint surface: every module that builds, splits, serializes, or
# executes physical plans.
TARGET_DIRS = ("exec", "executor", "scheduler", "client", "obs", "parallel")
TARGET_FILES = (
    "distributed_plan.py",
    "serde.py",
    "standalone.py",
    "cli.py",
    "plugin.py",
)


@dataclasses.dataclass(frozen=True)
class EqDiagnostic:
    file: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


def _package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[1]


def target_files(paths=None) -> list[pathlib.Path]:
    if paths is not None:
        return [pathlib.Path(p) for p in paths]
    root = _package_root()
    out: list[pathlib.Path] = []
    for d in TARGET_DIRS:
        out.extend(sorted((root / d).glob("*.py")))
    for f in TARGET_FILES:
        p = root / f
        if p.exists():
            out.append(p)
    return out


def _suppressed(lines: list[str], fn_line: int | None, line: int) -> frozenset:
    out: set[str] = set()
    for ln in (fn_line, line):
        if ln is None or ln < 1 or ln > len(lines):
            continue
        m = _SUPPRESS_RE.search(lines[ln - 1])
        if m:
            out |= {t.strip() for t in m.group(1).split(",")}
    return frozenset(out)


class _FnCtx:
    """Per-function context: name, whether it is a constructor, and the
    local names assigned from QueryStage(...) constructions (the
    uncertified-stage-write receiver inference)."""

    def __init__(self, name: str, line: int):
        self.name = name
        self.line = line
        self.stage_locals: set[str] = set()


def _is_stage_receiver(value: ast.AST, ctx: _FnCtx | None) -> bool:
    """Receiver inference for ``<x>.plan = ...``: a Name locally bound to
    ``QueryStage(...)``, a subscript of something spelled ``.stages``
    (``job.stages[sid]``), or a call/attr chain ending in ``.stages``."""
    if isinstance(value, ast.Name):
        return ctx is not None and value.id in ctx.stage_locals
    if isinstance(value, ast.Subscript):
        v = value.value
        return isinstance(v, ast.Attribute) and v.attr == "stages"
    return False


def lint_source(
    source: str, filename: str = "<memory>"
) -> list[EqDiagnostic]:
    basename = pathlib.PurePath(filename).name
    if basename in SANCTIONED_FILES:
        return []
    tree = ast.parse(source, filename=filename)
    lines = source.splitlines()
    diags: list[EqDiagnostic] = []

    def emit(node: ast.AST, rule: str, msg: str, fn: _FnCtx | None) -> None:
        sup = _suppressed(lines, fn.line if fn else None, node.lineno)
        if rule in sup or "all" in sup:
            return
        diags.append(EqDiagnostic(filename, node.lineno, rule, msg))

    def check_target(target: ast.AST, node: ast.AST, fn: _FnCtx | None):
        if isinstance(target, (ast.Tuple, ast.List)):
            for t in target.elts:
                check_target(t, node, fn)
            return
        if not isinstance(target, ast.Attribute):
            return
        attr = target.attr
        recv = target.value
        in_ctor = (
            fn is not None
            and fn.name in ("__init__", "__post_init__")
            and isinstance(recv, ast.Name)
            and recv.id == "self"
        )
        sanctioned = fn is not None and (
            (basename, fn.name) in SANCTIONED_FUNCTIONS
        )
        if attr in CHILD_SLOTS or attr in STRUCT_FIELDS:
            if in_ctor or sanctioned:
                return
            emit(
                node,
                "uncertified-plan-write",
                f"direct write to structural plan field .{attr} — route "
                "through ballista_tpu.rewrite (certified rewrite ops) or "
                "construct a new node",
                fn,
            )
        elif attr == "plan" and _is_stage_receiver(recv, fn):
            if sanctioned:
                return
            emit(
                node,
                "uncertified-stage-write",
                "direct swap of a QueryStage plan template — the "
                "scheduler's certified-rewrite acceptance path "
                "(apply_certified_rewrite) is the sanctioned swap point",
                fn,
            )

    def walk(node: ast.AST, fn: _FnCtx | None) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            fn = _FnCtx(node.name, node.lineno)
        elif isinstance(node, ast.Assign):
            # stage-receiver inference: x = QueryStage(...) or
            # x = <y>.stages[...] (the scheduler's template lookup idiom)
            if fn is not None and (
                (
                    isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Name)
                    and node.value.func.id == "QueryStage"
                )
                # covers x = <y>.stages[...] (the Subscript branch of
                # the receiver inference) and stage-local aliasing
                or _is_stage_receiver(node.value, fn)
            ):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        fn.stage_locals.add(t.id)
            for t in node.targets:
                check_target(t, node, fn)
        elif isinstance(node, ast.AugAssign):
            check_target(node.target, node, fn)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            check_target(node.target, node, fn)
        for child in ast.iter_child_nodes(node):
            walk(child, fn)

    walk(tree, None)
    return diags


def lint_paths(paths=None) -> list[EqDiagnostic]:
    out: list[EqDiagnostic] = []
    root = _package_root().parent
    for f in target_files(paths):
        rel = str(f.relative_to(root)) if f.is_relative_to(root) else str(f)
        out.extend(lint_source(f.read_text(), rel))
    return out


def suppression_count(paths=None) -> int:
    n = 0
    for f in target_files(paths):
        n += len(_SUPPRESS_RE.findall(f.read_text()))
    return n
