"""The declared cache registry: every cache in the tree is a contract.

Cache-coherence bugs are this repo's dominant reactively-found family —
the join probe-LUT plan-cache self-poisoned on dictionary-keyed builds
(PR 15), mid-job adoption of learned strategies silently emptied q15
(PR 16's job-snapshot fix), and lost-shuffle recovery hinges on
remembering to invalidate resolved plan bytes. This module closes the
class the same way the compile vocabulary and the config registry closed
theirs: a cache may only exist if it is DECLARED here, with its key
composition, scope, coherence class, and invalidation sites written
down — and :mod:`ballista_tpu.analysis.stalelint` proves the tree
against the declarations.

Coherence classes (what makes a hit safe):

- ``versioned`` — the key folds in a version of every mutable input
  (e.g. the result cache folds ``_data_version()``); stale entries are
  unreachable by construction, invalidation is only an eviction policy.
- ``snapshot`` — readers see a frozen copy taken at a declared seam
  (e.g. ``Executor._job_snapshot``); reading the live state from a task
  path is the q15 bug shape and a stalelint error.
- ``immutable-keyed`` — the value for a key never changes once written
  (a committed shuffle partition, a jitted callable for a full trace
  signature); eviction is safe at any time, staleness is impossible.
- ``speculative-validated`` — entries are guesses that every consumer
  re-validates at use via the ``defer_speculation`` seam in
  ``exec/base.py`` (a miss invalidates the key and re-runs); writes must
  stay inside functions wired into that seam.

Anchors are ``"relative/path.py::Class.attr"`` (instance attribute),
``"relative/path.py::Class.attr"`` for dataclass fields, or
``"relative/path.py::GLOBAL"`` (module global).
:func:`verify_anchors` proves every declared anchor still resolves to a
real assignment in the tree, so the registry cannot rot into
aspirational documentation; the reverse direction — no cache in the
tree left undeclared — is stalelint's ``undeclared-cache`` rule.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib


@dataclasses.dataclass(frozen=True)
class CacheEntry:
    """One declared cache. ``seam``/``ok_calls`` only matter for
    ``snapshot``-class entries: ``seam`` names the functions allowed to
    touch the live anchor (the snapshot taker itself, ``__init__``), and
    ``ok_calls`` names callables the live anchor may be passed to from
    other code paths (persistence sinks that never influence results)."""

    name: str
    anchors: tuple[str, ...]
    keyed_by: str
    scope: str  # process | job | session | task
    coherence: str  # versioned | snapshot | immutable-keyed | speculative-validated
    invalidation: tuple[str, ...]
    seam: tuple[str, ...] = ()
    ok_calls: tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class Exempt:
    """A heuristic match that is NOT a cache of derived state (a source
    of truth, a metrics sink) — declared so stalelint's undeclared-cache
    rule stays a closed ledger instead of a fuzzy allowlist."""

    anchor: str
    reason: str


@dataclasses.dataclass(frozen=True)
class VersionSource:
    """A declared producer of data-version identity: the thing
    ``versioned`` cache keys must fold in and whose mutation sites carry
    invalidation contracts."""

    name: str
    anchor: str  # "relative/path.py::func" or "::Class.method"
    description: str


@dataclasses.dataclass(frozen=True)
class InvalidationContract:
    """Machine-checked: every ``mutators`` function in ``file`` must
    contain a call whose dotted name ends with each ``must_call`` suffix
    — stalelint's missing-invalidation rule. This is how "eager plan
    bytes are invalidated on rewrite acceptance" stops being a comment
    (scheduler/server.py JobInfo) and becomes a gate failure when the
    call is dropped."""

    source: str
    file: str
    mutators: tuple[str, ...]
    must_call: tuple[str, ...]
    caches: tuple[str, ...]


SCOPES = ("process", "job", "session", "task")
COHERENCE = (
    "versioned", "snapshot", "immutable-keyed", "speculative-validated"
)

CACHES: tuple[CacheEntry, ...] = (
    CacheEntry(
        name="exec-plan-cache",
        anchors=("ballista_tpu/exec/context.py::TpuContext._plan_cache",),
        keyed_by="plan-shape fact key (join fingerprint, LUT domain, "
        "capacity site)",
        scope="session",
        coherence="speculative-validated",
        invalidation=(
            "register_*/deregister_table/append_table clear it",
            "SpeculationMiss pops the invalid keys "
            "(exec/base.py run_with_capacity_retry)",
            "evict_plan_cache bounds it oldest-first",
        ),
    ),
    CacheEntry(
        name="physical-plan-cache",
        anchors=("ballista_tpu/exec/context.py::TpuContext._physical_cache",),
        keyed_by="logical-plan serde bytes + sorted session settings + "
        "_data_version()",
        scope="session",
        coherence="versioned",
        invalidation=(
            "register_*/deregister_table/append_table clear it",
            "128-entry wholesale clear in create_physical_plan",
        ),
    ),
    CacheEntry(
        name="exec-capacity-hint",
        anchors=("ballista_tpu/exec/context.py::TpuContext._capacity_hint",),
        keyed_by="'agg_capacity' (grow-only working capacity)",
        scope="session",
        coherence="speculative-validated",
        invalidation=(
            "never invalidated: values only grow and an overshoot only "
            "costs memory, not correctness (CapacityError re-grows)",
        ),
    ),
    CacheEntry(
        name="executor-plan-cache",
        anchors=("ballista_tpu/executor/executor.py::Executor._plan_cache",),
        keyed_by="plan-shape fact key, executor-lifetime across jobs",
        scope="process",
        coherence="snapshot",
        invalidation=(
            "task commits merge attempt caches back post-task",
            "evict_plan_cache bounds it oldest-first at commit",
        ),
        seam=("__init__", "_job_snapshot"),
        ok_calls=("save_if_changed", "load_once", "evict_plan_cache"),
    ),
    CacheEntry(
        name="executor-job-snapshots",
        anchors=(
            "ballista_tpu/executor/executor.py::Executor._job_snapshots",
        ),
        keyed_by="job_id -> frozen copy of executor-plan-cache at the "
        "job's first task (the q15 fix)",
        scope="job",
        coherence="snapshot",
        invalidation=("bounded FIFO (64 jobs); a job's entry is only "
                      "needed while its tasks run",),
        seam=("__init__", "_job_snapshot"),
    ),
    CacheEntry(
        name="executor-capacity-hint",
        anchors=(
            "ballista_tpu/executor/executor.py::Executor._capacity_hint",
        ),
        keyed_by="'agg_capacity' (grow-only working capacity)",
        scope="process",
        coherence="speculative-validated",
        invalidation=("never: grow-only, overflow re-grows via "
                      "CapacityError retry",),
    ),
    CacheEntry(
        name="trace-cache",
        anchors=("ballista_tpu/compilecache/tracecache.py::_CACHE",),
        keyed_by="full trace signature (kernel, shapes, dtypes, static "
        "args)",
        scope="process",
        coherence="immutable-keyed",
        invalidation=("LRU eviction at 1024 entries", "clear() in tests"),
    ),
    CacheEntry(
        name="plan-hints",
        anchors=(
            "ballista_tpu/exec/context.py::TpuContext._hints",
            "ballista_tpu/executor/executor.py::Executor._hints",
            "ballista_tpu/scheduler/aqe.py::StrategyStore._persist",
        ),
        keyed_by="plan-shape fact key, persisted across processes "
        "(compilecache/hints.py)",
        scope="process",
        coherence="speculative-validated",
        invalidation=(
            "stale persisted guesses are invalidated at use by the "
            "defer_speculation seam, then overwritten by save_if_changed",
            "4096-entry bound at save",
        ),
    ),
    CacheEntry(
        name="aqe-strategy-store",
        anchors=("ballista_tpu/scheduler/aqe.py::StrategyStore._cache",),
        keyed_by="('aqe'|'aqe_deny', query_class) -> learned rewrite "
        "specs",
        scope="process",
        coherence="speculative-validated",
        invalidation=(
            "unlearn+deny on certificate rejection (self-healing)",
            "load_once prunes non-aqe keys",
        ),
    ),
    CacheEntry(
        name="result-cache",
        anchors=(
            "ballista_tpu/scheduler/result_cache.py::ResultCache._entries",
            "ballista_tpu/scheduler/server.py::SchedulerServer.result_cache",
        ),
        keyed_by="logical-plan serde bytes + sorted session settings + "
        "provider._data_version()",
        scope="process",
        coherence="versioned",
        invalidation=(
            "byte-bounded LRU eviction",
            "in-memory only: a restarted scheduler starts cold",
        ),
    ),
    CacheEntry(
        name="resolved-plan-bytes",
        anchors=(
            "ballista_tpu/scheduler/server.py::JobInfo.resolved_plan_bytes",
        ),
        keyed_by="stage id -> shuffle-patched serialized plan (locations "
        "baked in)",
        scope="job",
        coherence="versioned",
        invalidation=(
            "_on_shuffle_lost pops every consumer of the lost producer",
            "apply_certified_rewrite pops every touched/removed stage",
        ),
    ),
    CacheEntry(
        name="eager-plan-bytes",
        anchors=(
            "ballista_tpu/scheduler/server.py::JobInfo.eager_plan_bytes",
        ),
        keyed_by="stage id -> eager resolution (location-free, template-"
        "derived only)",
        scope="job",
        coherence="versioned",
        invalidation=(
            "apply_certified_rewrite pops every touched/removed stage "
            "(the only event that changes a template; lost-shuffle "
            "recovery cannot stale these — readers poll locations)",
        ),
    ),
    CacheEntry(
        name="push-registry",
        anchors=("ballista_tpu/executor/push.py::REGISTRY",),
        keyed_by="(job, stage, map task, partition) -> committed pushed "
        "batches",
        scope="process",
        coherence="immutable-keyed",
        invalidation=(
            "window-bounded with atomic spill fallback",
            "job teardown drops the job's streams",
        ),
    ),
    CacheEntry(
        name="flight-pool",
        anchors=("ballista_tpu/client/flight.py::_POOL",),
        keyed_by="(host, port) -> live FlightClient",
        scope="process",
        coherence="immutable-keyed",
        invalidation=(
            "_evict on transport error (ownership to GC)",
            "close_pool() at shutdown",
        ),
    ),
    CacheEntry(
        name="jit-program-memo",
        anchors=(
            "ballista_tpu/exec/aggregate.py::_ones_program",
            "ballista_tpu/exec/aggregate.py::_dec_learn_program",
            "ballista_tpu/exec/aggregate.py::_dec_scale_program",
            "ballista_tpu/exec/aggregate.py::_dec_unscale_program",
            "ballista_tpu/exec/aggregate.py::_bounds_program",
            "ballista_tpu/exec/aggregate.py::_boundary_merge_program",
            "ballista_tpu/exec/aggregate.py::_state_batch_program",
            "ballista_tpu/exec/aggregate.py::HashAggregateExec._jit_cache",
            "ballista_tpu/exec/joins.py::_jit_probe",
            "ballista_tpu/exec/joins.py::_jit_counts",
            "ballista_tpu/exec/joins.py::_jit_expand_total",
            "ballista_tpu/exec/percentile.py::_pct_program",
            "ballista_tpu/exec/repartition.py::_jit_mask_partition",
            "ballista_tpu/exec/repartition.py::jit_partition_ids",
            "ballista_tpu/exec/shrink.py::_shrink_program",
            "ballista_tpu/exec/sort.py::_fetch_program",
            "ballista_tpu/exec/window.py::_rank_program",
            "ballista_tpu/exec/window.py::_agg_window_program",
            "ballista_tpu/ops/aggregate.py::_zeroed_program",
            "ballista_tpu/ops/aggregate.py::_not_program",
            "ballista_tpu/ops/compact.py::_invalid_program",
            "ballista_tpu/ops/compact.py::_front_valid_program",
            "ballista_tpu/ops/fetch.py::_concat_program",
            "ballista_tpu/ops/fetch.py::_f64_concat_program",
            "ballista_tpu/ops/join.py::_build_prep_program",
            "ballista_tpu/ops/join.py::_exact2_range_program",
            "ballista_tpu/ops/join.py::_lut_program",
            "ballista_tpu/ops/pallas_agg.py::available",
            "ballista_tpu/ops/pallas_agg.py::_program",
            "ballista_tpu/ops/perm.py::_argsort_program",
            "ballista_tpu/ops/perm.py::_take_program",
            "ballista_tpu/ops/perm.py::_take_batch_program",
        ),
        keyed_by="full program signature (shapes, dtypes, capacities, "
        "static flags) — pure function of the key",
        scope="process",
        coherence="immutable-keyed",
        invalidation=(
            "none needed: values are deterministic functions of their "
            "full signature (the closed compile vocabulary is the "
            "companion gate — compilecache/registry.py)",
        ),
    ),
    CacheEntry(
        name="join-build-cache",
        anchors=("ballista_tpu/exec/joins.py::HashJoinExec._build_cache",),
        keyed_by="build-side plan fingerprint (+ LUT domain keys); the "
        "instance dies with its versioned physical plan, so a data "
        "change can never reuse it",
        scope="session",
        coherence="immutable-keyed",
        invalidation=(
            "HBM admission via the shared __build_cache_bytes__ tally",
            "instance-scoped: physical-plan-cache clears retire it",
        ),
    ),
    CacheEntry(
        name="dict-hash-cache",
        anchors=("ballista_tpu/ops/partition.py::_dict_hash_cache",),
        keyed_by="tuple of dictionary strings -> stable 64-bit hashes "
        "(deterministic pure function of the key)",
        scope="process",
        coherence="immutable-keyed",
        invalidation=("none needed: value is a pure function of the "
                      "key",),
    ),
    CacheEntry(
        name="capacity-ladder",
        anchors=("ballista_tpu/columnar/batch.py::_LADDER",),
        keyed_by="configured bucket spec -> rounded capacities",
        scope="process",
        coherence="versioned",
        invalidation=("set_capacity_buckets reinstalls the ladder when "
                      "the session spec changes",),
    ),
)

EXEMPT: tuple[Exempt, ...] = (
    Exempt(
        "ballista_tpu/obs/hist.py::REGISTRY",
        "metrics registry: a sink of observations, not derived state "
        "that can go stale against a source",
    ),
    Exempt(
        "ballista_tpu/client/flight.py::_POOL_TOKENS",
        "reswitness bookkeeping riding the flight pool, keyed 1:1 with "
        "_POOL and maintained at the same sites",
    ),
    Exempt(
        "ballista_tpu/exec/context.py::TpuContext._local_history",
        "HistoryStore is the append-only query log — a source of truth, "
        "not derived state",
    ),
    Exempt(
        "ballista_tpu/scheduler/server.py::SchedulerServer.history",
        "HistoryStore is the append-only query log — a source of truth, "
        "not derived state",
    ),
    Exempt(
        "ballista_tpu/scheduler/server.py::SchedulerServer.hists",
        "obs histogram registry: a sink of observations, not derived "
        "state that can go stale against a source",
    ),
    Exempt(
        "ballista_tpu/scheduler/aqe.py::StrategyStore._hint",
        "empty scalar-hint placeholder required by the HintStore API "
        "shape; never read",
    ),
    Exempt(
        "ballista_tpu/plugin.py::global_registry",
        "UDF plugin registry: the source of truth for registered "
        "functions, not derived state",
    ),
)

VERSION_SOURCES: tuple[VersionSource, ...] = (
    VersionSource(
        name="data-version",
        anchor="ballista_tpu/exec/context.py::TpuContext._data_version",
        description="registered-data signature (memory-table identity + "
        "rows, file mtimes); the version every versioned cache key over "
        "table data must fold in",
    ),
    VersionSource(
        name="job-snapshot-seam",
        anchor="ballista_tpu/executor/executor.py::Executor._job_snapshot",
        description="the ONLY sanctioned read of live learned strategies "
        "from the task path: a frozen per-job copy (q15 fix)",
    ),
)

# Machine-checked invalidation contracts (stalelint rule 2). Every
# mutator of a version source must reach the declared invalidation call
# of every dependent cache — drop a ``.clear()``/``.pop()`` and the gate
# goes red.
CONTRACTS: tuple[InvalidationContract, ...] = (
    InvalidationContract(
        source="registered-data",
        file="ballista_tpu/exec/context.py",
        mutators=(
            "register_table", "register_csv", "register_parquet",
            "register_avro", "deregister_table",
        ),
        must_call=("_plan_cache.clear", "_physical_cache.clear"),
        caches=("exec-plan-cache", "physical-plan-cache"),
    ),
    InvalidationContract(
        source="registered-data-append",
        file="ballista_tpu/exec/context.py",
        mutators=("append_table",),
        # append routes through register_table to inherit its contract
        must_call=("register_table",),
        caches=("exec-plan-cache", "physical-plan-cache"),
    ),
    InvalidationContract(
        source="executor-loss",
        file="ballista_tpu/scheduler/server.py",
        mutators=("_on_shuffle_lost",),
        must_call=("resolved_plan_bytes.pop",),
        caches=("resolved-plan-bytes",),
    ),
    InvalidationContract(
        source="rewrite-acceptance",
        file="ballista_tpu/scheduler/server.py",
        mutators=("apply_certified_rewrite",),
        must_call=("resolved_plan_bytes.pop", "eager_plan_bytes.pop"),
        caches=("resolved-plan-bytes", "eager-plan-bytes"),
    ),
)


def _package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def anchor_index() -> dict[str, str]:
    """anchor -> declared name ('!exempt' entries use the reason ledger
    separately); duplicate anchors are a registry bug caught here."""
    idx: dict[str, str] = {}
    for e in CACHES:
        for a in e.anchors:
            assert a not in idx, f"anchor declared twice: {a}"
            idx[a] = e.name
    for x in EXEMPT:
        assert x.anchor not in idx, f"anchor declared twice: {x.anchor}"
        idx[x.anchor] = "!exempt"
    return idx


def entry(name: str) -> CacheEntry:
    for e in CACHES:
        if e.name == name:
            return e
    raise KeyError(name)


def _resolve_anchor(tree: ast.Module, qual: str) -> bool:
    """Does ``qual`` ('Class.attr', 'Class.method', 'GLOBAL', 'func')
    resolve to a real assignment/def in ``tree``?"""
    parts = qual.split(".")
    if len(parts) == 1:
        name = parts[0]
        for node in tree.body:
            if isinstance(node, ast.FunctionDef) and node.name == name:
                return True
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign):
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == name:
                    return True
        return False
    cls_name, attr = parts
    for node in tree.body:
        if not (isinstance(node, ast.ClassDef) and node.name == cls_name):
            continue
        for sub in ast.walk(node):
            if isinstance(sub, ast.FunctionDef) and sub.name == attr:
                return True
            targets = []
            if isinstance(sub, ast.Assign):
                targets = sub.targets
            elif isinstance(sub, ast.AnnAssign):
                targets = [sub.target]
            for t in targets:
                # dataclass field: bare Name in the class body
                if isinstance(t, ast.Name) and t.id == attr:
                    return True
                # instance attribute: self.<attr> = ...
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == attr
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    return True
    return False


def verify_anchors() -> list[str]:
    """Every declared anchor (caches, exemptions, version sources) must
    resolve against the live tree — a renamed attribute goes red here,
    not silently stale in the docs."""
    root = _package_root()
    problems: list[str] = []
    trees: dict[str, ast.Module] = {}

    def tree_for(rel: str) -> ast.Module | None:
        if rel not in trees:
            path = root / rel
            if not path.exists():
                return None
            trees[rel] = ast.parse(path.read_text(), filename=rel)
        return trees[rel]

    anchors = [(a, e.name) for e in CACHES for a in e.anchors]
    anchors += [(x.anchor, "exempt") for x in EXEMPT]
    anchors += [(v.anchor, v.name) for v in VERSION_SOURCES]
    for anchor, owner in anchors:
        rel, _, qual = anchor.partition("::")
        t = tree_for(rel)
        if t is None:
            problems.append(f"{owner}: anchor file missing: {rel}")
        elif not _resolve_anchor(t, qual):
            problems.append(
                f"{owner}: anchor does not resolve: {anchor} "
                "(renamed attribute? update analysis/cachereg.py)"
            )
    for e in CACHES:
        if e.scope not in SCOPES:
            problems.append(f"{e.name}: unknown scope {e.scope!r}")
        if e.coherence not in COHERENCE:
            problems.append(f"{e.name}: unknown coherence {e.coherence!r}")
    for c in CONTRACTS:
        for name in c.caches:
            try:
                entry(name)
            except KeyError:
                problems.append(
                    f"contract {c.source}: unknown cache {name!r}"
                )
    return problems


def render_inventory() -> str:
    """The cache inventory as a markdown table — embedded verbatim in
    docs/analysis.md and checked by the gate (docs_in_sync), the same
    generated-docs discipline as docs/config.md."""
    lines = [
        "| cache | scope | coherence | keyed by | invalidation |",
        "|---|---|---|---|---|",
    ]
    for e in CACHES:
        inval = "; ".join(e.invalidation)
        lines.append(
            f"| `{e.name}` | {e.scope} | {e.coherence} | {e.keyed_by} "
            f"| {inval} |"
        )
    return "\n".join(lines)


def docs_path() -> pathlib.Path:
    return _package_root() / "docs" / "analysis.md"


def docs_in_sync() -> str | None:
    """None when docs/analysis.md embeds the generated inventory table
    verbatim, else the failure message."""
    try:
        text = docs_path().read_text()
    except OSError as e:
        return f"docs/analysis.md unreadable: {e}"
    if render_inventory() not in text:
        return (
            "docs/analysis.md cache inventory is out of sync with "
            "analysis/cachereg.py (paste render_inventory() output)"
        )
    return None
