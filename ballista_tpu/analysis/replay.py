"""Runtime replay witness: bit-exactness as a checkable invariant.

The fault-tolerance story promises "bit-exact under fault injection"
(docs/fault_tolerance.md), and the certified-rewrite API promises
semantics preservation (ballista_tpu/rewrite.py) — but until now both
were only ASSERTED by individual chaos tests comparing final tables.
This witness turns the promise into a first-class runtime invariant, the
replay analogue of the lock witness and the resource witness:

- every COMMITTED ``(job, stage, map task, output partition)`` shuffle
  output records a content hash at the producing executor
  (``Executor.execute_shuffle_write``), and
- every final result partition records one at the client fetch
  (``_fetch_results``).

Recording the same key twice — a bounded task retry, a lineage recompute
after an executor kill, eager-vs-barriered consumption feeding the same
downstream stage, a certified rewrite re-running a stage — must produce
the identical hash; a differing hash is a MISMATCH the test harness
fails on (:func:`assert_clean`).

Hashing is **canonical**: the partition's batches are concatenated,
sorted by every column, and serialized through uncompressed Arrow IPC
before hashing. That makes the hash invariant under the re-orderings
that are legitimately allowed to differ (batch boundaries, IPC
compression codec, fetch concurrency, row order permuted by a certified
rewrite such as a build-side flip) while any value-level divergence —
lost rows, duplicated rows, last-ULP float drift from a merge-order bug
— changes it with overwhelming probability. Note what this deliberately
checks: multiset equality of row values, the equivalence certified
rewrites actually promise.

Bucket-count-changing rewrites (coalesce/split/broadcast) legitimately
change per-key content; the scheduler's acceptance path calls
:func:`forget_stage` for exactly those stages (the certificate's
``bucket_changed_stages``), so the witness never compares across a
re-bucketing.

Default OFF: ``BALLISTA_REPLAY_WITNESS=1`` in the environment or
:func:`enable` — every instrumentation point is a single flag check, and
the hash work (a read-back of the just-written file) only happens when
enabled."""

from __future__ import annotations

import hashlib
import logging
import os
import threading

ENV_WITNESS = "BALLISTA_REPLAY_WITNESS"

log = logging.getLogger(__name__)

_enabled = os.environ.get(ENV_WITNESS, "") in ("1", "true", "yes")

_lock = threading.Lock()
_hashes: dict[tuple, str] = {}
_mismatches: list[dict] = []
# lifetime record counts per kind: "zero mismatches" must never silently
# mean "zero records" (same diagnostic stance as reswitness)
_records: dict[str, int] = {}
_rehashes = 0  # same-key re-records that MATCHED (retries proven equal)


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = flag


def enabled() -> bool:
    return _enabled


def canonical_hash(table) -> str:
    """Order-canonical content hash of an Arrow table: combine chunks,
    sort by every column (total order up to exact duplicate rows),
    serialize through uncompressed IPC, sha256. Schema (names + dtypes)
    rides in the IPC stream, so a schema drift also changes the hash."""
    import pyarrow as pa
    import pyarrow.ipc as paipc

    table = table.combine_chunks()
    if table.num_rows:
        table = table.sort_by([(n, "ascending") for n in table.schema.names])
    sink = pa.BufferOutputStream()
    with paipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return hashlib.sha256(sink.getvalue()).hexdigest()


def hash_file(path: str) -> str:
    """Canonical hash of one shuffle IPC file. A path that was never
    created (a zero-row partition writes no file) hashes as the stable
    ``"empty"`` marker — absent-both-times still compares equal across
    retries, and absent-vs-present is a mismatch."""
    import pyarrow.ipc as paipc

    if not os.path.exists(path):
        return "empty"
    with paipc.open_file(path) as r:
        return canonical_hash(r.read_all())


def record(kind: str, key: tuple, digest: str) -> None:
    """Record one content hash; a same-key re-record with a different
    digest is a mismatch (kept, counted, logged — assert_clean fails on
    it)."""
    global _rehashes
    full = (kind,) + tuple(key)
    with _lock:
        _records[kind] = _records.get(kind, 0) + 1
        prev = _hashes.get(full)
        if prev is None:
            _hashes[full] = digest
            return
        if prev == digest:
            _rehashes += 1
            return
        _mismatches.append({"key": full, "expected": prev, "got": digest})
    log.error(
        "replay witness MISMATCH at %s: %s != %s", full, prev, digest
    )


def forget_stage(job_id: str, stage_id: int) -> None:
    """Drop every recorded hash of one stage's shuffle output — called by
    the scheduler when a certified rewrite changes the stage's bucket
    count (per-bucket content then legitimately differs)."""
    with _lock:
        for k in [
            k
            for k in _hashes
            if k[0] == "shuffle" and k[1] == job_id and k[2] == stage_id
        ]:
            del _hashes[k]


def mismatches() -> list[dict]:
    with _lock:
        return [dict(m) for m in _mismatches]


def record_counts() -> dict[str, int]:
    with _lock:
        return dict(_records)


def rehash_count() -> int:
    """Same-key re-records that MATCHED — the count of retries /
    recomputes / rewrites the witness actually proved bit-exact."""
    with _lock:
        return _rehashes


def snapshot(strip_job: bool = False) -> dict[tuple, str]:
    """The recorded hash map; ``strip_job=True`` drops the job-id
    component so independent runs of the same query (each its own job)
    can be compared key-for-key — the cross-config property tests'
    comparison form."""
    with _lock:
        if not strip_job:
            return dict(_hashes)
        return {(k[0],) + k[2:]: v for k, v in _hashes.items()}


def summary() -> str:
    counts = record_counts()
    mm = mismatches()
    head = (
        f"{sum(counts.values())} hashes recorded ("
        + ", ".join(f"{k}:{n}" for k, n in sorted(counts.items()))
        + f"), {rehash_count()} re-records matched"
    )
    if not mm:
        return head + ", 0 mismatches"
    return head + f", {len(mm)} MISMATCHES: " + "; ".join(
        str(m["key"]) for m in mm
    )


def assert_clean(require_records: bool = True) -> None:
    """Zero mismatches (and, by default, a nonzero record count — a
    witness that saw no traffic proves nothing)."""
    mm = mismatches()
    if mm:
        lines = [
            f"{m['key']}: expected {m['expected']}, got {m['got']}"
            for m in mm
        ]
        raise AssertionError(
            f"{len(mm)} replay-witness hash mismatches:\n" + "\n".join(lines)
        )
    if require_records and not record_counts():
        raise AssertionError(
            "replay witness recorded nothing — enable() before the run, "
            "or the instrumentation points were never reached"
        )


def reset() -> None:
    global _rehashes
    with _lock:
        _hashes.clear()
        _mismatches.clear()
        _records.clear()
        _rehashes = 0
