"""lifelint: resource-lifecycle & error-taxonomy static analysis.

Ballista's reliability story rests on executors that persist shuffle
state, serve it over Flight, and get killed/restarted at will — which
only works if every channel, thread pool, file, mmap and spill set has a
provable owner, and every error that crosses the task boundary is
classified correctly (``errors.error_is_retryable`` decides whether a
failed task burns a bounded retry or the whole job). planlint proved
plans (PR 2) and racelint proved locks (PR 4); lifelint is the same
verify-before-run posture for *lifecycle* and *error propagation* — the
discipline Rust's ownership/borrow checker gives the reference
implementation for free.

Rule families (AST-based, import-free over the source tree):

==================== ========================================================
rule                 rationale
==================== ========================================================
leaked-resource      A resource acquisition (gRPC channel, Flight client,
                     thread/pool, open file, ``pa.memory_map``, IPC writer,
                     SpillManager, gRPC server) with no provable owner: not
                     ``with``-managed, never released, and never handed off
                     (returned/yielded, stored into an owning class with a
                     releasing method, stored into a container, or passed to
                     a class that releases it). Class-held resources
                     (``self.x = ctor()``) require a method of that class to
                     release ``self.x`` (directly or through a local alias).
leak-on-error        The release exists but only on the straight-line path:
                     an exception (or, in a generator, consumer abandonment
                     — ``GeneratorExit`` — while suspended at a ``yield``)
                     skips it. Releases must sit in a ``finally`` (or the
                     acquisition in a ``with``) whenever anything between
                     acquire and release can raise.
unclassified-raise   A ``raise`` in the task-boundary surfaces (executor/,
                     exec/, client/, scheduler/) of an exception type that
                     maps into neither ``errors.NON_RETRYABLE_ERROR_TYPES``
                     nor ``errors.RETRYABLE_ERROR_TYPES``. Task errors cross
                     the wire as "TypeName: message" strings; an unlisted
                     type silently defaults to *retryable*, so a
                     deterministic failure would burn every bounded attempt
                     before failing the job.
swallowed-error      A bare ``except:`` — or an ``except Exception/
                     BaseException:`` handler that neither re-raises nor
                     logs — silently discards a failure. Exempt: the
                     close-suppression idiom (a ``try`` body consisting only
                     of release calls, where failure to close is the
                     expected case being suppressed).
untyped-injection    A handler catching a fault-injection type
                     (``Injected*``) that does not re-raise: chaos faults
                     must surface through the SAME typed error paths real
                     faults take, or the chaos suite proves nothing about
                     production error flow.
==================== ========================================================

Ownership-transfer annotation: append ``# lifelint: transfer`` to an
acquisition line whose ownership moves somewhere the analysis cannot see
(e.g. a fire-and-forget worker bounded by a semaphore, or the
executor-injected ``TaskContext.shuffle_locations`` hand-off). Transfers
are declared design facts, not suppressions, and are listed by
``transfer_sites()`` — but keep them rare and commented.

Suppression: append ``# lifelint: disable=<rule>[,<rule>...]`` (or
``disable=all``) to the offending line or the enclosing ``def`` line.
The tier-1 suite budgets suppressions at ≤ 5 tree-wide (shared across
rule families, like racelint's).

Scope/limitations (deliberate): acquisition tracking is function-local
with one level of alias (``y = x``) and factory propagation (a function
whose returns are all fresh resources is itself an acquisition site);
resources passed to arbitrary calls are treated as *shared*, not
transferred — only constructors of locally-defined classes that provably
release the stored attribute count as transfer sinks. Locks are covered
by racelint/witness, not here; bounded queues and per-location fetch
queues are covered by the runtime witness
(:mod:`ballista_tpu.analysis.reswitness`), which this module's static
rules complement.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

RULES: dict[str, str] = {
    "leaked-resource": "resource acquisition (channel/client/pool/thread/"
    "file/mmap/spill) with no provable owner: never released and never "
    "handed off to something that releases it",
    "leak-on-error": "release only on the straight-line path — an "
    "exception edge (or generator cancellation at a yield) skips it; "
    "use with/finally",
    "unclassified-raise": "raised exception type missing from the "
    "errors.py retryable/non-retryable taxonomy — it would silently "
    "default to retryable at the task boundary",
    "swallowed-error": "bare except (or except Exception) that neither "
    "re-raises nor logs — failures vanish",
    "untyped-injection": "fault-injection handler (Injected*) that does "
    "not re-raise typed — chaos faults must take the production error "
    "path",
}

_SUPPRESS_RE = re.compile(r"#\s*lifelint:\s*disable=([A-Za-z0-9_,\- ]+)")
_TRANSFER_RE = re.compile(r"#\s*lifelint:\s*transfer\b(?:=(\S+))?")

# resource constructors: dotted call name -> (kind, release-method names)
_RESOURCE_CTORS: dict[str, tuple[str, tuple[str, ...]]] = {
    "grpc.insecure_channel": ("grpc-channel", ("close",)),
    "_grpc.insecure_channel": ("grpc-channel", ("close",)),
    "grpc.secure_channel": ("grpc-channel", ("close",)),
    "grpc.server": ("grpc-server", ("stop",)),
    "paflight.connect": ("flight-client", ("close",)),
    "flight.connect": ("flight-client", ("close",)),
    "paflight.FlightClient": ("flight-client", ("close",)),
    "ThreadPoolExecutor": ("thread-pool", ("shutdown",)),
    "futures.ThreadPoolExecutor": ("thread-pool", ("shutdown",)),
    "concurrent.futures.ThreadPoolExecutor": ("thread-pool", ("shutdown",)),
    "threading.Thread": ("thread", ("join",)),
    "open": ("file", ("close",)),
    "pa.OSFile": ("file", ("close",)),
    "pa.memory_map": ("mmap", ("close",)),
    "paipc.new_file": ("ipc-writer", ("close",)),
    "pa.ipc.new_file": ("ipc-writer", ("close",)),
    "paipc.open_file": ("ipc-reader", ("close",)),
    "pa.ipc.open_file": ("ipc-reader", ("close",)),
    "SpillManager": ("spill-manager", ("close",)),
}

# any of these discharges the obligation for its kind (a close method may
# legitimately be named stop/shutdown/join on wrappers)
_RELEASE_METHODS = frozenset(
    {"close", "shutdown", "join", "stop", "cancel", "terminate", "release"}
)

# calls that take OWNERSHIP of an argument resource (the wrapper releases
# the inner resource with itself, or manages it as a context). NOTE
# pyarrow's ``ipc.open_file``/``open_stream`` are deliberately NOT here:
# the returned reader has no ``close()`` and its ``with`` is a no-op — it
# never closes the source file/mmap you hand it (the PR 8 reader.py leak).
_TRANSFER_SINKS = frozenset(
    {
        "contextlib.closing",
        "closing",
        "enter_context",  # ExitStack
        "grpc.server",  # the server drives its worker pool's lifetime
    }
)

# container-mutator method names: `xs.append(res)` stores the resource in
# an owned collection — ownership moved to the container's owner
_CONTAINER_MUTATORS = frozenset(
    {"append", "add", "insert", "extend", "put", "put_nowait",
     "setdefault", "register", "appendleft"}
)

_EXC_BASES = frozenset({"Exception", "BaseException"})


@dataclasses.dataclass(frozen=True)
class LifeDiagnostic:
    file: str
    line: int
    rule: str
    message: str
    function: str = ""

    def __str__(self) -> str:
        where = f" [{self.function}]" if self.function else ""
        return f"{self.file}:{self.line}: {self.rule}{where}: {self.message}"


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _terminal(name: str | None) -> str:
    return (name or "").split(".")[-1]


def _ctor_kind(call: ast.Call) -> tuple[str, tuple[str, ...]] | None:
    d = _dotted(call.func)
    if d is None:
        return None
    hit = _RESOURCE_CTORS.get(d)
    if hit is None:
        # unqualified class name fallback (from-imports): match terminal
        hit = _RESOURCE_CTORS.get(_terminal(d)) if "." in d else None
    return hit


# --------------------------------------------------------------------------
# module model
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _ClassInfo:
    name: str
    file: str
    node: ast.ClassDef
    methods: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict
    )
    # attr -> (kind, line) for self.attr = <resource ctor>
    resource_attrs: dict[str, tuple[str, int]] = dataclasses.field(
        default_factory=dict
    )
    # attrs with release evidence (self.attr.close() or alias release)
    released_attrs: set[str] = dataclasses.field(default_factory=set)
    # __init__ params stored to self attrs: param name -> attr
    init_stores: dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _ModuleInfo:
    name: str
    file: str
    tree: ast.Module
    lines: list[str]
    classes: dict[str, _ClassInfo] = dataclasses.field(default_factory=dict)
    functions: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict
    )


def _collect_module(source: str, filename: str) -> _ModuleInfo:
    tree = ast.parse(source, filename=filename)
    mi = _ModuleInfo(
        pathlib.Path(filename).stem, filename, tree, source.splitlines()
    )
    for node in tree.body:
        if isinstance(node, ast.FunctionDef):
            mi.functions[node.name] = node
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(node.name, filename, node)
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    ci.methods[item.name] = item
            mi.classes[ci.name] = ci
    return mi


def _suppressed(mi: _ModuleInfo, fn_line: int, line: int) -> frozenset:
    out: set[str] = set()
    for ln in (line, fn_line):
        if 0 < ln <= len(mi.lines):
            m = _SUPPRESS_RE.search(mi.lines[ln - 1])
            if m:
                out.update(p.strip() for p in m.group(1).split(","))
    return frozenset(out)


def _transfer_note(mi: _ModuleInfo, line: int) -> str | None:
    """The ``# lifelint: transfer[=note]`` annotation on ``line``, if any
    (a declared ownership hand-off, not a suppression)."""
    if 0 < line <= len(mi.lines):
        m = _TRANSFER_RE.search(mi.lines[line - 1])
        if m:
            return m.group(1) or "declared"
    return None


# --------------------------------------------------------------------------
# resource-lifecycle analysis
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _Acq:
    """One tracked acquisition inside a function."""

    kind: str
    releases: tuple[str, ...]
    line: int
    node: ast.Call | None = None
    var: str | None = None  # local name when assigned to one
    self_attr: str | None = None  # self.<attr> when stored directly
    with_managed: bool = False
    discharged: bool = False  # escaped to an owner
    release_lines: list[tuple[int, bool]] = dataclasses.field(
        default_factory=list
    )  # (line, in_finally)


class _Analysis:
    def __init__(self, modules: list[_ModuleInfo]):
        self.modules = modules
        self.classes: dict[str, _ClassInfo] = {}
        for m in modules:
            for c in m.classes.values():
                self.classes.setdefault(c.name, c)
        self._collect_class_facts()
        # factory fixpoint: functions/methods whose returns are all fresh
        # resources become acquisition sites themselves
        self.factories: dict[str, tuple[str, tuple[str, ...]]] = {}
        for _round in range(2):
            for mi in self.modules:
                for fn in mi.functions.values():
                    self._maybe_factory(fn)
                for ci in mi.classes.values():
                    for meth in ci.methods.values():
                        self._maybe_factory(meth)
        # sink classes: ctor takes ownership of resource args because the
        # class releases what it stores
        self.sink_classes: set[str] = set()
        for ci in self.classes.values():
            if ci.released_attrs or any(
                m in ci.methods for m in ("close", "stop", "shutdown",
                                          "__exit__")
            ):
                self.sink_classes.add(ci.name)

    # -- class facts --------------------------------------------------------
    def _collect_class_facts(self) -> None:
        for mi in self.modules:
            for ci in mi.classes.values():
                init = ci.methods.get("__init__")
                if init is not None:
                    params = {
                        a.arg for a in init.args.args + init.args.kwonlyargs
                    }
                    for sub in ast.walk(init):
                        if (
                            isinstance(sub, ast.Assign)
                            and len(sub.targets) == 1
                            and isinstance(sub.targets[0], ast.Attribute)
                            and isinstance(sub.targets[0].value, ast.Name)
                            and sub.targets[0].value.id == "self"
                            and isinstance(sub.value, ast.Name)
                            and sub.value.id in params
                        ):
                            ci.init_stores[sub.value.id] = (
                                sub.targets[0].attr
                            )
                for meth in ci.methods.values():
                    self._release_evidence(meth, ci)

    def _release_evidence(self, meth: ast.FunctionDef, ci: _ClassInfo):
        """Record self-attrs this method provably releases: direct
        ``self.x.close()`` or via a local alias (incl. tuple swaps like
        ``pool, self._pool = self._pool, None``)."""
        aliases: dict[str, str] = {}
        for sub in ast.walk(meth):
            if isinstance(sub, ast.Assign):
                tgts, vals = sub.targets, [sub.value]
                if (
                    len(tgts) == 1
                    and isinstance(tgts[0], ast.Tuple)
                    and isinstance(sub.value, ast.Tuple)
                    and len(tgts[0].elts) == len(sub.value.elts)
                ):
                    tgts, vals = tgts[0].elts, sub.value.elts
                elif len(tgts) == 1:
                    tgts = [tgts[0]]
                for t, v in zip(tgts, vals * len(tgts) if len(vals) == 1
                                else vals):
                    if (
                        isinstance(t, ast.Name)
                        and isinstance(v, ast.Attribute)
                        and isinstance(v.value, ast.Name)
                        and v.value.id == "self"
                    ):
                        aliases[t.id] = v.attr
            elif isinstance(sub, ast.Call) and isinstance(
                sub.func, ast.Attribute
            ):
                if sub.func.attr not in _RELEASE_METHODS:
                    continue
                recv = sub.func.value
                if (
                    isinstance(recv, ast.Attribute)
                    and isinstance(recv.value, ast.Name)
                    and recv.value.id == "self"
                ):
                    ci.released_attrs.add(recv.attr)
                elif isinstance(recv, ast.Name) and recv.id in aliases:
                    ci.released_attrs.add(aliases[recv.id])
        # `for t in self._threads: t.join()` — loop-variable alias
        for sub in ast.walk(meth):
            if (
                isinstance(sub, ast.For)
                and isinstance(sub.target, ast.Name)
                and isinstance(sub.iter, ast.Attribute)
                and isinstance(sub.iter.value, ast.Name)
                and sub.iter.value.id == "self"
            ):
                var, attr = sub.target.id, sub.iter.attr
                for inner in ast.walk(sub):
                    if (
                        isinstance(inner, ast.Call)
                        and isinstance(inner.func, ast.Attribute)
                        and inner.func.attr in _RELEASE_METHODS
                        and isinstance(inner.func.value, ast.Name)
                        and inner.func.value.id == var
                    ):
                        ci.released_attrs.add(attr)

    # -- factory detection --------------------------------------------------
    def _returned_resource(
        self, expr: ast.AST
    ) -> tuple[str, tuple[str, ...]] | None:
        if not isinstance(expr, ast.Call):
            return None
        hit = _ctor_kind(expr)
        if hit is not None:
            return hit
        d = _terminal(_dotted(expr.func))
        return self.factories.get(d)

    def _maybe_factory(self, fn: ast.FunctionDef) -> None:
        returns = [
            n for n in ast.walk(fn)
            if isinstance(n, ast.Return) and n.value is not None
        ]
        if not returns:
            return
        kinds = [self._returned_resource(r.value) for r in returns]
        if all(k is not None for k in kinds) and kinds:
            self.factories[fn.name] = kinds[0]


def _nested_defs(fn: ast.FunctionDef) -> set[ast.AST]:
    """All nodes belonging to nested function/lambda bodies (excluded from
    the enclosing function's walk; nested defs are checked separately)."""
    out: set[ast.AST] = set()
    for node in ast.walk(fn):
        if node is fn:
            continue
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            for sub in ast.walk(node):
                if sub is not node:
                    out.add(sub)
    return out


def _finally_nodes(fn: ast.FunctionDef) -> set[ast.AST]:
    out: set[ast.AST] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Try):
            for stmt in node.finalbody:
                for sub in ast.walk(stmt):
                    out.add(sub)
    return out


def _check_resources(
    fn: ast.FunctionDef,
    mi: _ModuleInfo,
    ci: _ClassInfo | None,
    analysis: _Analysis,
    diags: list[LifeDiagnostic],
    class_obligations: list[tuple[_ClassInfo, str, str, int, _ModuleInfo]],
) -> None:
    nested = _nested_defs(fn)
    in_finally = _finally_nodes(fn)
    parent: dict[ast.AST, ast.AST] = {}
    for node in ast.walk(fn):
        if node in nested:
            continue
        for child in ast.iter_child_nodes(node):
            parent[child] = node

    def ctor_hit(call: ast.Call):
        hit = _ctor_kind(call)
        if hit is not None:
            return hit
        return analysis.factories.get(_terminal(_dotted(call.func)))

    # --- pass 1: acquisitions ---------------------------------------------
    acqs: list[_Acq] = []
    by_var: dict[str, _Acq] = {}
    for node in ast.walk(fn):
        if node in nested or not isinstance(node, ast.Call):
            continue
        hit = ctor_hit(node)
        if hit is None:
            continue
        kind, rels = hit
        p = parent.get(node)
        acq = _Acq(kind, rels, node.lineno, node)
        if isinstance(p, ast.withitem):
            acq.with_managed = True
        elif isinstance(p, ast.Call):
            # argument to another call: transfer sink or sink class?
            d = _dotted(p.func)
            t = _terminal(d)
            if (d in _TRANSFER_SINKS or t in _TRANSFER_SINKS
                    or t in analysis.sink_classes):
                acq.discharged = True
            # else: anonymous resource consumed by an arbitrary call —
            # nobody can release it; falls through as a leak
        elif isinstance(p, (ast.Return, ast.Yield, ast.YieldFrom)):
            acq.discharged = True  # caller/consumer owns it
        elif isinstance(p, ast.Assign) and len(p.targets) == 1:
            t = p.targets[0]
            if isinstance(t, ast.Name):
                acq.var = t.id
                by_var[t.id] = acq
            elif (
                isinstance(t, ast.Attribute)
                and isinstance(t.value, ast.Name)
                and t.value.id == "self"
            ):
                acq.self_attr = t.attr
            else:
                acq.discharged = True  # container/subscript store
        elif isinstance(p, ast.Attribute):
            # `ctor().start()` — the instance is dropped on the spot
            pass
        elif isinstance(p, (ast.Tuple, ast.List)):
            acq.discharged = True  # collected into a structure
        acqs.append(acq)

    # --- pass 2: releases / escapes / aliases for tracked locals ----------
    aliases: dict[str, _Acq] = {}
    yields: list[int] = []
    for node in ast.walk(fn):
        if node in nested:
            continue
        if isinstance(node, (ast.Yield, ast.YieldFrom)):
            yields.append(node.lineno)
        if isinstance(node, ast.Assign):
            tgts, vals = node.targets, [node.value]
            if (
                len(tgts) == 1
                and isinstance(tgts[0], ast.Tuple)
                and isinstance(node.value, ast.Tuple)
                and len(tgts[0].elts) == len(node.value.elts)
            ):
                tgts, vals = tgts[0].elts, node.value.elts
            for t, v in zip(tgts, vals if len(vals) == len(tgts)
                            else vals * len(tgts)):
                src = None
                if isinstance(v, ast.Name):
                    src = by_var.get(v.id) or aliases.get(v.id)
                if src is None:
                    continue
                if isinstance(t, ast.Name):
                    aliases[t.id] = src
                elif (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    # self.<attr> = x : ownership moves to the instance
                    src.discharged = True
                    src.self_attr = t.attr
                else:
                    src.discharged = True  # container store
        elif isinstance(node, ast.Return) and isinstance(
            node.value, (ast.Name, ast.Tuple)
        ):
            names = (
                [node.value]
                if isinstance(node.value, ast.Name)
                else [e for e in node.value.elts if isinstance(e, ast.Name)]
            )
            for nm in names:
                src = by_var.get(nm.id) or aliases.get(nm.id)
                if src is not None:
                    src.discharged = True
        elif isinstance(node, (ast.Yield, ast.YieldFrom)):
            v = node.value
            if isinstance(v, ast.Name):
                src = by_var.get(v.id) or aliases.get(v.id)
                if src is not None:
                    src.discharged = True
        elif isinstance(node, ast.Call):
            d = _dotted(node.func)
            t = _terminal(d)
            if isinstance(node.func, ast.Attribute):
                recv = node.func.value
                # release on the resource itself (or an alias)
                if isinstance(recv, ast.Name):
                    src = by_var.get(recv.id) or aliases.get(recv.id)
                    if src is not None and node.func.attr in set(
                        src.releases
                    ) | set(_RELEASE_METHODS):
                        src.release_lines.append(
                            (node.lineno, node in in_finally)
                        )
                # container mutator absorbing the resource as an argument
                if node.func.attr in _CONTAINER_MUTATORS:
                    for a in list(node.args) + [
                        kw.value for kw in node.keywords
                    ]:
                        for nm in ast.walk(a):
                            if isinstance(nm, ast.Name):
                                src = by_var.get(nm.id) or aliases.get(nm.id)
                                if src is not None:
                                    src.discharged = True
            # resource passed to a transfer sink / sink class
            if (d in _TRANSFER_SINKS or t in _TRANSFER_SINKS
                    or t in analysis.sink_classes):
                for a in list(node.args) + [kw.value for kw in node.keywords]:
                    if isinstance(a, ast.Name):
                        src = by_var.get(a.id) or aliases.get(a.id)
                        if src is not None:
                            src.discharged = True

    # IPC readers over an explicitly-owned source are VIEWS: pyarrow's
    # reader has no close(); the obligation lives (and is checked) on the
    # source file/mmap it reads. Only a reader over an INTERNAL fd (a
    # plain path string) carries its own obligation.
    for acq in acqs:
        if acq.kind != "ipc-reader" or acq.node is None:
            continue
        for a in list(acq.node.args) + [
            kw.value for kw in acq.node.keywords
        ]:
            if isinstance(a, ast.Name) and (
                a.id in by_var or a.id in aliases
            ):
                acq.discharged = True
            elif isinstance(a, ast.Call) and ctor_hit(a) is not None:
                # open_file(memory_map(p)): the source is anonymous and
                # flagged on its own — don't double-report the view
                acq.discharged = True

    # --- verdicts ----------------------------------------------------------
    def emit(line: int, rule: str, msg: str) -> None:
        sup = _suppressed(mi, fn.lineno, line)
        if rule in sup or "all" in sup:
            return
        if _transfer_note(mi, line) is not None:
            return  # declared ownership hand-off
        diags.append(LifeDiagnostic(mi.file, line, rule, msg, fn.name))

    is_ctx_method = ci is not None and fn.name in (
        "__exit__", "__del__", "close", "stop", "shutdown", "__enter__"
    )
    for acq in acqs:
        if acq.with_managed or acq.discharged:
            continue
        if acq.self_attr is not None:
            if ci is not None:
                class_obligations.append(
                    (ci, acq.self_attr, acq.kind, acq.line, mi)
                )
            continue
        if not acq.release_lines:
            emit(
                acq.line, "leaked-resource",
                f"{acq.kind} acquired here is never released "
                f"({'/'.join(acq.releases)}) and never handed off",
            )
            continue
        if any(in_f for _ln, in_f in acq.release_lines):
            continue  # a finally-guarded release reaches every edge
        if is_ctx_method:
            continue  # release methods run on already-owned state
        first_release = min(ln for ln, _f in acq.release_lines)
        held_yields = [y for y in yields if acq.line < y < first_release]
        if held_yields:
            emit(
                acq.line, "leak-on-error",
                f"{acq.kind} held across yield (line {held_yields[0]}) "
                "with release outside finally — consumer abandonment "
                "(GeneratorExit) leaks it",
            )
            continue
        # anything that can raise between acquire and release skips it
        risky = _risky_between(fn, nested, acq, first_release, by_var,
                               aliases)
        if risky is not None:
            emit(
                acq.line, "leak-on-error",
                f"{acq.kind} release at line {first_release} is not in a "
                f"finally, but line {risky} between acquire and release "
                "can raise past it",
            )


def _risky_between(
    fn: ast.FunctionDef,
    nested: set[ast.AST],
    acq: _Acq,
    first_release: int,
    by_var: dict[str, _Acq],
    aliases: dict[str, _Acq],
) -> int | None:
    """Line of a call between acquire and release that may raise, or None.
    Calls on the resource itself (or its aliases) are exempt — failures of
    the resource's own methods are the release idiom's concern, and e.g.
    ``q.put``/``pool.submit`` sequences between create and close would
    otherwise always trip the rule."""
    for node in ast.walk(fn):
        if node in nested or not isinstance(node, ast.Call):
            continue
        if not (acq.line < node.lineno < first_release):
            continue
        if isinstance(node.func, ast.Attribute) and isinstance(
            node.func.value, ast.Name
        ):
            src = by_var.get(node.func.value.id) or aliases.get(
                node.func.value.id
            )
            if src is acq:
                continue
        return node.lineno
    return None


def _check_class_obligations(
    obligations: list[tuple[_ClassInfo, str, str, int, _ModuleInfo]],
    diags: list[LifeDiagnostic],
) -> None:
    for ci, attr, kind, line, mi in obligations:
        if attr in ci.released_attrs:
            continue
        sup = _suppressed(mi, line, line)
        if "leaked-resource" in sup or "all" in sup:
            continue
        if _transfer_note(mi, line) is not None:
            continue
        diags.append(
            LifeDiagnostic(
                mi.file, line, "leaked-resource",
                f"self.{attr} holds a {kind} but no method of "
                f"{ci.name} releases it",
            )
        )


# --------------------------------------------------------------------------
# error-taxonomy analysis
# --------------------------------------------------------------------------


def _classified_types() -> frozenset[str]:
    from ballista_tpu.errors import (
        NON_RETRYABLE_ERROR_TYPES,
        RETRYABLE_ERROR_TYPES,
    )

    return frozenset(NON_RETRYABLE_ERROR_TYPES) | frozenset(
        RETRYABLE_ERROR_TYPES
    )


# process-exit / control-flow types that never cross the task boundary as
# a task error string
_TAXONOMY_EXEMPT = frozenset(
    {"SystemExit", "KeyboardInterrupt", "GeneratorExit", "StopIteration"}
)


def _exc_factories(modules: list[_ModuleInfo]) -> dict[str, str]:
    """Functions/methods whose every return is a constructor call of a
    classified exception type: ``raise _escalate(...)`` then classifies as
    what the factory returns."""
    classified = _classified_types()
    out: dict[str, str] = {}
    for _round in range(2):
        for mi in modules:
            fns: list[ast.FunctionDef] = list(mi.functions.values())
            for ci in mi.classes.values():
                fns.extend(ci.methods.values())
            for fn in fns:
                returns = [
                    n for n in ast.walk(fn)
                    if isinstance(n, ast.Return) and n.value is not None
                ]
                if not returns:
                    continue
                names = []
                for r in returns:
                    if not isinstance(r.value, ast.Call):
                        names = []
                        break
                    t = _terminal(_dotted(r.value.func))
                    if t in classified:
                        names.append(t)
                    elif t in out:
                        names.append(out[t])
                    else:
                        names = []
                        break
                if names:
                    out[fn.name] = names[0]
    return out


def _check_taxonomy(
    mi: _ModuleInfo,
    factories: dict[str, str],
    classified: frozenset[str],
    diags: list[LifeDiagnostic],
) -> None:
    if mi.name == "__main__":
        return  # CLI entry points exit, they don't report task errors

    def handler_ctx(fn: ast.FunctionDef) -> dict[ast.AST, set[str]]:
        """Map each node to the caught-exception variable names in scope."""
        scopes: dict[ast.AST, set[str]] = {}

        def walk(node: ast.AST, names: set[str]):
            scopes[node] = names
            for child in ast.iter_child_nodes(node):
                if isinstance(node, ast.Try) and isinstance(
                    child, ast.ExceptHandler
                ):
                    walk(
                        child,
                        names | ({child.name} if child.name else set()),
                    )
                else:
                    walk(child, names)

        walk(fn, set())
        return scopes

    fns: list[tuple[ast.FunctionDef, str]] = [
        (f, f.name) for f in mi.functions.values()
    ]
    for ci in mi.classes.values():
        fns.extend((m, f"{ci.name}.{m.name}") for m in ci.methods.values())

    for fn, disp in fns:
        scopes = handler_ctx(fn)
        for node in ast.walk(fn):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            caught = scopes.get(node, set())
            exc = node.exc
            tname: str | None = None
            if isinstance(exc, ast.Call):
                tname = _terminal(_dotted(exc.func))
                tname = factories.get(tname, tname)
            elif isinstance(exc, ast.Name):
                if exc.id in caught:
                    continue  # re-raise of the caught exception
                tname = exc.id
            else:
                continue  # attribute relay (raise item.exc) etc.
            if tname is None or tname in _TAXONOMY_EXEMPT:
                continue
            if tname in classified:
                continue
            if not tname or not tname[0].isupper():
                continue  # dynamic/variable raise — out of scope
            sup = _suppressed(mi, fn.lineno, node.lineno)
            if "unclassified-raise" in sup or "all" in sup:
                continue
            diags.append(
                LifeDiagnostic(
                    mi.file, node.lineno, "unclassified-raise",
                    f"raise of {tname} which is in neither "
                    "NON_RETRYABLE_ERROR_TYPES nor RETRYABLE_ERROR_TYPES "
                    "(errors.py) — it would silently default to retryable "
                    "at the task boundary",
                    disp,
                )
            )


# --------------------------------------------------------------------------
# swallow / injection handler analysis
# --------------------------------------------------------------------------

_LOG_CALL_RE = re.compile(
    r"\b(log|logger|logging)\.(debug|info|warning|error|exception|critical)"
    r"\b|\bwarnings\.warn\b|\btraceback\."
)


def _handler_types(h: ast.ExceptHandler) -> list[str]:
    t = h.type
    if t is None:
        return []
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    return [_terminal(_dotted(e)) or "" for e in elts]


def _body_has(node_list: list[ast.stmt], kinds: tuple) -> bool:
    for stmt in node_list:
        for sub in ast.walk(stmt):
            if isinstance(sub, kinds):
                return True
    return False


def _body_logs(mi: _ModuleInfo, h: ast.ExceptHandler) -> bool:
    start = h.lineno
    end = max(
        getattr(s, "end_lineno", s.lineno) for s in h.body
    ) if h.body else h.lineno
    text = "\n".join(mi.lines[start - 1:end])
    return bool(_LOG_CALL_RE.search(text))


def _is_release_only_try(try_node: ast.Try) -> bool:
    """The close-suppression idiom: every statement in the try body is a
    release-method call (or pass)."""
    for stmt in try_node.body:
        if isinstance(stmt, ast.Pass):
            continue
        if not (
            isinstance(stmt, ast.Expr)
            and isinstance(stmt.value, ast.Call)
            and isinstance(stmt.value.func, ast.Attribute)
            and stmt.value.func.attr in _RELEASE_METHODS
        ):
            return False
    return True


def _check_handlers(
    mi: _ModuleInfo, diags: list[LifeDiagnostic]
) -> None:
    fns: list[ast.FunctionDef] = list(mi.functions.values())
    for ci in mi.classes.values():
        fns.extend(ci.methods.values())
    for fn in fns:
        for node in ast.walk(fn):
            if not isinstance(node, ast.Try):
                continue
            for h in node.handlers:
                types = _handler_types(h)
                raises = _body_has(h.body, (ast.Raise,))
                sup = _suppressed(mi, fn.lineno, h.lineno)
                if any(t.startswith("Injected") for t in types):
                    if not raises and not (
                        "untyped-injection" in sup or "all" in sup
                    ):
                        diags.append(
                            LifeDiagnostic(
                                mi.file, h.lineno, "untyped-injection",
                                "handler catches a fault-injection type "
                                f"({[t for t in types if t.startswith('Injected')][0]}) "
                                "without re-raising typed — chaos faults "
                                "must take the production error path",
                                fn.name,
                            )
                        )
                    continue
                broad = h.type is None or any(t in _EXC_BASES for t in types)
                if not broad:
                    continue
                if raises:
                    continue
                if _body_logs(mi, h):
                    continue
                if _is_release_only_try(node):
                    continue
                # relay: the caught exception object is handed onward
                # (``self.action.on_error(e)``, ``_Err(e)`` into a queue)
                if h.name and any(
                    isinstance(sub, ast.Call)
                    and any(
                        isinstance(n, ast.Name) and n.id == h.name
                        for a in list(sub.args)
                        + [kw.value for kw in sub.keywords]
                        for n in ast.walk(a)
                    )
                    for stmt in h.body
                    for sub in ast.walk(stmt)
                ):
                    continue
                # fallback: the handler REACTS by substituting a value or
                # leaving — the failure is handled, not discarded
                if _body_has(h.body, (ast.Assign, ast.AugAssign,
                                      ast.AnnAssign, ast.Return)):
                    continue
                if "swallowed-error" in sup or "all" in sup:
                    continue
                label = "bare except" if h.type is None else (
                    f"except {'/'.join(types)}"
                )
                diags.append(
                    LifeDiagnostic(
                        mi.file, h.lineno, "swallowed-error",
                        f"{label} neither re-raises nor logs — the "
                        "failure vanishes (log it, type it, or narrow "
                        "the except)",
                        fn.name,
                    )
                )


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

# resource-lifecycle + handler rules: the full control & data plane
_RESOURCE_TARGETS = (
    "scheduler",
    "executor",
    "exec",
    "client",
    "compilecache",
    "event_loop.py",
    "standalone.py",
    # observability plane (PR 10): JSONL export files + the metrics HTTP
    # server's listening socket
    "obs",
)

# error-taxonomy closure: the surfaces whose raises cross the task
# boundary as wire strings (ISSUE 8; executor catch-alls serialize them)
_TAXONOMY_TARGETS = ("executor", "exec", "client", "scheduler")


def _target_files(subs, paths=None) -> list[pathlib.Path]:
    if paths is not None:
        out: list[pathlib.Path] = []
        for p in paths:
            p = pathlib.Path(p)
            out.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
        return out
    root = pathlib.Path(__file__).resolve().parent.parent
    files: list[pathlib.Path] = []
    for sub in subs:
        p = root / sub
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    return files


def _load(paths=None) -> tuple[list[_ModuleInfo], list[_ModuleInfo]]:
    res_files = _target_files(_RESOURCE_TARGETS, paths)
    tax_files = _target_files(_TAXONOMY_TARGETS, paths)
    cache: dict[str, _ModuleInfo] = {}

    def mod(f: pathlib.Path) -> _ModuleInfo:
        key = str(f)
        if key not in cache:
            cache[key] = _collect_module(f.read_text(), key)
        return cache[key]

    return [mod(f) for f in res_files], [mod(f) for f in tax_files]


def lint_paths(paths=None) -> list[LifeDiagnostic]:
    """Analyze files/directories (default: the control & data planes)."""
    res_mods, tax_mods = _load(paths)
    return _diagnose(res_mods, tax_mods)


def lint_source(
    source: str, filename: str = "synth.py"
) -> list[LifeDiagnostic]:
    """Single-module convenience for tests (all rules applied)."""
    mi = _collect_module(source, filename)
    return _diagnose([mi], [mi])


def _diagnose(
    res_mods: list[_ModuleInfo], tax_mods: list[_ModuleInfo]
) -> list[LifeDiagnostic]:
    diags: list[LifeDiagnostic] = []
    analysis = _Analysis(res_mods)
    obligations: list = []
    for mi in res_mods:
        for fn in mi.functions.values():
            _walk_with_nested(fn, mi, None, analysis, diags, obligations)
        for ci in mi.classes.values():
            for meth in ci.methods.values():
                _walk_with_nested(meth, mi, ci, analysis, diags, obligations)
        _check_handlers(mi, diags)
    _check_class_obligations(obligations, diags)
    classified = _classified_types()
    factories = _exc_factories(tax_mods)
    for mi in tax_mods:
        _check_taxonomy(mi, factories, classified, diags)
    diags.sort(key=lambda d: (d.file, d.line, d.rule))
    return diags


def _walk_with_nested(
    fn: ast.FunctionDef,
    mi: _ModuleInfo,
    ci: _ClassInfo | None,
    analysis: _Analysis,
    diags: list[LifeDiagnostic],
    obligations: list,
) -> None:
    _check_resources(fn, mi, ci, analysis, diags, obligations)
    for node in ast.walk(fn):
        if node is not fn and isinstance(node, ast.FunctionDef):
            # nested defs get their own resource check (acquisitions in a
            # closure are owned by that closure unless they escape)
            _check_resources(node, mi, ci, analysis, diags, obligations)


def suppression_count(paths=None) -> int:
    """Number of ``# lifelint: disable=`` escape hatches in the targets
    (transfer annotations are NOT suppressions and are not counted)."""
    n = 0
    seen = set()
    for f in _target_files(_RESOURCE_TARGETS, paths) + _target_files(
        _TAXONOMY_TARGETS, paths
    ):
        if str(f) in seen:
            continue
        seen.add(str(f))
        n += len(_SUPPRESS_RE.findall(f.read_text()))
    return n


def transfer_sites(paths=None) -> list[tuple[str, int, str]]:
    """Every declared ``# lifelint: transfer`` annotation: (file, line,
    note) — the audited ownership hand-offs."""
    out: list[tuple[str, int, str]] = []
    seen = set()
    for f in _target_files(_RESOURCE_TARGETS, paths) + _target_files(
        _TAXONOMY_TARGETS, paths
    ):
        if str(f) in seen:
            continue
        seen.add(str(f))
        for i, line in enumerate(f.read_text().splitlines(), 1):
            m = _TRANSFER_RE.search(line)
            if m:
                out.append((str(f), i, m.group(1) or "declared"))
    return out
