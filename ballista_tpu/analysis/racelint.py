"""racelint: lock-discipline & state-machine static analysis.

PR 3 made the scheduler/executor control plane genuinely concurrent:
``SchedulerServer``, ``StageManager``, ``ExecutorManager``, the state
backends, the Flight connection pool, the event loop, and the executor
poll/heartbeat/cleanup threads juggle ~15 locks across a dozen daemon
threads. Nothing checked lock discipline statically — the next recovery
change could reintroduce exactly the deadlock and silent-race classes PR 3
hand-fixed (the ``EventLoop.stop()`` full-queue deadlock, the ``next_task``
re-resolution race). racelint is the default-on gate for that: an
AST-based, import-free analysis of the concurrent control plane with four
rule families:

==================== ========================================================
rule                 rationale
==================== ========================================================
unguarded-field      For each class owning a ``threading.Lock``/``RLock``
                     (or a :func:`witness.make_lock`), infer the fields
                     *written* under ``with self._lock`` — those are the
                     lock's protectorate — and flag any read/write of them
                     outside the lock (``__init__`` exempt: construction is
                     single-threaded). Same inference for module globals
                     written under a module-level lock.
lock-order-cycle     Build the inter-class lock acquisition graph from
                     nested ``with``-lock scopes and calls into lock-taking
                     methods (receiver types resolved from ``self.x =
                     Class()`` constructor assignments), and fail on cycles
                     — the static deadlock hazard. Also flags re-acquiring
                     a NON-reentrant lock through a call chain.
blocking-under-lock  gRPC/Flight/socket/``sleep``/blocking ``queue.get``/
                     ``queue.put``/file-IO reachable while a lock is held —
                     the exact shape of the PR 3 ``EventLoop.stop()``
                     deadlock (a bounded-queue ``put`` under a lock the
                     consumer needs). Propagated transitively through
                     resolved calls.
undeclared-transition Every ``.state = TaskState.X`` assignment must be a
                     declared edge of
                     :data:`~ballista_tpu.analysis.statemachine.TASK_TRANSITIONS`
                     (source state inferred from enclosing guards and
                     assignment flow, or the function must gate on the
                     declared table), and every ``.status = "<s>"`` string
                     must be a declared job state with declared in-edges.
==================== ========================================================

Suppression: append ``# racelint: disable=<rule>[,<rule>...]`` (or
``disable=all``) to the offending line or to the enclosing ``def`` line.
The tier-1 suite budgets suppressions at ≤ 5 tree-wide.

Scope/limitations (deliberate): receiver types are resolved only through
``self.attr = ClassName(...)`` constructor assignments and ``self`` calls
(including inherited methods), so cross-object accesses like
``rest.py``'s ``server.jobs`` snapshots are out of scope — the rule is a
per-class discipline check, not an escape analysis. Locks passed as
arguments or returned from functions are not tracked.

The static lock-order graph is exported (:func:`lock_order_graph`,
``--dot``) and shares its node vocabulary (``Class._lockfield`` /
``module._LOCK``) with the runtime witness
(:mod:`ballista_tpu.analysis.witness`), which asserts during tests that
every acquisition order actually taken is consistent with this graph.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

from ballista_tpu.analysis.statemachine import (
    JOB_STATES,
    JOB_TRANSITIONS,
    TASK_TRANSITIONS,
)

RULES: dict[str, str] = {
    "unguarded-field": "read/write of a lock-guarded field (one written "
    "under the owning lock) outside any holder of that lock",
    "lock-order-cycle": "cycle in the static lock acquisition-order graph "
    "(or re-acquisition of a non-reentrant lock) — deadlock hazard",
    "blocking-under-lock": "blocking call (RPC/Flight/sleep/queue/IO) "
    "reachable while a lock is held — the PR 3 deadlock shape",
    "undeclared-transition": "status assignment that is not a declared "
    "edge of the canonical task/job state machine",
}

_SUPPRESS_RE = re.compile(r"#\s*racelint:\s*disable=([A-Za-z0-9_,\- ]+)")

# threading constructors (and the witness factory) that create a lock
_LOCK_CTORS = {
    "threading.Lock": False,
    "threading.RLock": True,
    "Lock": False,
    "RLock": True,
    "make_lock": None,  # reentrant= kwarg decides
    "witness.make_lock": None,
}

# dotted call names that block the calling thread
_BLOCKING_DOTTED = {
    "time.sleep": "time.sleep()",
    "sleep": "sleep()",
    "paflight.connect": "Flight dial (paflight.connect)",
    "flight.connect": "Flight dial",
    "grpc.insecure_channel": "gRPC channel setup",
    "_grpc.insecure_channel": "gRPC channel setup",
    "shutil.rmtree": "file-tree removal",
    "os.walk": "filesystem walk",
    "socket.create_connection": "socket connect",
    "open": "file open",
}

# RPC verbs of this codebase's two gRPC services (scheduler/rpc.py): a
# stub call on any of these is a network round trip with a deadline
_RPC_METHODS = {
    "PollWork", "RegisterExecutor", "HeartBeatFromExecutor",
    "UpdateTaskStatus", "ExecuteQuery", "GetJobStatus", "GetFileMetadata",
    "LaunchTask", "StopExecutor",
}

# attribute-call names that block regardless of receiver
_BLOCKING_ATTRS = {
    "do_get": "Flight do_get stream",
    "read_all": "Flight read_all",
    "serve": "server loop",
    "join": "thread join",
    "wait": "event wait",
    **{m: f"{m} RPC" for m in _RPC_METHODS},
}

# receiver-method calls that MUTATE the receiver (write for rule 1)
_MUTATORS = {
    "append", "add", "pop", "popitem", "clear", "update", "discard",
    "remove", "setdefault", "extend", "insert", "put", "put_nowait",
}


@dataclasses.dataclass(frozen=True)
class RaceDiagnostic:
    file: str
    line: int
    rule: str
    message: str
    function: str = ""

    def __str__(self) -> str:
        where = f" [{self.function}]" if self.function else ""
        return f"{self.file}:{self.line}: {self.rule}{where}: {self.message}"


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _lock_ctor_kind(value: ast.AST) -> bool | None:
    """True/False (reentrant) when ``value`` constructs a lock, else None."""
    if not isinstance(value, ast.Call):
        return None
    d = _dotted(value.func)
    if d not in _LOCK_CTORS:
        return None
    kind = _LOCK_CTORS[d]
    if kind is not None:
        return kind
    for kw in value.keywords:  # make_lock(..., reentrant=True)
        if kw.arg == "reentrant" and isinstance(kw.value, ast.Constant):
            return bool(kw.value.value)
    return False


# --------------------------------------------------------------------------
# module / class models
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _ClassInfo:
    name: str
    module: str
    file: str
    node: ast.ClassDef
    bases: list[str] = dataclasses.field(default_factory=list)
    # lock field -> (lock_id, reentrant)
    lock_fields: dict[str, tuple[str, bool]] = dataclasses.field(
        default_factory=dict
    )
    # attr -> class name (constructor-typed)
    attr_types: dict[str, str] = dataclasses.field(default_factory=dict)
    methods: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass
class _ModuleInfo:
    name: str  # module stem (for lock ids)
    file: str
    tree: ast.Module
    lines: list[str]
    classes: dict[str, _ClassInfo] = dataclasses.field(default_factory=dict)
    functions: dict[str, ast.FunctionDef] = dataclasses.field(
        default_factory=dict
    )
    # module-level lock var -> (lock_id, reentrant)
    module_locks: dict[str, tuple[str, bool]] = dataclasses.field(
        default_factory=dict
    )
    module_globals: set[str] = dataclasses.field(default_factory=set)


def _collect_module(source: str, filename: str) -> _ModuleInfo:
    tree = ast.parse(source, filename=filename)
    stem = pathlib.Path(filename).stem
    mi = _ModuleInfo(stem, filename, tree, source.splitlines())
    for node in tree.body:
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and (
            isinstance(node.targets[0], ast.Name)
        ):
            name = node.targets[0].id
            mi.module_globals.add(name)
            kind = _lock_ctor_kind(node.value)
            if kind is not None:
                mi.module_locks[name] = (f"{stem}.{name}", kind)
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            mi.module_globals.add(node.target.id)
        elif isinstance(node, ast.FunctionDef):
            mi.functions[node.name] = node
        elif isinstance(node, ast.ClassDef):
            ci = _ClassInfo(node.name, stem, filename, node)
            ci.bases = [b for b in (_dotted(x) for x in node.bases) if b]
            for item in node.body:
                if isinstance(item, ast.FunctionDef):
                    ci.methods[item.name] = item
            # discover lock fields + constructor-typed attrs in any method
            for meth in ci.methods.values():
                for sub in ast.walk(meth):
                    if not (
                        isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.targets[0], ast.Attribute)
                        and isinstance(sub.targets[0].value, ast.Name)
                        and sub.targets[0].value.id == "self"
                    ):
                        continue
                    field = sub.targets[0].attr
                    kind = _lock_ctor_kind(sub.value)
                    if kind is not None:
                        ci.lock_fields[field] = (
                            f"{ci.name}.{field}", kind
                        )
                    elif isinstance(sub.value, ast.Call):
                        d = _dotted(sub.value.func) or ""
                        ci.attr_types.setdefault(field, d.split(".")[-1])
            mi.classes[ci.name] = ci
    return mi


# --------------------------------------------------------------------------
# per-function walk: accesses, acquisitions, calls, blocking sites
# --------------------------------------------------------------------------


@dataclasses.dataclass
class _FnFacts:
    key: tuple  # ("m", Class, name) | ("f", module, name)
    node: ast.FunctionDef
    module: _ModuleInfo
    cls: _ClassInfo | None
    # (field, write?, frozenset(held lock ids), line) for self.X accesses
    field_accesses: list[tuple[str, bool, frozenset, int]] = (
        dataclasses.field(default_factory=list)
    )
    # same for module globals
    global_accesses: list[tuple[str, bool, frozenset, int]] = (
        dataclasses.field(default_factory=list)
    )
    # (lock_id, reentrant, frozenset(held BEFORE), line)
    acquisitions: list[tuple[str, bool, frozenset, int]] = (
        dataclasses.field(default_factory=list)
    )
    # (callee_key, frozenset(held), line, display)
    calls: list[tuple[tuple, frozenset, int, str]] = dataclasses.field(
        default_factory=list
    )
    # (description, frozenset(held), line)
    blocking: list[tuple[str, frozenset, int]] = dataclasses.field(
        default_factory=list
    )


class _Registry:
    """Cross-module class/function lookup."""

    def __init__(self, modules: list[_ModuleInfo]):
        self.modules = modules
        self.classes: dict[str, _ClassInfo] = {}
        for m in modules:
            for c in m.classes.values():
                self.classes.setdefault(c.name, c)

    def resolve_method(
        self, cls: _ClassInfo | None, name: str
    ) -> tuple | None:
        """("m", file, DefiningClassName, name) through the base chain —
        the file keeps keys unique across same-named modules/classes."""
        seen = set()
        while cls is not None and cls.name not in seen:
            seen.add(cls.name)
            if name in cls.methods:
                return ("m", cls.file, cls.name, name)
            nxt = None
            for b in cls.bases:
                base = self.classes.get(b.split(".")[-1])
                if base is not None:
                    nxt = base
                    break
            cls = nxt
        return None


def _walk_function(
    fn: ast.FunctionDef,
    mi: _ModuleInfo,
    ci: _ClassInfo | None,
    reg: _Registry,
    nested_out: list,
) -> _FnFacts:
    key = (
        ("m", ci.file, ci.name, fn.name)
        if ci
        else ("f", mi.file, fn.name)
    )
    facts = _FnFacts(key, fn, mi, ci)
    # locals: params + names assigned without a `global` declaration
    globals_decl: set[str] = set()
    local_names: set[str] = set()
    a = fn.args
    for p in a.posonlyargs + a.args + a.kwonlyargs:
        local_names.add(p.arg)
    if a.vararg:
        local_names.add(a.vararg.arg)
    if a.kwarg:
        local_names.add(a.kwarg.arg)
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Global):
            globals_decl.update(sub.names)
        elif isinstance(sub, ast.Name) and isinstance(sub.ctx, ast.Store):
            local_names.add(sub.id)
        elif isinstance(sub, (ast.For,)) and isinstance(
            sub.target, ast.Name
        ):
            local_names.add(sub.target.id)
    local_names -= globals_decl

    def lock_of(expr: ast.AST) -> tuple[str, bool] | None:
        if isinstance(expr, ast.Name) and expr.id in mi.module_locks:
            return mi.module_locks[expr.id]
        if (
            ci is not None
            and isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and expr.attr in ci.lock_fields
        ):
            return ci.lock_fields[expr.attr]
        return None

    def record_field(name: str, write: bool, held: frozenset, line: int):
        if ci is None:
            return
        if name in ci.lock_fields:
            return
        if name in ci.methods or (
            reg.resolve_method(ci, name) is not None
        ):
            return  # method reference, not data
        facts.field_accesses.append((name, write, held, line))

    def record_global(name: str, write: bool, held: frozenset, line: int):
        if name in mi.module_locks or name not in mi.module_globals:
            return
        if name in mi.functions or name in mi.classes:
            return
        if not write and name in local_names:
            return  # shadowed
        facts.global_accesses.append((name, write, held, line))

    def scan_expr(expr: ast.AST, held: frozenset) -> None:
        """Record calls/accesses/blocking sites in an expression tree,
        PRUNING nested function/lambda subtrees (they run later, with no
        lock inherited — ast.walk would descend into them, wrongly
        attributing a deferred callback's body to the current locks)."""
        stack: list[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(
                node, (ast.Lambda, ast.FunctionDef, ast.AsyncFunctionDef)
            ):
                continue  # deferred body: pruned, children not visited
            stack.extend(ast.iter_child_nodes(node))
            if isinstance(node, ast.Call):
                _scan_call(node, held)
            elif isinstance(node, ast.Attribute):
                if (
                    isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                ):
                    write = isinstance(node.ctx, (ast.Store, ast.Del))
                    record_field(node.attr, write, held, node.lineno)
            elif isinstance(node, ast.Name):
                if isinstance(node.ctx, ast.Load):
                    record_global(node.id, False, held, node.lineno)
                elif node.id in globals_decl:
                    record_global(node.id, True, held, node.lineno)

    def _scan_call(node: ast.Call, held: frozenset) -> None:
        d = _dotted(node.func)
        line = node.lineno
        # blocking primitives -------------------------------------------------
        if d in _BLOCKING_DOTTED:
            facts.blocking.append((_BLOCKING_DOTTED[d], held, line))
        if isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            recv = node.func.value
            recv_is_str = isinstance(recv, ast.Constant) and isinstance(
                recv.value, str
            )
            if attr in _BLOCKING_ATTRS and not recv_is_str:
                facts.blocking.append(
                    (f"{_BLOCKING_ATTRS[attr]} (.{attr}())", held, line)
                )
            elif attr == "get" and not node.args:
                # zero-positional .get() is a queue get (dict.get needs a
                # key); timeout= keeps it blocking, just bounded
                facts.blocking.append(("blocking queue.get()", held, line))
            elif attr == "put" and len(node.args) <= 1:
                # one-positional .put(item) is a queue put (KV-store puts
                # carry (key, value)); a bounded queue makes it blocking
                facts.blocking.append(
                    ("queue.put() (may block on a bounded queue)",
                     held, line)
                )
            # receiver mutation => write of the receiver field/global
            if attr in _MUTATORS:
                tgt = recv
                if (
                    isinstance(tgt, ast.Attribute)
                    and isinstance(tgt.value, ast.Name)
                    and tgt.value.id == "self"
                ):
                    record_field(tgt.attr, True, held, line)
                elif isinstance(tgt, ast.Name):
                    record_global(tgt.id, True, held, line)
        # call resolution -----------------------------------------------------
        callee = None
        disp = d or "<call>"
        if isinstance(node.func, ast.Name):
            nm = node.func.id
            if ci is not None and nm in mi.classes and nm == ci.name:
                callee = reg.resolve_method(mi.classes[nm], "__init__")
            elif nm in mi.functions:
                callee = ("f", mi.file, nm)
            elif nm in reg.classes:
                callee = reg.resolve_method(reg.classes[nm], "__init__")
        elif isinstance(node.func, ast.Attribute):
            recv = node.func.value
            meth = node.func.attr
            if isinstance(recv, ast.Name) and recv.id == "self":
                callee = reg.resolve_method(ci, meth)
            elif (
                isinstance(recv, ast.Attribute)
                and isinstance(recv.value, ast.Name)
                and recv.value.id == "self"
                and ci is not None
            ):
                tname = ci.attr_types.get(recv.attr)
                target = reg.classes.get(tname) if tname else None
                if target is not None:
                    callee = reg.resolve_method(target, meth)
        if callee is not None:
            facts.calls.append((callee, held, line, disp))

    def walk_stmts(stmts: list[ast.stmt], held: frozenset) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.FunctionDef):
                nested_out.append((stmt, mi, ci))
                continue
            if isinstance(stmt, ast.With):
                inner = held
                for item in stmt.items:
                    lk = lock_of(item.context_expr)
                    scan_expr(item.context_expr, inner)
                    if lk is not None:
                        lock_id, reentrant = lk
                        facts.acquisitions.append(
                            (lock_id, reentrant, inner, stmt.lineno)
                        )
                        inner = inner | {lock_id}
                walk_stmts(stmt.body, inner)
                continue
            if isinstance(stmt, ast.If):
                scan_expr(stmt.test, held)
                walk_stmts(stmt.body, held)
                walk_stmts(stmt.orelse, held)
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                scan_expr(stmt.iter, held)
                scan_expr(stmt.target, held)
                walk_stmts(stmt.body, held)
                walk_stmts(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.While):
                scan_expr(stmt.test, held)
                walk_stmts(stmt.body, held)
                walk_stmts(stmt.orelse, held)
                continue
            if isinstance(stmt, ast.Try):
                walk_stmts(stmt.body, held)
                for h in stmt.handlers:
                    walk_stmts(h.body, held)
                walk_stmts(stmt.orelse, held)
                walk_stmts(stmt.finalbody, held)
                continue
            # subscript stores mutate the container: self.X[k] = v
            if isinstance(stmt, (ast.Assign, ast.AugAssign)):
                targets = (
                    stmt.targets
                    if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if (
                        isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                    ):
                        record_field(base.attr, True, held, stmt.lineno)
                    elif isinstance(base, ast.Name) and not isinstance(
                        t, ast.Name
                    ):
                        record_global(base.id, True, held, stmt.lineno)
            if isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    base = t
                    while isinstance(base, ast.Subscript):
                        base = base.value
                    if isinstance(base, ast.Name):
                        record_global(base.id, True, held, stmt.lineno)
                    elif (
                        isinstance(base, ast.Attribute)
                        and isinstance(base.value, ast.Name)
                        and base.value.id == "self"
                    ):
                        record_field(base.attr, True, held, stmt.lineno)
            scan_expr(stmt, held)

    walk_stmts(fn.body, frozenset())
    # plain `self.x = v` is seen by both the Assign-target handler and
    # scan_expr's Store-ctx walk — merge per (name, line, held), keeping
    # the stronger (write) classification, so a violation emits once
    facts.field_accesses = _dedupe_accesses(facts.field_accesses)
    facts.global_accesses = _dedupe_accesses(facts.global_accesses)
    return facts


def _dedupe_accesses(
    accesses: list[tuple[str, bool, frozenset, int]]
) -> list[tuple[str, bool, frozenset, int]]:
    merged: dict[tuple, bool] = {}
    for name, write, held, line in accesses:
        key = (name, line, held)
        merged[key] = merged.get(key, False) or write
    return sorted(
        ((n, w, h, l) for (n, l, h), w in merged.items()),
        key=lambda a: (a[3], a[0]),
    )


# --------------------------------------------------------------------------
# analysis passes
# --------------------------------------------------------------------------


def _suppressed(mi: _ModuleInfo, fn: ast.FunctionDef, line: int) -> frozenset:
    out: set[str] = set()
    for ln in (line, fn.lineno):
        if 0 < ln <= len(mi.lines):
            m = _SUPPRESS_RE.search(mi.lines[ln - 1])
            if m:
                out.update(p.strip() for p in m.group(1).split(","))
    return frozenset(out)


class _Analysis:
    def __init__(self, modules: list[_ModuleInfo]):
        self.modules = modules
        self.reg = _Registry(modules)
        self.fns: dict[tuple, _FnFacts] = {}
        self.lock_reentrant: dict[str, bool] = {}
        pending: list[tuple[ast.FunctionDef, _ModuleInfo, _ClassInfo | None]]
        pending = []
        for mi in modules:
            for lock_id, kind in mi.module_locks.values():
                self.lock_reentrant[lock_id] = kind
            for fn in mi.functions.values():
                pending.append((fn, mi, None))
            for ci in mi.classes.values():
                for lock_id, kind in ci.lock_fields.values():
                    self.lock_reentrant[lock_id] = kind
                for meth in ci.methods.values():
                    pending.append((meth, mi, ci))
        while pending:
            fn, mi, ci = pending.pop()
            facts = _walk_function(fn, mi, ci, self.reg, pending)
            # nested defs share the enclosing key space via (key, name)
            self.fns.setdefault(facts.key, facts)
        self._fixpoint()

    def _fixpoint(self) -> None:
        """Transitive may-acquire lock set and may-block flag per fn."""
        self.may_acquire: dict[tuple, set[str]] = {
            k: {a[0] for a in f.acquisitions} for k, f in self.fns.items()
        }
        self.may_block: dict[tuple, str | None] = {
            k: (f.blocking[0][0] if f.blocking else None)
            for k, f in self.fns.items()
        }
        changed = True
        while changed:
            changed = False
            for k, f in self.fns.items():
                for callee, _held, _line, disp in f.calls:
                    extra = self.may_acquire.get(callee, set())
                    if not extra <= self.may_acquire[k]:
                        self.may_acquire[k] |= extra
                        changed = True
                    cb = self.may_block.get(callee)
                    if cb and self.may_block[k] is None:
                        self.may_block[k] = f"{disp}() -> {cb}"
                        changed = True

    # -- rule 2: lock-order graph -------------------------------------------
    def lock_edges(self) -> dict[tuple[str, str], list[tuple[str, int]]]:
        edges: dict[tuple[str, str], list[tuple[str, int]]] = {}
        for f in self.fns.values():
            for lock_id, _re, held, line in f.acquisitions:
                for h in held:
                    if h != lock_id:
                        edges.setdefault((h, lock_id), []).append(
                            (f.module.file, line)
                        )
            for callee, held, line, _d in f.calls:
                for m in self.may_acquire.get(callee, ()):
                    for h in held:
                        if h != m:
                            edges.setdefault((h, m), []).append(
                                (f.module.file, line)
                            )
        return edges

    def diagnostics(self) -> list[RaceDiagnostic]:
        diags: list[RaceDiagnostic] = []

        def emit(
            mi: _ModuleInfo, fn: ast.FunctionDef, line: int, rule: str,
            msg: str,
        ) -> None:
            sup = _suppressed(mi, fn, line)
            if rule in sup or "all" in sup:
                return
            diags.append(
                RaceDiagnostic(mi.file, line, rule, msg, fn.name)
            )

        # -- rule 1: guarded-field inference ---------------------------------
        by_class: dict[str, list[_FnFacts]] = {}
        by_module: dict[str, list[_FnFacts]] = {}
        for f in self.fns.values():
            if f.cls is not None:
                by_class.setdefault(f.cls.name, []).append(f)
            by_module.setdefault(f.module.name, []).append(f)

        _INIT = ("__init__", "__post_init__")
        for cname, fns in by_class.items():
            ci = self.reg.classes[cname]
            if not ci.lock_fields:
                continue
            own_locks = {lid for lid, _k in ci.lock_fields.values()}
            guards: dict[str, set[str]] = {}
            for f in fns:
                if f.node.name in _INIT:
                    continue
                for field, write, held, _line in f.field_accesses:
                    if write and (held & own_locks):
                        guards.setdefault(field, set()).update(
                            held & own_locks
                        )
            for f in fns:
                if f.node.name in _INIT:
                    continue
                for field, write, held, line in f.field_accesses:
                    locks = guards.get(field)
                    if not locks or (held & locks):
                        continue
                    emit(
                        f.module, f.node, line, "unguarded-field",
                        f"{'write to' if write else 'read of'} "
                        f"{cname}.{field} without holding "
                        f"{sorted(locks)} (field is written under that "
                        "lock elsewhere)",
                    )

        for mname, fns in by_module.items():
            mi = fns[0].module
            if not mi.module_locks:
                continue
            mlocks = {lid for lid, _k in mi.module_locks.values()}
            guards = {}
            for f in fns:
                for name, write, held, _line in f.global_accesses:
                    if write and (held & mlocks):
                        guards.setdefault(name, set()).update(held & mlocks)
            for f in fns:
                for name, write, held, line in f.global_accesses:
                    locks = guards.get(name)
                    if not locks or (held & locks):
                        continue
                    emit(
                        f.module, f.node, line, "unguarded-field",
                        f"{'write to' if write else 'read of'} module "
                        f"global {name} without holding {sorted(locks)}",
                    )

        # -- rule 2: cycles + non-reentrant re-acquisition -------------------
        edges = self.lock_edges()
        adj: dict[str, set[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, set()).add(b)
        cycle = _find_cycle(adj)
        if cycle:
            first = edges[(cycle[0], cycle[1])][0]
            mi_fn = self._site_fn(first)
            path = " -> ".join(cycle)
            if mi_fn is not None:
                emit(
                    mi_fn[0], mi_fn[1], first[1], "lock-order-cycle",
                    f"lock acquisition cycle: {path}",
                )
            else:
                diags.append(
                    RaceDiagnostic(
                        first[0], first[1], "lock-order-cycle",
                        f"lock acquisition cycle: {path}",
                    )
                )
        for f in self.fns.values():
            for lock_id, _re, held, line in f.acquisitions:
                if lock_id in held and not self.lock_reentrant.get(
                    lock_id, True
                ):
                    emit(
                        f.module, f.node, line, "lock-order-cycle",
                        f"re-acquisition of non-reentrant {lock_id} "
                        "while already held (self-deadlock)",
                    )
            for callee, held, line, disp in f.calls:
                for m in self.may_acquire.get(callee, ()):
                    if m in held and not self.lock_reentrant.get(m, True):
                        emit(
                            f.module, f.node, line, "lock-order-cycle",
                            f"{disp}() re-acquires non-reentrant {m} "
                            "already held here (self-deadlock)",
                        )

        # -- rule 3: blocking under lock -------------------------------------
        for f in self.fns.values():
            for desc, held, line in f.blocking:
                if held:
                    emit(
                        f.module, f.node, line, "blocking-under-lock",
                        f"{desc} while holding {sorted(held)}",
                    )
            for callee, held, line, disp in f.calls:
                if not held:
                    continue
                cb = self.may_block.get(callee)
                if cb:
                    emit(
                        f.module, f.node, line, "blocking-under-lock",
                        f"{disp}() may block ({cb}) while holding "
                        f"{sorted(held)}",
                    )

        # -- rule 4: state machine -------------------------------------------
        for f in self.fns.values():
            diags.extend(
                d for d in _check_transitions(f)
                if not (
                    _suppressed(f.module, f.node, d.line)
                    & {d.rule, "all"}
                )
            )

        diags.sort(key=lambda d: (d.file, d.line, d.rule))
        return diags

    def suppression_count(self) -> int:
        return sum(
            len(_SUPPRESS_RE.findall("\n".join(m.lines)))
            for m in self.modules
        )

    def _site_fn(self, site: tuple[str, int]):
        for f in self.fns.values():
            if f.module.file == site[0] and (
                f.node.lineno <= site[1] <= max(
                    getattr(f.node, "end_lineno", f.node.lineno),
                    f.node.lineno,
                )
            ):
                return f.module, f.node
        return None


def _find_cycle(adj: dict[str, set[str]]) -> list[str] | None:
    """First cycle found via DFS, as [n0, n1, ..., n0]."""
    WHITE, GREY, BLACK = 0, 1, 2
    color = {n: WHITE for n in adj}
    stack: list[str] = []

    def dfs(n: str) -> list[str] | None:
        color[n] = GREY
        stack.append(n)
        for m in sorted(adj.get(n, ())):
            if color.get(m, WHITE) == GREY:
                i = stack.index(m)
                return stack[i:] + [m]
            if color.get(m, WHITE) == WHITE:
                color.setdefault(m, WHITE)
                found = dfs(m)
                if found:
                    return found
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(adj):
        if color[n] == WHITE:
            found = dfs(n)
            if found:
                return found
    return None


# --------------------------------------------------------------------------
# rule 4: state-machine verification
# --------------------------------------------------------------------------

_TASK_EDGE_SET = set(TASK_TRANSITIONS)
_JOB_EDGE_SET = set(JOB_TRANSITIONS)


def _key(expr: ast.AST) -> str:
    """Stable identity for an lvalue/rvalue expression: the dotted chain
    when one exists ("t.state", "new_state") — ast.dump embeds Load/Store
    ctx, which would keep an if-test fact from ever matching the
    assignment target it guards."""
    d = _dotted(expr)
    return d if d is not None else ast.dump(expr)


def _module_mentions_taskstate(mi: _ModuleInfo) -> bool:
    cached = getattr(mi, "_mentions_taskstate", None)
    if cached is None:
        cached = any(
            (isinstance(n, ast.Name) and n.id == "TaskState")
            or (isinstance(n, ast.ClassDef) and n.name == "TaskState")
            or (
                isinstance(n, ast.ImportFrom)
                and any(a.name == "TaskState" for a in n.names)
            )
            for n in ast.walk(mi.tree)
        )
        mi._mentions_taskstate = cached
    return cached


def _task_const(expr: ast.AST) -> str | None:
    """'pending' for ``TaskState.PENDING`` attribute refs."""
    if (
        isinstance(expr, ast.Attribute)
        and isinstance(expr.value, ast.Name)
        and expr.value.id == "TaskState"
    ):
        return expr.attr.lower()
    return None


def _facts_from_test(test: ast.AST) -> dict[str, set[str]]:
    """expr-dump -> possible states, from an if-test (Eq / In / And)."""
    out: dict[str, set[str]] = {}
    if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
        for v in test.values:
            for k, s in _facts_from_test(v).items():
                out.setdefault(k, set()).update(s)
        return out
    if isinstance(test, ast.Compare) and len(test.ops) == 1:
        left, op, right = test.left, test.ops[0], test.comparators[0]
        if isinstance(op, ast.Eq):
            s = _task_const(right)
            if s is None and isinstance(right, ast.Constant) and (
                isinstance(right.value, str)
            ):
                s = right.value
            if s is not None:
                out[_key(left)] = {s}
        elif isinstance(op, ast.In) and isinstance(
            right, (ast.Tuple, ast.List, ast.Set)
        ):
            states = set()
            for elt in right.elts:
                s = _task_const(elt)
                if s is None and isinstance(elt, ast.Constant) and (
                    isinstance(elt.value, str)
                ):
                    s = elt.value
                if s is not None:
                    states.add(s)
            if states:
                out[_key(left)] = states
    return out


def _fn_has_table_guard(fn: ast.FunctionDef) -> bool:
    """The function gates on the declared table (membership test on
    ``_LEGAL``/``TASK_TRANSITIONS`` or a call to
    ``is_legal_task_transition``)."""
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare) and any(
            isinstance(op, (ast.In, ast.NotIn)) for op in node.ops
        ):
            for comp in node.comparators:
                d = _dotted(comp) or ""
                if d.split(".")[-1] in ("_LEGAL", "TASK_TRANSITIONS"):
                    return True
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d.split(".")[-1] == "is_legal_task_transition":
                return True
    return False


def _check_transitions(f: _FnFacts) -> list[RaceDiagnostic]:
    fn = f.node
    mi = f.module
    mentions_taskstate = _module_mentions_taskstate(mi)
    guarded = _fn_has_table_guard(fn)
    diags: list[RaceDiagnostic] = []

    def check_edges(
        sources: set[str] | None, targets: set[str], table: set,
        names: tuple, kind: str, line: int,
    ) -> None:
        bad_states = [t for t in targets if t not in names]
        if bad_states:
            diags.append(
                RaceDiagnostic(
                    mi.file, line, "undeclared-transition",
                    f"assignment to undeclared {kind} state "
                    f"{bad_states}", fn.name,
                )
            )
            return
        if sources is None:
            if not guarded:
                declared_in = {t for t in targets if any(
                    (s, t) in table for s in names
                )}
                if declared_in != set(targets):
                    diags.append(
                        RaceDiagnostic(
                            mi.file, line, "undeclared-transition",
                            f"{kind} state {sorted(set(targets) - declared_in)} "
                            "has no declared in-edge", fn.name,
                        )
                    )
            return
        for s in sources:
            for t in targets:
                if s != t and (s, t) not in table:
                    diags.append(
                        RaceDiagnostic(
                            mi.file, line, "undeclared-transition",
                            f"{kind} transition {s} -> {t} is not a "
                            "declared edge", fn.name,
                        )
                    )

    def walk(stmts, env: dict[str, set[str]], aliases: dict[str, str]):
        for stmt in stmts:
            if isinstance(stmt, ast.If):
                benv = dict(env)
                benv.update(_facts_from_test(stmt.test))
                walk(stmt.body, benv, dict(aliases))
                walk(stmt.orelse, dict(env), dict(aliases))
                continue
            if isinstance(stmt, (ast.For, ast.While, ast.With)):
                walk(stmt.body, env, aliases)
                walk(getattr(stmt, "orelse", []), env, aliases)
                continue
            if isinstance(stmt, ast.Try):
                walk(stmt.body, env, aliases)
                for h in stmt.handlers:
                    walk(h.body, env, aliases)
                walk(stmt.orelse, env, aliases)
                walk(stmt.finalbody, env, aliases)
                continue
            if isinstance(stmt, ast.FunctionDef):
                continue
            if not (
                isinstance(stmt, ast.Assign)
                and len(stmt.targets) == 1
                and isinstance(stmt.targets[0], ast.Attribute)
            ):
                continue
            target = stmt.targets[0]
            key = _key(target)

            def source_states() -> set[str] | None:
                if key in env:
                    return env[key]
                alias = aliases.get(key)
                if alias is not None and alias in env:
                    return env[alias]
                return None

            if target.attr == "state":
                tconst = _task_const(stmt.value)
                if tconst is not None:
                    check_edges(
                        source_states(), {tconst}, _TASK_EDGE_SET,
                        tuple(s for s, _t in _TASK_EDGE_SET) + tuple(
                            t for _s, t in _TASK_EDGE_SET
                        ),
                        "task", stmt.lineno,
                    )
                    env[key] = {tconst}
                    aliases.pop(key, None)
                elif isinstance(stmt.value, ast.Name) and mentions_taskstate:
                    vkey = _key(stmt.value)
                    if vkey in env:
                        check_edges(
                            source_states(), env[vkey], _TASK_EDGE_SET,
                            tuple(s for s, _t in _TASK_EDGE_SET) + tuple(
                                t for _s, t in _TASK_EDGE_SET
                            ),
                            "task", stmt.lineno,
                        )
                        env[key] = set(env[vkey])
                    elif not guarded:
                        diags.append(
                            RaceDiagnostic(
                                mi.file, stmt.lineno,
                                "undeclared-transition",
                                "dynamic task-state assignment without a "
                                "declared-table guard "
                                "(test membership in TASK_TRANSITIONS/"
                                "_LEGAL first)", fn.name,
                            )
                        )
                    else:
                        env.pop(key, None)
                        aliases[key] = _key(stmt.value)
            elif target.attr == "status" and isinstance(
                stmt.value, ast.Constant
            ) and isinstance(stmt.value.value, str):
                check_edges(
                    source_states(), {stmt.value.value}, _JOB_EDGE_SET,
                    JOB_STATES, "job", stmt.lineno,
                )
                env[key] = {stmt.value.value}
                aliases.pop(key, None)

    walk(fn.body, {}, {})
    return diags


# --------------------------------------------------------------------------
# public API
# --------------------------------------------------------------------------

_DEFAULT_TARGETS = (
    "scheduler",
    "executor",
    "client/flight.py",
    "event_loop.py",
    "standalone.py",
    "testing/faults.py",
    # streaming-pipeline primitives: bounded-queue handoff between
    # background workers and consuming generators (the shuffle reader's
    # overlapped fetch lives in executor/, covered above; the scan
    # prefetch pipeline lives here)
    "exec/pipeline.py",
    # observability plane (PR 10): the trace ring/outbox is written by
    # every task thread and drained by the poll/heartbeat loops
    "obs",
)


def _target_files(paths=None) -> list[pathlib.Path]:
    if paths is not None:
        out = []
        for p in paths:
            p = pathlib.Path(p)
            out.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
        return out
    root = pathlib.Path(__file__).resolve().parent.parent
    files: list[pathlib.Path] = []
    for sub in _DEFAULT_TARGETS:
        p = root / sub
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    return files


def _load(paths=None) -> _Analysis:
    modules = [
        _collect_module(f.read_text(), str(f)) for f in _target_files(paths)
    ]
    return _Analysis(modules)


def analyze(paths=None) -> _Analysis:
    """Parse + analyze the targets ONCE; the returned object answers
    ``.diagnostics()``, ``.lock_edges()``, and ``.suppression_count()``
    without re-reading anything (the combined CLI gate uses this)."""
    return _load(paths)


def lint_paths(paths=None) -> list[RaceDiagnostic]:
    """Analyze files/directories (default: the concurrent control plane)."""
    return _load(paths).diagnostics()


def lint_source(source: str, filename: str = "synth.py") -> list[RaceDiagnostic]:
    """Single-module convenience for tests."""
    return _Analysis([_collect_module(source, filename)]).diagnostics()


def lock_order_graph(
    paths=None,
) -> dict[tuple[str, str], list[tuple[str, int]]]:
    """The static lock acquisition-order graph: ``(held, acquired) ->
    [(file, line), ...]``. Shares node names with the runtime witness."""
    return _load(paths).lock_edges()


def lock_order_dot(paths=None) -> str:
    """Graphviz dump of the lock-order graph (``--dot``)."""
    edges = lock_order_graph(paths)
    out = ["digraph lock_order {"]
    for (a, b), sites in sorted(edges.items()):
        f, line = sites[0]
        label = f"{pathlib.Path(f).name}:{line}"
        out.append(f'  "{a}" -> "{b}" [label="{label}"];')
    out.append("}")
    return "\n".join(out)


def suppression_count(paths=None) -> int:
    """Number of ``# racelint: disable=`` escape hatches in the targets."""
    n = 0
    for f in _target_files(paths):
        n += len(_SUPPRESS_RE.findall(f.read_text()))
    return n
