"""Runtime lock-order witness (the dynamic half of ``racelint``).

The static lock-order graph (:mod:`ballista_tpu.analysis.racelint`) proves
no *syntactically reachable* acquisition cycle exists; this module checks
the orders that are *actually taken* at runtime. Every control-plane lock
is created through :func:`make_lock`. In normal operation that returns a
plain ``threading.Lock``/``RLock`` — zero overhead, nothing recorded. In
debug mode (``BALLISTA_LOCK_WITNESS=1`` in the environment, or
:func:`enable` before the locks are constructed) it returns a
:class:`TracedLock` that

- keeps a per-thread stack of held lock names,
- records every ordered pair ``(held -> acquiring)`` into a global edge
  set, and
- flags an inversion the moment a thread acquires ``A`` while holding
  ``B`` after some thread acquired ``B`` while holding ``A`` (a runtime
  deadlock hazard even if the test run got lucky with timing).

Tests enable it around a cluster run, then assert
:func:`violations` is empty and :func:`assert_consistent` against the
static graph — witnessed orders must never invert a statically-derived
edge (a witnessed edge the static pass missed is reported too, as a
coverage gap, but only inversions fail).

Re-entrant re-acquisition of the same named lock never records an edge
(that is what RLock is for); the witness's own bookkeeping lock is plain
and its critical sections call no user code, so it cannot participate in
any cycle it reports.
"""

from __future__ import annotations

import logging
import os
import threading

log = logging.getLogger(__name__)

ENV_WITNESS = "BALLISTA_LOCK_WITNESS"

_enabled = os.environ.get(ENV_WITNESS, "") in ("1", "true", "yes")
_tls = threading.local()

_registry_lock = threading.Lock()
# (held_name, acquired_name) -> number of times witnessed
_edges: dict[tuple[str, str], int] = {}
# inversions observed live: (edge, reversed-edge-already-witnessed, thread)
_violations: list[dict] = []


def enable(flag: bool = True) -> None:
    """Turn the witness on/off for locks created AFTER this call."""
    global _enabled
    _enabled = flag


def enabled() -> bool:
    return _enabled


def _held_stack() -> list[str]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _has_path(src: str, dst: str, edges: set[tuple[str, str]]) -> bool:
    """DFS reachability src -> dst over the witnessed edge set."""
    seen = {src}
    frontier = [src]
    while frontier:
        node = frontier.pop()
        if node == dst:
            return True
        for a, b in edges:
            if a == node and b not in seen:
                seen.add(b)
                frontier.append(b)
    return False


class TracedLock:
    """Lock wrapper recording acquisition order per thread."""

    def __init__(self, name: str, reentrant: bool = False):
        self.name = name
        self.reentrant = reentrant
        self._lock = threading.RLock() if reentrant else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok:
            self._record_acquired()
        return ok

    def release(self) -> None:
        stack = _held_stack()
        # releases are almost always LIFO; tolerate out-of-order by
        # dropping the LAST occurrence of this name
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._lock.release()

    def __enter__(self) -> "TracedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def _record_acquired(self) -> None:
        stack = _held_stack()
        if self.name in stack:  # re-entrant: no new ordering established
            stack.append(self.name)
            return
        held = [n for n in dict.fromkeys(stack)]  # distinct, order kept
        stack.append(self.name)
        if not held:
            return
        with _registry_lock:
            snapshot = set(_edges)
            for h in held:
                edge = (h, self.name)
                first_time = edge not in _edges
                _edges[edge] = _edges.get(edge, 0) + 1
                if first_time and _has_path(self.name, h, snapshot):
                    v = {
                        "edge": edge,
                        "thread": threading.current_thread().name,
                        "held": list(held),
                    }
                    _violations.append(v)
                    log.error(
                        "lock-order inversion witnessed: %s -> %s "
                        "(thread %s, holding %s) — reverse order was "
                        "witnessed earlier", h, self.name, v["thread"], held,
                    )

    def locked(self) -> bool:
        return self._lock.locked() if hasattr(self._lock, "locked") else False


def make_lock(name: str, reentrant: bool = False):
    """Create a control-plane lock. ``name`` must be the racelint-qualified
    identity (``ClassName._lockfield`` or ``module._LOCK_GLOBAL``) so the
    witnessed graph and the static graph share a vocabulary."""
    if not _enabled:
        return threading.RLock() if reentrant else threading.Lock()
    return TracedLock(name, reentrant=reentrant)


def edges() -> dict[tuple[str, str], int]:
    with _registry_lock:
        return dict(_edges)


def violations() -> list[dict]:
    with _registry_lock:
        return list(_violations)


def reset() -> None:
    with _registry_lock:
        _edges.clear()
        _violations.clear()


def assert_consistent(static_edges) -> None:
    """Witnessed orders must not invert the static lock-order graph: for
    every witnessed edge ``A -> B``, the static graph must not contain a
    path ``B`` ⇝ ``A``. Witnessed edges absent from the static graph are
    allowed (the static pass is conservative about call resolution) but
    inversions are exactly the deadlocks the static gate exists to stop.
    Raises ``AssertionError`` naming the offending pair."""
    static = {(a, b) for a, b in static_edges}
    witnessed = edges()
    problems = []
    for a, b in witnessed:
        if _has_path(b, a, static):
            problems.append(
                f"witnessed {a} -> {b} but the static graph orders "
                f"{b} before {a}"
            )
    live = violations()
    for v in live:
        problems.append(
            f"runtime inversion: {v['edge'][0]} -> {v['edge'][1]} "
            f"(thread {v['thread']}, holding {v['held']})"
        )
    assert not problems, "; ".join(problems)
