"""Static analysis subsystem (``planlint``).

Three passes that move whole classes of executor-runtime failures to
submission/collection time:

- :mod:`ballista_tpu.analysis.verifier` — pre-execution plan verification
  (schema agreement, column resolution, TPU dtype legality, shuffle
  partition-count consistency, stage-DAG well-formedness), wired into every
  submission path behind ``ballista.tpu.verify_plans``.
- :mod:`ballista_tpu.analysis.serde_audit` — structural closure audit of the
  plan/expression serde vocabulary: every node class either round-trips
  byte-stably through the proto codec or is explicitly exempted.
- :mod:`ballista_tpu.analysis.jaxlint` — AST lint for JAX/TPU hazards
  (tracer branching, host sync inside jit, missing static_argnames,
  dynamic-shape primitives) over ``ops/`` and ``exec/``, plus a per-kernel
  static signature report.
"""

from ballista_tpu.errors import PlanVerificationError  # noqa: F401
from ballista_tpu.analysis.verifier import (  # noqa: F401
    VerifyReport,
    sql_span,
    verify_logical,
    verify_physical,
    verify_stages,
)
