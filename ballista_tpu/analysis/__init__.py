"""Static analysis subsystem (``planlint``/``racelint``/``lifelint``).

Passes that move whole classes of executor-runtime failures to
submission/collection time — run together via
``python -m ballista_tpu.analysis``:

- :mod:`ballista_tpu.analysis.verifier` — pre-execution plan verification
  (schema agreement, column resolution, TPU dtype legality, shuffle
  partition-count consistency, stage-DAG well-formedness), wired into every
  submission path behind ``ballista.tpu.verify_plans``.
- :mod:`ballista_tpu.analysis.serde_audit` — structural closure audit of the
  plan/expression serde vocabulary: every node class either round-trips
  byte-stably through the proto codec or is explicitly exempted.
- :mod:`ballista_tpu.analysis.jaxlint` — AST lint for JAX/TPU hazards
  (tracer branching, host sync inside jit, missing static_argnames,
  dynamic-shape primitives) over ``ops/`` and ``exec/``, plus a per-kernel
  static signature report.
- :mod:`ballista_tpu.analysis.racelint` — lock-discipline + state-machine
  lint over the concurrent control plane (guarded-field inference,
  lock-order cycles, blocking-under-lock, declared status transitions),
  with the canonical transition tables in
  :mod:`ballista_tpu.analysis.statemachine` and a runtime lock-order
  witness in :mod:`ballista_tpu.analysis.witness`
  (``BALLISTA_LOCK_WITNESS=1``).
- :mod:`ballista_tpu.analysis.lifelint` — resource-lifecycle + error-
  taxonomy lint over the control & data planes (leaked
  channels/pools/files/mmaps/spill sets, releases missing from
  exception/cancellation edges, raises outside the errors.py
  retryable/non-retryable taxonomy, swallowed errors, untyped
  fault-injection handlers), with a runtime resource witness in
  :mod:`ballista_tpu.analysis.reswitness`
  (``BALLISTA_RESOURCE_WITNESS=1``).
- :mod:`ballista_tpu.analysis.protodrift` — proto text ↔ generated
  descriptor agreement (protoc-less descriptor mutations) plus the
  committed field-number ledger (``proto/field_numbers.json``).
- :mod:`ballista_tpu.analysis.configlint` — config-key & env-var
  registry closure with the generated ``docs/config.md`` table.
- :mod:`ballista_tpu.analysis.eqlint` — the no-uncertified-mutation
  closure over physical plans: direct writes to structural plan fields
  outside the certified rewrite API (``ballista_tpu/rewrite.py``) are
  findings, so every plan mutation carries a machine-checkable
  equivalence certificate.
- :mod:`ballista_tpu.analysis.detlint` — determinism lint over the data
  plane and plan pipeline (unordered set iteration, undeclared RNG,
  wall-clock reads in kernels, completion-order-dependent merges), with
  its runtime counterpart in :mod:`ballista_tpu.analysis.replay`
  (``BALLISTA_REPLAY_WITNESS=1``): canonical content hashes proving
  retries, lineage recomputes, and certified rewrites replay bit-exact.

Suppression budgets for all AST analyzers live in the single ledger
:mod:`ballista_tpu.analysis.budget`.
"""

from ballista_tpu.errors import PlanVerificationError  # noqa: F401
from ballista_tpu.analysis.verifier import (  # noqa: F401
    VerifyReport,
    sql_span,
    verify_logical,
    verify_physical,
    verify_stages,
)
