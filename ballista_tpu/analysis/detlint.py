"""detlint: determinism lint over the data plane and the plan pipeline.

Bit-exactness under fault injection — the invariant the replay witness
(analysis/replay.py) checks at runtime — dies by a thousand innocuous
cuts: a ``set`` iteration whose order leaks into plan construction, an
unseeded RNG in partition routing, a float reduction folded in task-
completion order. This lint flags those patterns statically, over
``ops/``, ``exec/``, ``executor/``, ``scheduler/``, and
``compilecache/``:

=====================  ====================================================
rule                   rationale
=====================  ====================================================
unordered-iteration    Iterating a ``set``/``frozenset`` (literal,
                       comprehension, constructor call, set-typed local or
                       ``self`` attribute, or a call whose annotation
                       returns ``set``) in an ORDER-SENSITIVE position
                       (``for``, comprehensions, ``list``/``tuple``/
                       ``enumerate``/``join``): Python set order varies
                       with PYTHONHASHSEED and insertion history, so
                       anything built from the walk — plan children, serde
                       output, partition routing — varies run to run.
                       Wrap in ``sorted(...)`` or declare the
                       nondeterminism.
undeclared-rng         ``random.*`` / ``np.random.*`` without a declared
                       seed or an explicit nondeterminism declaration.
                       Control-plane placement choices (the scheduler's
                       random stage pick) are legitimately nondeterministic
                       — they must SAY so with ``# detlint: nondet=<why>``
                       so the data plane stays provably seeded.
                       (``jax.random`` is exempt: its explicit-key API is
                       deterministic by construction.)
wallclock-in-dataplane ``time.time()`` inside ``ops/``/``exec/``/
                       ``compilecache/``: a wall-clock read in a kernel or
                       operator is either dead code or a value that varies
                       per run. Metrics timers use ``perf_counter`` via
                       ``Metrics.time`` and are exempt by construction.
reduction-order        Augmented accumulation (``acc += ...``) inside a
                       loop over ``as_completed(...)`` or
                       ``imap_unordered(...)``: float addition is not
                       associative, so a partial-aggregate merge folded in
                       completion order differs run to run in the last
                       ULP — the chaos suites' bit-exact assertions are
                       exactly what this breaks.
completion-order       ``yield``/``.append(...)``/``.extend(...)`` inside
                       a completion-ordered loop: result order then
                       depends on thread scheduling (the overlapped-fetch
                       merge hazard — the shipped reader consumes
                       per-location queues in LOCATION order for exactly
                       this reason, docs/shuffle.md).
=====================  ====================================================

Declared nondeterminism: ``# detlint: nondet=<why>`` on the line or the
enclosing ``def`` line declares a site deliberately nondeterministic
(control-plane placement, id minting); :func:`nondet_sites` enumerates
them and the tier-1 suite pins the list. Suppression:
``# detlint: disable=<rule>`` with the shared budget ledger
(analysis/budget.py)."""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

RULES: dict[str, str] = {
    "unordered-iteration": "set iteration in an order-sensitive position",
    "undeclared-rng": "random.* without a declared seed or nondet note",
    "wallclock-in-dataplane": "time.time() inside ops//exec//compilecache/",
    "reduction-order": "accumulation folded in task-completion order",
    "completion-order": "output order depends on thread completion order",
}

_SUPPRESS_RE = re.compile(r"#\s*detlint:\s*disable=([A-Za-z0-9_,\- ]+)")
_NONDET_RE = re.compile(r"#\s*detlint:\s*nondet=([A-Za-z0-9_\-]+)")

TARGET_DIRS = ("ops", "exec", "executor", "scheduler", "compilecache", "obs")
# wall-clock reads are only categorically wrong in the data plane proper;
# the control plane legitimately timestamps (heartbeats, TTLs, deadlines)
WALLCLOCK_DIRS = ("ops", "exec", "compilecache")

_COMPLETION_ITERS = ("as_completed", "imap_unordered")
_ORDER_SENSITIVE_CALLS = frozenset({"list", "tuple", "enumerate"})


@dataclasses.dataclass(frozen=True)
class DetDiagnostic:
    file: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


def _package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[1]


def target_files(paths=None) -> list[pathlib.Path]:
    if paths is not None:
        return [pathlib.Path(p) for p in paths]
    root = _package_root()
    out: list[pathlib.Path] = []
    for d in TARGET_DIRS:
        out.extend(sorted((root / d).glob("*.py")))
    return out


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ann_is_set(ann: ast.AST | None) -> bool:
    if ann is None:
        return False
    if isinstance(ann, ast.Name):
        return ann.id in ("set", "frozenset")
    if isinstance(ann, ast.Subscript):
        return _ann_is_set(ann.value)
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return ann.value.strip().startswith(("set", "frozenset"))
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, source: str, filename: str):
        self.filename = filename
        self.lines = source.splitlines()
        self.diags: list[DetDiagnostic] = []
        self.fn_stack: list[int] = []  # def line numbers
        self.set_locals_stack: list[set[str]] = [set()]
        # self.<attr> assigned a set construct in any method
        self.set_attrs: set[str] = set()
        # functions whose return annotation is set-typed
        self.set_returning: set[str] = set()
        self.completion_loop_depth = 0
        self.stmt_line = 0  # first line of the enclosing statement
        tree = ast.parse(source, filename=filename)
        # pre-pass: set-typed attributes + set-returning defs
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if _ann_is_set(node.returns):
                    self.set_returning.add(node.name)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets
                    if isinstance(node, ast.Assign)
                    else [node.target]
                )
                value = node.value
                if value is not None and self._is_set_expr_shallow(value):
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            self.set_attrs.add(t.attr)
        self.visit(tree)

    # -- plumbing -------------------------------------------------------------
    def visit(self, node):
        if isinstance(node, ast.stmt):
            self.stmt_line = node.lineno
        return super().visit(node)

    def _marked(self, line: int, kinds=("disable", "nondet")) -> set[str]:
        # honored on the flagged line, the enclosing statement's first
        # line (multi-line calls), or the enclosing def line
        out: set[str] = set()
        for ln in [line, self.stmt_line] + self.fn_stack[-1:]:
            if ln < 1 or ln > len(self.lines):
                continue
            text = self.lines[ln - 1]
            if "disable" in kinds:
                m = _SUPPRESS_RE.search(text)
                if m:
                    out |= {t.strip() for t in m.group(1).split(",")}
            if "nondet" in kinds and _NONDET_RE.search(text):
                out.add("__nondet__")
        return out

    def _emit(self, node: ast.AST, rule: str, msg: str) -> None:
        marks = self._marked(node.lineno)
        if rule in marks or "all" in marks or "__nondet__" in marks:
            return
        self.diags.append(
            DetDiagnostic(self.filename, node.lineno, rule, msg)
        )

    # -- set-typed expression inference ---------------------------------------
    def _is_set_expr_shallow(self, node: ast.AST) -> bool:
        """Syntactically a set, without local-name context (pre-pass)."""
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname in ("set", "frozenset"):
                return True
            if isinstance(node.func, ast.Attribute) and node.func.attr in (
                "intersection",
                "union",
                "difference",
                "symmetric_difference",
            ):
                return True
        return False

    def _is_set_expr(self, node: ast.AST) -> bool:
        if self._is_set_expr_shallow(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in self.set_locals_stack[-1]
        if (
            isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        ):
            return node.attr in self.set_attrs
        if isinstance(node, ast.Call):
            fname = _dotted(node.func)
            if fname is not None and (
                fname.split(".")[-1] in self.set_returning
            ):
                return True
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitOr, ast.BitAnd, ast.Sub)
        ):
            return self._is_set_expr(node.left) or self._is_set_expr(
                node.right
            )
        return False

    # -- visitors -------------------------------------------------------------
    def visit_FunctionDef(self, node):
        self.fn_stack.append(node.lineno)
        self.set_locals_stack.append(set())
        self.generic_visit(node)
        self.set_locals_stack.pop()
        self.fn_stack.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Assign(self, node):
        if self._is_set_expr(node.value):
            for t in node.targets:
                if isinstance(t, ast.Name):
                    self.set_locals_stack[-1].add(t.id)
        self.generic_visit(node)

    def _check_iter(self, iter_node: ast.AST, where: ast.AST) -> None:
        if self._is_set_expr(iter_node):
            self._emit(
                where,
                "unordered-iteration",
                "iteration over a set in an order-sensitive position — "
                "wrap in sorted(...) or declare with "
                "# detlint: nondet=<why>",
            )

    def _is_completion_iter(self, iter_node: ast.AST) -> bool:
        if not isinstance(iter_node, ast.Call):
            return False
        fname = _dotted(iter_node.func) or ""
        return fname.split(".")[-1] in _COMPLETION_ITERS

    def visit_For(self, node):
        self._check_iter(node.iter, node)
        completion = self._is_completion_iter(node.iter)
        if completion and self.completion_loop_depth == 0:
            # the scan walks the whole body, so a nested completion loop
            # is already covered — re-scanning it would double-emit
            self._scan_completion_body(node)
        if completion:
            self.completion_loop_depth += 1
        self.generic_visit(node)
        if completion:
            self.completion_loop_depth -= 1

    def _scan_completion_body(self, loop: ast.For) -> None:
        for sub in ast.walk(loop):
            if sub is loop:
                continue
            if isinstance(sub, ast.AugAssign) and isinstance(
                sub.op, (ast.Add, ast.Mult)
            ):
                self._emit(
                    sub,
                    "reduction-order",
                    "accumulation inside a completion-ordered loop: float "
                    "folds are not associative — collect then fold in a "
                    "canonical (submission-index) order",
                )
            elif isinstance(sub, (ast.Yield, ast.YieldFrom)):
                self._emit(
                    sub,
                    "completion-order",
                    "yield inside a completion-ordered loop: result order "
                    "depends on thread scheduling — re-order by "
                    "submission index before yielding",
                )
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr in ("append", "extend")
            ):
                self._emit(
                    sub,
                    "completion-order",
                    "ordered-output build inside a completion-ordered "
                    "loop — index results by submission order instead",
                )

    def visit_comprehension_node(self, node):
        for gen in node.generators:
            # anchor the finding (and its marker lookup) at the iterable
            self._check_iter(gen.iter, gen.iter)
        self.generic_visit(node)

    visit_ListComp = visit_comprehension_node
    visit_GeneratorExp = visit_comprehension_node
    visit_DictComp = visit_comprehension_node

    def visit_Call(self, node):
        fname = _dotted(node.func) or ""
        base = fname.split(".")[-1]
        # list(<set>) / tuple(<set>) / enumerate(<set>) / s.join(<set>)
        if (
            fname in _ORDER_SENSITIVE_CALLS or base == "join"
        ) and node.args:
            if self._is_set_expr(node.args[0]):
                self._emit(
                    node,
                    "unordered-iteration",
                    f"{base}() over a set is order-sensitive — wrap in "
                    "sorted(...)",
                )
        # undeclared RNG (jax.random is explicit-key deterministic)
        if (
            fname.startswith("random.") or ".random." in f".{fname}"
        ) and not fname.startswith("jax."):
            self._emit(
                node,
                "undeclared-rng",
                f"{fname}() without a declared seed — seed it, or declare "
                "with # detlint: nondet=<why> if this is deliberate "
                "control-plane nondeterminism",
            )
        if fname in ("time.time", "time.time_ns") and any(
            f"/{d}/" in self.filename.replace("\\", "/")
            or self.filename.replace("\\", "/").startswith(f"{d}/")
            for d in WALLCLOCK_DIRS
        ):
            self._emit(
                node,
                "wallclock-in-dataplane",
                "wall-clock read in the data plane — a per-run-varying "
                "value in a kernel/operator path (metrics timers use "
                "Metrics.time / perf_counter)",
            )
        self.generic_visit(node)


def lint_source(source: str, filename: str = "<memory>") -> list[DetDiagnostic]:
    return _Linter(source, filename).diags


def lint_paths(paths=None) -> list[DetDiagnostic]:
    out: list[DetDiagnostic] = []
    root = _package_root().parent
    for f in target_files(paths):
        rel = str(f.relative_to(root)) if f.is_relative_to(root) else str(f)
        out.extend(lint_source(f.read_text(), rel))
    return out


def nondet_sites(paths=None) -> list[tuple[str, int, str]]:
    """Every declared-nondeterminism site: (file, line, why). Enumerable
    so the tier-1 suite pins the list — a new deliberate nondeterminism
    must show up in a test diff, exactly like lifelint's ownership
    transfers."""
    out: list[tuple[str, int, str]] = []
    root = _package_root().parent
    for f in target_files(paths):
        rel = str(f.relative_to(root)) if f.is_relative_to(root) else str(f)
        for i, line in enumerate(f.read_text().splitlines(), 1):
            m = _NONDET_RE.search(line)
            if m:
                out.append((rel, i, m.group(1)))
    return out


def suppression_count(paths=None) -> int:
    n = 0
    for f in target_files(paths):
        n += len(_SUPPRESS_RE.findall(f.read_text()))
    return n
