"""configlint: config-key & env-var registry closure.

Two configuration surfaces exist: string-keyed session settings
(``ballista.*`` — validated by :class:`~ballista_tpu.config.BallistaConfig`
against its closed entry table) and process-scoped ``BALLISTA_*``
environment variables (daemons, debug witnesses, cache dirs — declared in
``config.ENV_REGISTRY`` since PR 8). The session side has always rejected
unknown keys at runtime; nothing checked the env side, and nothing
checked that every READ SITE in the tree goes through a declared entry —
a new ``os.environ.get("BALLISTA_…")`` added in a hot fix becomes an
undocumented, untyped, silently-defaulted knob.

configlint closes both, statically:

- every string literal shaped like a config key (``ballista.foo.bar``)
  anywhere in ``ballista_tpu/`` must be a declared
  :class:`~ballista_tpu.config.ConfigEntry` (or the task-scoped
  ``ballista.internal.`` prefix);
- every ``os.environ`` read/write of a ``BALLISTA_*`` name — literal or
  f-string with a literal prefix — must resolve to exactly one
  ``ENV_REGISTRY`` entry (prefix families like ``BALLISTA_SCHEDULER_*``
  cover the daemons' per-flag overrides);
- ``docs/config.md`` is GENERATED from the two registries
  (:func:`render_config_docs`) and a tier-1 test pins the committed file
  to the generated content, so the docs table cannot drift from the code.

At runtime, :func:`ballista_tpu.config.warn_unknown_env` (wired into
cluster/daemon start) warns once about set-but-undeclared ``BALLISTA_*``
vars — the typo'd-knob case static analysis cannot see.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

_KEY_RE = re.compile(r"^ballista\.[a-z0-9_]+(\.[a-z0-9_]+)*$")


@dataclasses.dataclass(frozen=True)
class ConfigDiagnostic:
    file: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


def _package_files() -> list[pathlib.Path]:
    root = pathlib.Path(__file__).resolve().parent.parent
    return [
        f for f in sorted(root.rglob("*.py"))
        if "proto" not in f.parts  # generated descriptors
    ]


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _env_name_arg(arg: ast.AST) -> tuple[str, bool] | None:
    """(name-or-prefix, is_prefix) for a literal or f-string env name."""
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value, False
    if isinstance(arg, ast.JoinedStr):
        prefix = ""
        for v in arg.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                prefix += v.value
            else:
                break
        return prefix, True
    return None


def _check_file(
    path: pathlib.Path, valid_keys: frozenset, internal_prefix: str,
    env_entry_for, diags: list[ConfigDiagnostic],
    source: str | None = None,
) -> tuple[int, int]:
    src = path.read_text() if source is None else source
    tree = ast.parse(src, filename=str(path))
    is_registry = path.name == "config.py"
    n_keys = n_env = 0
    for node in ast.walk(tree):
        # ---- env reads: os.environ.get/pop/setdefault + subscripts -----
        name_node = None
        if isinstance(node, ast.Call):
            d = _dotted(node.func) or ""
            if d.endswith(("environ.get", "environ.pop",
                           "environ.setdefault")) and node.args:
                name_node = node.args[0]
        elif isinstance(node, ast.Subscript):
            d = _dotted(node.value) or ""
            if d.endswith("environ"):
                name_node = node.slice
        if name_node is not None:
            got = _env_name_arg(name_node)
            if got is not None:
                name, is_prefix = got
                if name.startswith("BALLISTA"):
                    n_env += 1
                    if is_prefix:
                        # a computed name needs a declared * family
                        entry = env_entry_for(name + "X")
                        if entry is not None and not entry.name.endswith(
                            "*"
                        ):
                            entry = None
                    else:
                        entry = env_entry_for(name)
                    if entry is None:
                        diags.append(
                            ConfigDiagnostic(
                                str(path), node.lineno, "unknown-env",
                                f"env var {name + ('…' if is_prefix else '')!r}"
                                " read here has no config.ENV_REGISTRY "
                                "entry (type/default/doc) — declare it",
                            )
                        )
            continue
        # ---- config-key literals ---------------------------------------
        if (
            not is_registry
            and isinstance(node, ast.Constant)
            and isinstance(node.value, str)
            and _KEY_RE.match(node.value)
        ):
            n_keys += 1
            key = node.value
            if key in valid_keys or key.startswith(internal_prefix) or (
                internal_prefix.startswith(key)
            ):
                continue
            diags.append(
                ConfigDiagnostic(
                    str(path), node.lineno, "unknown-config-key",
                    f"config key literal {key!r} is not a declared "
                    "ConfigEntry (config.py) — unknown keys raise "
                    "ConfigError at runtime",
                )
            )
    return n_keys, n_env


def lint_tree() -> tuple[list[ConfigDiagnostic], str]:
    """Scan the package; returns (diagnostics, summary)."""
    from ballista_tpu import config as cfg

    valid_keys = frozenset(cfg._entries().keys())
    diags: list[ConfigDiagnostic] = []
    n_keys = n_env = 0
    for f in _package_files():
        k, e = _check_file(
            f, valid_keys, cfg.BALLISTA_INTERNAL_PREFIX,
            cfg.env_entry_for, diags,
        )
        n_keys += k
        n_env += e
    summary = (
        f"{n_keys} config-key literals + {n_env} env read sites resolve "
        f"to {len(valid_keys)} declared keys / "
        f"{len(cfg.ENV_REGISTRY)} env entries"
    )
    return diags, summary


def lint_source(
    source: str, filename: str = "synth.py"
) -> list[ConfigDiagnostic]:
    """Single-source convenience for tests."""
    from ballista_tpu import config as cfg

    diags: list[ConfigDiagnostic] = []
    valid_keys = frozenset(cfg._entries().keys())
    _check_file(
        pathlib.Path(filename), valid_keys, cfg.BALLISTA_INTERNAL_PREFIX,
        cfg.env_entry_for, diags, source=source,
    )
    return diags


# --------------------------------------------------------------------------
# generated docs
# --------------------------------------------------------------------------

_PARSER_KINDS = {
    "int": "int",
    "float": "float",
    "str": "str",
    "_parse_bool": "bool",
    "_parse_shuffle_compression": "none|lz4|zstd",
    "_parse_prewarm": "off|on|background",
    "_parse_capacity_buckets": "ladder spec",
    "_parse_trace": "off|on|path",
    "_parse_metrics_collector": "shipping|logging",
}


def _md(s: str) -> str:
    return re.sub(r"\s+", " ", s).strip().replace("|", "\\|")


def render_config_docs() -> str:
    """docs/config.md content, generated from the two registries. The
    committed file is pinned to this output by a tier-1 test — edit the
    registries, then regenerate with
    ``python -m ballista_tpu.analysis --write-config-docs``."""
    from ballista_tpu import config as cfg

    out = [
        "# Configuration reference",
        "",
        "<!-- GENERATED by ballista_tpu/analysis/configlint.py —",
        "     do not edit by hand; regenerate with",
        "     `python -m ballista_tpu.analysis --write-config-docs` -->",
        "",
        "Two configuration surfaces (docs/analysis.md § config-registry):",
        "**session settings** travel with every query, are validated "
        "against the closed table below (unknown keys raise "
        "`ConfigError`), and are read through typed getters on "
        "`BallistaConfig`; **environment variables** are process-scoped "
        "(daemon flags, debug witnesses, cache locations) and are "
        "declared in `config.ENV_REGISTRY` — the `configlint` analyzer "
        "proves every read site in the tree resolves to a declared "
        "entry, and `config.warn_unknown_env()` warns at cluster/daemon "
        "start about set-but-undeclared `BALLISTA_*` names.",
        "",
        "## Session settings (`ballista.*`)",
        "",
        "| key | type | default | description |",
        "|---|---|---|---|",
    ]
    for name, e in sorted(cfg._entries().items()):
        kind = _PARSER_KINDS.get(
            getattr(e.parse, "__name__", ""), "str"
        )
        default = e.default if e.default != "" else "''"
        out.append(
            f"| `{name}` | {kind} | `{default}` | {_md(e.description)} |"
        )
    out += [
        "",
        "## Environment variables (`BALLISTA_*`)",
        "",
        "| variable | value | default | description | doc |",
        "|---|---|---|---|---|",
    ]
    for e in cfg.ENV_REGISTRY:
        default = e.default if e.default != "" else "''"
        out.append(
            f"| `{e.name}` | {e.kind} | `{default}` | "
            f"{_md(e.description)} | {e.doc} |"
        )
    out += [
        "",
        "Task-scoped internal props (`ballista.internal.*`) are stamped "
        "by the scheduler onto task definitions and stripped before "
        "session-config validation — they are not settable.",
        "",
    ]
    return "\n".join(out)


def docs_path() -> pathlib.Path:
    return (
        pathlib.Path(__file__).resolve().parents[2] / "docs" / "config.md"
    )


def run() -> tuple[bool, str]:
    """The combined-gate entry point: registry closure over the tree AND
    the generated-docs pin."""
    diags, summary = lint_tree()
    problems = [str(d) for d in diags]
    dp = docs_path()
    if not dp.exists():
        problems.append(
            f"{dp} missing — generate with "
            "`python -m ballista_tpu.analysis --write-config-docs`"
        )
    elif dp.read_text() != render_config_docs():
        problems.append(
            f"{dp} is stale vs the registries — regenerate with "
            "`python -m ballista_tpu.analysis --write-config-docs`"
        )
    if problems:
        return False, "\n".join(problems)
    return True, summary + "; docs/config.md in sync"
