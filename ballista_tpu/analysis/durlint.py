"""durlint: distributed-durability static analysis over the declared
state registry.

Four rule families, proven over the AST of the scheduler control plane
(same engine style as stalelint; ``# durlint: disable=<rule>``
suppressions are honored on the flagged line, its enclosing statement,
or the enclosing ``def`` line, and count against the shared
``analysis/budget.py`` ledger):

- **undeclared-state** — every mutable container assigned to ``self``
  anywhere in a declared control-plane class
  (:data:`~ballista_tpu.analysis.durreg.CONTROL_CLASSES`), and EVERY
  dataclass field of ``JobInfo``, must resolve to a declared
  :class:`~ballista_tpu.analysis.durreg.StateEntry` anchor. New
  scheduler state cannot land without writing down whether a restart
  keeps it, rebuilds it, or legitimately loses it.
- **unpersisted-mutation** — every mutator named in a declared
  :class:`~ballista_tpu.analysis.durreg.PersistenceContract` must
  contain a call whose dotted name ends with each required persistence
  suffix. Dropping ``self.state.save_job(job)`` from
  ``_on_job_failed`` is a gate failure — the job would vanish from the
  backend while its terminal status exists only in dying memory.
- **recovery-gap** — every ``persisted`` entry's declared load method
  must actually be CALLED in ``_recover_state`` (write-only durability:
  a key that is saved religiously and never read back survives every
  restart while recovering nothing).
- **unguarded-backend-write** — ``backend.put``/``backend.delete``
  calls in the sweep must sit lexically inside
  ``with <...>.lock():`` or in a declared
  :class:`~ballista_tpu.analysis.durreg.WriteSeam` — a lock-free
  read-modify-write against shared etcd is the split-brain shape that
  corrupts two-scheduler deployments.

Runtime counterpart: :mod:`ballista_tpu.analysis.durwitness`
(``BALLISTA_DUR_WITNESS=1``) — a restarted scheduler's recovered state
is diffed against the declared durability classes.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

from ballista_tpu.analysis import durreg
from ballista_tpu.analysis.stalelint import _Marked, _call_name, _dotted

_SUPPRESS_RE = re.compile(r"#\s*durlint:\s*disable=([A-Za-z0-9_,\- ]+)")

RULES = {
    "undeclared-state": "mutable control-plane state not declared in "
    "analysis/durreg.py",
    "unpersisted-mutation": "declared mutator dropped a required "
    "persistence call",
    "recovery-gap": "persisted key written but never read back in "
    "_recover_state",
    "unguarded-backend-write": "state-backend write outside the "
    "lock/ownership seam",
}

# Files swept: the scheduler control plane plus the history log (the
# one declared write seam outside scheduler/).
TARGET_DIR = "scheduler"
TARGET_MODULES = ("obs/history.py",)

# container shapes that count as mutable state for undeclared-state
_CONTAINER_CALLS = (
    "dict", "list", "set", "deque", "defaultdict", "OrderedDict",
    "Counter",
)
# methods that count as backend writes for unguarded-backend-write
_BACKEND_WRITES = ("put", "delete")


@dataclasses.dataclass(frozen=True)
class DurDiagnostic:
    file: str
    line: int
    rule: str
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: {self.rule}: {self.message}"


def _package_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def target_files() -> list[pathlib.Path]:
    root = _package_root() / "ballista_tpu"
    files = sorted((root / TARGET_DIR).rglob("*.py"))
    files += [root / m for m in TARGET_MODULES if (root / m).exists()]
    return files


class _DurMarked(_Marked):
    """stalelint's suppression-lookup engine, re-keyed to the durlint
    marker."""

    def __call__(self, line: int, rule: str) -> bool:
        for ln in {line, self._stmt_line.get(line), self._def_line.get(line)}:
            if ln is None or ln < 1 or ln > len(self.lines):
                continue
            m = _SUPPRESS_RE.search(self.lines[ln - 1])
            if m and rule in [s.strip() for s in m.group(1).split(",")]:
                return True
        return False


def _container_value(value: ast.expr | None) -> bool:
    if value is None:
        return False
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                          ast.ListComp, ast.SetComp)):
        return True
    if isinstance(value, ast.Call):
        name = _call_name(value.func)
        if name in _CONTAINER_CALLS:
            return True
        if name == "field":
            # dataclasses.field(default_factory=dict/list/set/...)
            for kw in value.keywords:
                if kw.arg == "default_factory" and isinstance(
                    kw.value, ast.Name
                ) and kw.value.id in _CONTAINER_CALLS:
                    return True
    return False


# ---------------------------------------------------------------------------
# rule 1: undeclared-state
# ---------------------------------------------------------------------------

def _rule_undeclared_state(
    tree: ast.Module, filename: str, marked: _DurMarked,
    index: dict[str, str],
) -> list[DurDiagnostic]:
    out: list[DurDiagnostic] = []
    modes = {
        qual.split("::", 1)[1]: mode
        for qual, mode in durreg.CONTROL_CLASSES.items()
        if qual.startswith(filename + "::")
    }
    if not modes:
        return out
    for node in tree.body:
        if not (isinstance(node, ast.ClassDef) and node.name in modes):
            continue
        mode = modes[node.name]
        flagged: set[str] = set()

        def flag(attr: str, line: int, what: str) -> None:
            anchor = f"{filename}::{node.name}.{attr}"
            if anchor in index or attr in flagged:
                return
            flagged.add(attr)
            if marked(line, "undeclared-state"):
                return
            out.append(DurDiagnostic(
                filename, line, "undeclared-state",
                f"`{node.name}.{attr}` is {what} with no durability "
                f"declaration — add anchor '{anchor}' to a StateEntry "
                "in analysis/durreg.py (persisted, rebuilt, or "
                "ephemeral with a written story)",
            ))

        if mode == "dataclass-fields":
            # EVERY field of the record must be anchored: a scalar
            # status field is exactly the state a restart loses
            for sub in node.body:
                target = None
                if isinstance(sub, ast.AnnAssign) and isinstance(
                    sub.target, ast.Name
                ):
                    target = sub.target.id
                elif isinstance(sub, ast.Assign) and len(
                    sub.targets
                ) == 1 and isinstance(sub.targets[0], ast.Name):
                    target = sub.targets[0].id
                if target is not None and not target.startswith("_"):
                    flag(target, sub.lineno, "a dataclass field")
            continue
        # init-containers: any `self.x = <mutable container>` anywhere
        # in the class's methods (state introduced lazily counts too)
        for sub in ast.walk(node):
            targets: list[ast.expr] = []
            value: ast.expr | None = None
            if isinstance(sub, ast.Assign):
                targets, value = sub.targets, sub.value
            elif isinstance(sub, ast.AnnAssign):
                targets, value = [sub.target], sub.value
            if not _container_value(value):
                continue
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    flag(t.attr, sub.lineno, "a mutable container")
    return out


# ---------------------------------------------------------------------------
# rule 2: unpersisted-mutation
# ---------------------------------------------------------------------------

def _rule_unpersisted_mutation(
    tree: ast.Module, filename: str, marked: _DurMarked
) -> list[DurDiagnostic]:
    out: list[DurDiagnostic] = []
    contracts = [c for c in durreg.CONTRACTS if c.file == filename]
    if not contracts:
        return out
    funcs: dict[str, ast.FunctionDef] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            funcs.setdefault(node.name, node)
    for c in contracts:
        for mut in c.mutators:
            fn = funcs.get(mut)
            if fn is None:
                out.append(DurDiagnostic(
                    filename, 1, "unpersisted-mutation",
                    f"contract '{c.source}': mutator `{mut}` not found "
                    "(renamed? update analysis/durreg.py)",
                ))
                continue
            calls = {
                _dotted(sub.func)
                for sub in ast.walk(fn)
                if isinstance(sub, ast.Call)
            }
            for suffix in c.must_call:
                if any(d.endswith(suffix) for d in calls):
                    continue
                if marked(fn.lineno, "unpersisted-mutation"):
                    continue
                out.append(DurDiagnostic(
                    filename, fn.lineno, "unpersisted-mutation",
                    f"`{mut}` mutates durable state '{c.source}' but "
                    f"never calls `...{suffix}(...)` — declared fields "
                    f"{', '.join(c.fields)} would not survive a "
                    "scheduler restart",
                ))
    return out


# ---------------------------------------------------------------------------
# rule 3: recovery-gap
# ---------------------------------------------------------------------------

def _rule_recovery_gap(
    tree: ast.Module, filename: str, marked: _DurMarked
) -> list[DurDiagnostic]:
    if filename != "ballista_tpu/scheduler/server.py":
        return []
    out: list[DurDiagnostic] = []
    recover = None
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == "_recover_state":
            recover = node
            break
    if recover is None:
        return [DurDiagnostic(
            filename, 1, "recovery-gap",
            "_recover_state not found — the recovery entry point the "
            "persisted registry is proven against (renamed? update "
            "analysis/durlint.py)",
        )]
    calls = {
        _dotted(sub.func)
        for sub in ast.walk(recover)
        if isinstance(sub, ast.Call)
    }
    for e in durreg.entries("persisted"):
        if e.load is None:
            continue  # verify_anchors already flags this
        if any(d.endswith(e.load) for d in calls):
            continue
        if marked(recover.lineno, "recovery-gap"):
            continue
        out.append(DurDiagnostic(
            filename, recover.lineno, "recovery-gap",
            f"persisted entry '{e.name}' declares load `{e.load}` but "
            "_recover_state never calls it — write-only durability: "
            "the key survives every restart while recovering nothing",
        ))
    return out


# ---------------------------------------------------------------------------
# rule 4: unguarded-backend-write
# ---------------------------------------------------------------------------

def _rule_unguarded_backend_write(
    tree: ast.Module, filename: str, marked: _DurMarked
) -> list[DurDiagnostic]:
    out: list[DurDiagnostic] = []
    seams = {
        fn
        for s in durreg.WRITE_SEAMS
        if s.file == filename
        for fn in s.functions
    }

    def is_backend_write(call: ast.Call) -> bool:
        func = call.func
        return (
            isinstance(func, ast.Attribute)
            and func.attr in _BACKEND_WRITES
            and isinstance(func.value, ast.Attribute)
            and func.value.attr == "backend"
        )

    def is_lock_with(node: ast.With) -> bool:
        return any(
            isinstance(item.context_expr, ast.Call)
            and _dotted(item.context_expr.func).endswith("lock")
            for item in node.items
        )

    def walk(node: ast.AST, locked: bool, seam: bool) -> None:
        for child in ast.iter_child_nodes(node):
            child_locked = locked
            child_seam = seam
            if isinstance(child, ast.With) and is_lock_with(child):
                child_locked = True
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                child_seam = seam or child.name in seams
                # a nested def is a new lexical frame: an enclosing
                # `with lock:` does not guard calls made later through
                # the closure
                child_locked = False
            if (
                isinstance(child, ast.Call)
                and is_backend_write(child)
                and not child_locked
                and not child_seam
                and not marked(child.lineno, "unguarded-backend-write")
            ):
                out.append(DurDiagnostic(
                    filename, child.lineno, "unguarded-backend-write",
                    f"`{_dotted(child.func)}` writes the state backend "
                    "outside `with backend.lock():` and outside any "
                    "declared WriteSeam — on a shared etcd backend this "
                    "is the split-brain shape (declare a seam with "
                    "reasoning in analysis/durreg.py or take the lock)",
                ))
            walk(child, child_locked, child_seam)

    walk(tree, False, False)
    return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------

def lint_source(source: str, filename: str) -> list[DurDiagnostic]:
    tree = ast.parse(source, filename=filename)
    marked = _DurMarked(source, tree)
    index = durreg.anchor_index()
    diags = (
        _rule_undeclared_state(tree, filename, marked, index)
        + _rule_unpersisted_mutation(tree, filename, marked)
        + _rule_recovery_gap(tree, filename, marked)
        + _rule_unguarded_backend_write(tree, filename, marked)
    )
    return sorted(diags, key=lambda d: (d.file, d.line, d.rule))


def lint_paths(paths=None) -> list[DurDiagnostic]:
    root = _package_root()
    files = (
        [pathlib.Path(p) for p in paths] if paths else target_files()
    )
    diags: list[DurDiagnostic] = []
    seen: set[str] = set()
    for path in files:
        rel = str(path.relative_to(root)) if path.is_absolute() else str(path)
        seen.add(rel)
        diags += lint_source(path.read_text(), rel)
    if paths is None:
        # contracts/classes/seams over files outside the sweep would
        # silently never run
        for c in durreg.CONTRACTS:
            if c.file not in seen:
                diags.append(DurDiagnostic(
                    c.file, 1, "unpersisted-mutation",
                    f"contract '{c.source}' targets a file outside the "
                    "durlint sweep",
                ))
        for qual in durreg.CONTROL_CLASSES:
            rel = qual.split("::", 1)[0]
            if rel not in seen:
                diags.append(DurDiagnostic(
                    rel, 1, "undeclared-state",
                    f"control class '{qual}' lives outside the durlint "
                    "sweep",
                ))
        for s in durreg.WRITE_SEAMS:
            if s.file not in seen:
                diags.append(DurDiagnostic(
                    s.file, 1, "unguarded-backend-write",
                    f"write seam over '{s.file}' targets a file outside "
                    "the durlint sweep",
                ))
    return sorted(set(diags), key=lambda d: (d.file, d.line, d.rule))


def suppression_count(paths=None) -> int:
    root = _package_root()
    files = (
        [pathlib.Path(p) for p in paths] if paths else target_files()
    )
    n = 0
    for path in files:
        for line in path.read_text().splitlines():
            if _SUPPRESS_RE.search(line):
                n += 1
    return n
