"""Combined static-analysis gate: ``python -m ballista_tpu.analysis``.

Runs all twelve analyzers with one exit code and a per-analyzer summary
line — the single command CI (and a developer pre-push) needs:

- **planlint** — the plan verifier over the TPC-H q1-q22 corpus
  (logical + physical tiers, plus distributed stage DAGs for a
  representative mix), proving the verifier still accepts every plan the
  engine produces.
- **serde-audit** — structural closure of the proto vocabulary
  (round-trip byte stability or written exemption for every node class).
- **jaxlint** — JAX/TPU hazard lint over ``ops/`` + ``exec/`` + ``obs/``.
- **racelint** — lock-discipline + state-machine lint over the
  concurrent control plane, including the ``obs/`` trace ring/outbox.
- **compile-vocab** — the closed compiled-kernel vocabulary gate
  (compilecache/registry.py): every jit site in the source report must be
  registered, and every operator class reachable from TPC-H q1-q22
  logical→physical→stage lowering must declare its compile surface — a
  silently-grown recompile vocabulary is a cold-start regression
  (docs/compile_cache.md).
- **lifelint** — resource-lifecycle + error-taxonomy lint over the
  control & data planes, with its runtime counterpart in
  :mod:`ballista_tpu.analysis.reswitness`
  (``BALLISTA_RESOURCE_WITNESS=1``).
- **proto-drift** — proto TEXT ↔ generated DESCRIPTOR agreement plus the
  committed field-number ledger (proto/field_numbers.json).
- **config-registry** — every ``ballista.*`` config-key literal and
  ``BALLISTA_*`` env read site must resolve to a declared registry
  entry, and docs/config.md must match the generated table.
- **eqlint** — the no-uncertified-mutation closure: direct writes to
  structural plan fields outside the certified rewrite API
  (ballista_tpu/rewrite.py) are findings, making the rewrite-certificate
  contract load-bearing (docs/analysis.md).
- **detlint** — determinism lint over ``ops/``/``exec/``/``executor/``/
  ``scheduler/``/``compilecache/``: unordered set iteration in
  order-sensitive positions, undeclared RNG, wall-clock reads in the
  data plane, and completion-order-dependent reductions/merges; its
  runtime counterpart is the replay witness
  (:mod:`ballista_tpu.analysis.replay`, ``BALLISTA_REPLAY_WITNESS=1``).
- **stalelint** — cache-coherence lint over the declared cache registry
  (analysis/cachereg.py): undeclared cache-shaped state,
  version-source mutators that drop a declared invalidation call,
  reads of snapshot-class learned state outside the job-snapshot seam
  (the q15 bug shape), and speculative-cache writes outside the
  validation seam; its runtime counterpart is the staleness witness
  (:mod:`ballista_tpu.analysis.stalewitness`,
  ``BALLISTA_CACHE_WITNESS=1``).
- **durlint** — distributed-durability lint over the declared state
  registry (analysis/durreg.py): undeclared mutable control-plane
  state, mutators that drop a declared persistence call, persisted
  keys never read back in ``_recover_state``, and state-backend writes
  outside the lock/ownership seam (the two-scheduler split-brain
  shape); its runtime counterpart is the durability witness
  (:mod:`ballista_tpu.analysis.durwitness`, ``BALLISTA_DUR_WITNESS=1``).

Suppression budgets for every AST analyzer live in ONE ledger
(:mod:`ballista_tpu.analysis.budget`) enforced here and pinned by a
single tier-1 test.

Analyzers run CONCURRENTLY by default (the two TPC-H-corpus analyzers —
planlint and compile-vocab — share one worker since both build the same
heavy context); ``--serial`` restores one-at-a-time execution. Output
order is fixed regardless.

Flags: ``--json`` emits one machine-readable document (per-analyzer
ok/summary/seconds, the suppression ledger, and the failure list) for CI
annotation instead of the human lines; ``--list`` prints the registered
analyzer names one per line (ci/analysis-gate.sh diffs this against its
pinned matrix, so an analyzer added here but not there — or vice versa —
fails CI); ``--dot`` prints the racelint lock-order graph (Graphviz) and
exits; ``--tables`` prints the canonical status state machines and
exits; ``--write-config-docs`` regenerates docs/config.md and exits;
``--skip a,b`` / ``--only a,b`` select analyzers; ``--queries 1,3,6``
limits the TPC-H corpus (tier-1 runs a subset — the full corpus is
covered by tests/test_plan_verifier.py).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

ANALYZERS = (
    "planlint", "serde-audit", "jaxlint", "racelint", "compile-vocab",
    "lifelint", "proto-drift", "config-registry", "eqlint", "detlint",
    "stalelint", "durlint",
)

# analyzers sharing one worker under parallel execution: planlint and
# compile-vocab both build a TpuContext + the TPC-H corpus; running them
# in a single group avoids doing that heavy setup twice concurrently
_SHARED_CORPUS = ("planlint", "compile-vocab")


def run_planlint(queries=None) -> tuple[bool, str]:
    import pathlib

    from ballista_tpu.analysis import (
        verify_logical,
        verify_physical,
        verify_stages,
    )
    from ballista_tpu.distributed_plan import DistributedPlanner
    from ballista_tpu.exec.context import TpuContext
    from ballista_tpu.exec.planner import PhysicalPlanner
    from ballista_tpu.plan.optimizer import optimize
    from ballista_tpu.tpch import gen_all

    qdir = (
        pathlib.Path(__file__).resolve().parents[2]
        / "benchmarks" / "queries"
    )
    ctx = TpuContext()
    for name, tab in gen_all(scale=0.001).items():
        ctx.register_table(name, tab)
    qs = list(queries) if queries else list(range(1, 23))
    checks = 0
    for i in qs:
        sql = (qdir / f"q{i}.sql").read_text()
        optimized = optimize(ctx.sql_to_logical(sql))
        checks += verify_logical(optimized, sql=sql).checks
        phys = ctx.create_physical_plan(optimized, sql=sql)
        checks += verify_physical(phys, sql=sql).checks
        dist = PhysicalPlanner(
            ctx, 2, config=ctx.config, distributed=True
        ).plan(optimized)
        stages = DistributedPlanner().plan_query_stages(f"job-q{i}", dist)
        checks += verify_stages(stages, sql=sql).checks
    return True, f"{len(qs)} TPC-H queries verified ({checks} checks)"


def run_serde_audit() -> tuple[bool, str]:
    from ballista_tpu.analysis.serde_audit import (
        audit_expressions,
        audit_logical,
        audit_physical,
    )

    results = [audit_expressions(), audit_logical(), audit_physical()]
    ok = all(r.ok for r in results)
    return ok, "; ".join(r.summary() for r in results)


def run_jaxlint() -> tuple[bool, str]:
    from ballista_tpu.analysis import budget, jaxlint

    diags = jaxlint.lint_paths()
    sup = jaxlint.suppression_count()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    over = budget.check("jaxlint", sup)
    if over:
        return False, over
    return True, f"0 hazards, {sup} suppressions"


def run_racelint() -> tuple[bool, str]:
    from ballista_tpu.analysis import budget, racelint

    analysis = racelint.analyze()  # one parse+fixpoint for all three views
    diags = analysis.diagnostics()
    sup = analysis.suppression_count()
    edges = analysis.lock_edges()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    over = budget.check("racelint", sup)
    if over:
        return False, over
    return True, (
        f"0 findings, {sup} suppressions, lock-order graph: "
        f"{len(edges)} edges, acyclic"
    )


def run_compile_vocab(queries=None) -> tuple[bool, str]:
    """Closed-vocabulary gate: the source-derived jit-site report must
    match compilecache.registry.VOCABULARY, and every operator class in
    the TPC-H physical/stage plans must be mapped in OPERATOR_KERNELS."""
    import pathlib

    from ballista_tpu.compilecache import registry
    from ballista_tpu.distributed_plan import DistributedPlanner
    from ballista_tpu.exec.context import TpuContext
    from ballista_tpu.exec.planner import PhysicalPlanner
    from ballista_tpu.plan.optimizer import optimize
    from ballista_tpu.tpch import gen_all

    problems = registry.check_vocabulary()

    qdir = (
        pathlib.Path(__file__).resolve().parents[2]
        / "benchmarks" / "queries"
    )
    ctx = TpuContext()
    for name, tab in gen_all(scale=0.001).items():
        ctx.register_table(name, tab)
    qs = list(queries) if queries else list(range(1, 23))
    kernels: set[str] = set()
    for i in qs:
        sql = (qdir / f"q{i}.sql").read_text()
        optimized = optimize(ctx.sql_to_logical(sql))
        phys = ctx.create_physical_plan(optimized, sql=sql)
        problems += [
            f"q{i} (physical): {p}" for p in registry.check_plan(phys)
        ]
        kernels |= registry.plan_kernels(phys)
        dist = PhysicalPlanner(
            ctx, 2, config=ctx.config, distributed=True
        ).plan(optimized)
        stages = DistributedPlanner().plan_query_stages(f"job-q{i}", dist)
        for st in stages:
            problems += [
                f"q{i} (stage {st.stage_id}): {p}"
                for p in registry.check_plan(st.plan)
            ]
            kernels |= registry.plan_kernels(st.plan)
    if problems:
        return False, "\n".join(problems)
    return True, (
        f"{len(registry.VOCABULARY)} kernels registered; {len(qs)} TPC-H "
        f"queries lower onto {len(kernels)} of them, all in vocabulary"
    )


def run_lifelint() -> tuple[bool, str]:
    from ballista_tpu.analysis import budget, lifelint

    diags = lifelint.lint_paths()
    sup = lifelint.suppression_count()
    transfers = lifelint.transfer_sites()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    over = budget.check("lifelint", sup)
    if over:
        return False, over
    return True, (
        f"0 findings, {sup} suppressions, {len(transfers)} declared "
        "ownership transfers"
    )


def run_proto_drift() -> tuple[bool, str]:
    from ballista_tpu.analysis import protodrift

    return protodrift.run()


def run_config_registry() -> tuple[bool, str]:
    from ballista_tpu.analysis import configlint

    return configlint.run()


def run_eqlint() -> tuple[bool, str]:
    from ballista_tpu.analysis import budget, eqlint

    diags = eqlint.lint_paths()
    sup = eqlint.suppression_count()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    over = budget.check("eqlint", sup)
    if over:
        return False, over
    return True, (
        f"0 findings, {sup} suppressions (plan mutation closed over "
        "rewrite.py)"
    )


def run_detlint() -> tuple[bool, str]:
    from ballista_tpu.analysis import budget, detlint

    diags = detlint.lint_paths()
    sup = detlint.suppression_count()
    nondet = detlint.nondet_sites()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    over = budget.check("detlint", sup)
    if over:
        return False, over
    return True, (
        f"0 findings, {sup} suppressions, {len(nondet)} declared "
        "nondeterminism sites"
    )


def run_stalelint() -> tuple[bool, str]:
    from ballista_tpu.analysis import budget, cachereg, stalelint

    problems = cachereg.verify_anchors()
    docs = cachereg.docs_in_sync()
    if docs:
        problems.append(docs)
    diags = stalelint.lint_paths()
    sup = stalelint.suppression_count()
    if problems or diags:
        return False, "\n".join(problems + [str(d) for d in diags])
    over = budget.check("stalelint", sup)
    if over:
        return False, over
    return True, (
        f"0 findings, {sup} suppressions, {len(cachereg.CACHES)} declared "
        f"caches / {len(cachereg.CONTRACTS)} invalidation contracts"
    )


def run_durlint() -> tuple[bool, str]:
    from ballista_tpu.analysis import budget, durlint, durreg

    problems = durreg.verify_anchors()
    docs = durreg.docs_in_sync()
    if docs:
        problems.append(docs)
    diags = durlint.lint_paths()
    sup = durlint.suppression_count()
    if problems or diags:
        return False, "\n".join(problems + [str(d) for d in diags])
    over = budget.check("durlint", sup)
    if over:
        return False, over
    return True, (
        f"0 findings, {sup} suppressions, {len(durreg.STATE)} declared "
        f"state entries / {len(durreg.CONTRACTS)} persistence contracts"
    )


def _runners(queries):
    """Resolved at call time from module attributes, so tests can
    monkeypatch individual runners."""
    return {
        "planlint": lambda: run_planlint(queries),
        "serde-audit": run_serde_audit,
        "jaxlint": run_jaxlint,
        "racelint": run_racelint,
        "compile-vocab": lambda: run_compile_vocab(queries),
        "lifelint": run_lifelint,
        "proto-drift": run_proto_drift,
        "config-registry": run_config_registry,
        "eqlint": run_eqlint,
        "detlint": run_detlint,
        "stalelint": run_stalelint,
        "durlint": run_durlint,
    }


def run_all(
    skip=(), only=(), queries=None, out=print, parallel=True,
    as_json=False,
) -> int:
    """Run the selected analyzers; returns the process exit code."""
    runners = _runners(queries)
    selected = [
        n
        for n in ANALYZERS
        if n not in skip and (not only or n in only)
    ]

    def run_one(name) -> dict:
        t0 = time.perf_counter()
        try:
            ok, summary = runners[name]()
        except Exception as e:  # noqa: BLE001 — an analyzer crash is a fail
            ok, summary = False, f"analyzer crashed: {type(e).__name__}: {e}"
        return {
            "name": name,
            "ok": ok,
            "summary": summary,
            "seconds": round(time.perf_counter() - t0, 3),
        }

    results: dict[str, dict] = {}
    if parallel and len(selected) > 1:
        from concurrent.futures import ThreadPoolExecutor

        corpus = [n for n in selected if n in _SHARED_CORPUS]
        singles = [n for n in selected if n not in _SHARED_CORPUS]
        groups: list[list[str]] = ([corpus] if corpus else []) + [
            [n] for n in singles
        ]

        def run_group(names: list[str]) -> list[dict]:
            return [run_one(n) for n in names]

        with ThreadPoolExecutor(
            max_workers=min(8, len(groups)), thread_name_prefix="analysis"
        ) as pool:
            for group_results in pool.map(run_group, groups):
                for r in group_results:
                    results[r["name"]] = r
    else:
        for name in selected:
            results[name] = run_one(name)

    failed = [n for n in ANALYZERS if n in results and not results[n]["ok"]]
    if as_json:
        from ballista_tpu.analysis import budget

        try:
            suppressions = budget.ledger()
        except Exception as e:  # noqa: BLE001 — ledger breakage must not
            # mask the analyzer verdicts in CI output
            suppressions = {"error": f"{type(e).__name__}: {e}"}
        doc = {
            "ok": not failed,
            "failed": failed,
            "analyzers": [
                {**results[n]}
                if n in results
                else {"name": n, "skipped": True}
                for n in ANALYZERS
            ],
            "suppressions": suppressions,
        }
        out(json.dumps(doc, indent=2, sort_keys=True))
        return 1 if failed else 0
    for name in ANALYZERS:
        if name not in results:
            out(f"{name}: SKIPPED")
            continue
        r = results[name]
        out(f"{name}: {'OK' if r['ok'] else 'FAIL'} — {r['summary']}")
    if failed:
        out(f"FAILED: {', '.join(failed)}")
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m ballista_tpu.analysis")
    ap.add_argument("--skip", default="", help="comma-separated analyzers")
    ap.add_argument("--only", default="", help="comma-separated analyzers")
    ap.add_argument(
        "--queries", default="",
        help="comma-separated TPC-H query numbers for planlint",
    )
    ap.add_argument(
        "--json", action="store_true",
        help="machine-readable output (per-analyzer verdicts, timings, "
        "suppression ledger) for CI annotation",
    )
    ap.add_argument(
        "--serial", action="store_true",
        help="run analyzers one at a time instead of concurrently",
    )
    ap.add_argument(
        "--list", action="store_true",
        help="print the registered analyzer names (one per line) and "
        "exit — CI diffs this against its pinned matrix",
    )
    ap.add_argument(
        "--dot", action="store_true",
        help="print the racelint lock-order graph (Graphviz) and exit",
    )
    ap.add_argument(
        "--tables", action="store_true",
        help="print the canonical status state machines and exit",
    )
    ap.add_argument(
        "--write-config-docs", action="store_true",
        help="regenerate docs/config.md from the config registries and "
        "exit",
    )
    args = ap.parse_args(argv)
    if args.list:
        for name in ANALYZERS:
            print(name)
        return 0
    if args.write_config_docs:
        from ballista_tpu.analysis import configlint

        configlint.docs_path().write_text(configlint.render_config_docs())
        print(f"wrote {configlint.docs_path()}")
        return 0
    if args.dot:
        from ballista_tpu.analysis import racelint

        print(racelint.lock_order_dot())
        return 0
    if args.tables:
        from ballista_tpu.analysis.statemachine import render_tables

        print(render_tables())
        return 0
    skip = tuple(s for s in args.skip.split(",") if s)
    only = tuple(s for s in args.only.split(",") if s)
    queries = [int(q) for q in args.queries.split(",") if q] or None
    return run_all(
        skip=skip, only=only, queries=queries,
        parallel=not args.serial, as_json=args.json,
    )


if __name__ == "__main__":
    sys.exit(main())
