"""Combined static-analysis gate: ``python -m ballista_tpu.analysis``.

Runs all four analyzers with one exit code and a per-analyzer summary
line — the single command CI (and a developer pre-push) needs:

- **planlint** — the plan verifier over the TPC-H q1-q22 corpus
  (logical + physical tiers, plus distributed stage DAGs for a
  representative mix), proving the verifier still accepts every plan the
  engine produces.
- **serde-audit** — structural closure of the proto vocabulary
  (round-trip byte stability or written exemption for every node class).
- **jaxlint** — JAX/TPU hazard lint over ``ops/`` + ``exec/`` + ``obs/``.
- **racelint** — lock-discipline + state-machine lint over the
  concurrent control plane, including the ``obs/`` trace ring/outbox
  (suppression budget enforced here too).
- **compile-vocab** — the closed compiled-kernel vocabulary gate
  (compilecache/registry.py): every jit site in the source report must be
  registered, and every operator class reachable from TPC-H q1-q22
  logical→physical→stage lowering must declare its compile surface — a
  silently-grown recompile vocabulary is a cold-start regression
  (docs/compile_cache.md).
- **lifelint** — resource-lifecycle + error-taxonomy lint over the
  control & data planes (leaked channels/pools/files/mmaps/spill sets,
  releases missing from exception/cancellation edges, raises outside
  the errors.py retryable/non-retryable taxonomy, swallowed errors,
  untyped fault-injection handlers), with its runtime counterpart in
  :mod:`ballista_tpu.analysis.reswitness`
  (``BALLISTA_RESOURCE_WITNESS=1``).
- **proto-drift** — proto TEXT ↔ generated DESCRIPTOR agreement (the
  image has no protoc; edits are hand-synced descriptor mutations) plus
  the committed field-number ledger (proto/field_numbers.json): no
  renumber, no reuse of retired numbers, every new field appended.
- **config-registry** — every ``ballista.*`` config-key literal and
  ``BALLISTA_*`` env read site must resolve to a declared registry
  entry, and docs/config.md must match the generated table.

Flags: ``--dot`` prints the racelint lock-order graph (Graphviz) and
exits; ``--tables`` prints the canonical status state machines and
exits; ``--write-config-docs`` regenerates docs/config.md and exits;
``--skip a,b`` / ``--only a,b`` select analyzers;
``--queries 1,3,6`` limits planlint's TPC-H corpus (tier-1 runs a
subset — the full corpus is covered by tests/test_plan_verifier.py).
"""

from __future__ import annotations

import argparse
import sys

ANALYZERS = (
    "planlint", "serde-audit", "jaxlint", "racelint", "compile-vocab",
    "lifelint", "proto-drift", "config-registry",
)


def run_planlint(queries=None) -> tuple[bool, str]:
    import pathlib

    from ballista_tpu.analysis import (
        verify_logical,
        verify_physical,
        verify_stages,
    )
    from ballista_tpu.distributed_plan import DistributedPlanner
    from ballista_tpu.exec.context import TpuContext
    from ballista_tpu.exec.planner import PhysicalPlanner
    from ballista_tpu.plan.optimizer import optimize
    from ballista_tpu.tpch import gen_all

    qdir = (
        pathlib.Path(__file__).resolve().parents[2]
        / "benchmarks" / "queries"
    )
    ctx = TpuContext()
    for name, tab in gen_all(scale=0.001).items():
        ctx.register_table(name, tab)
    qs = list(queries) if queries else list(range(1, 23))
    checks = 0
    for i in qs:
        sql = (qdir / f"q{i}.sql").read_text()
        optimized = optimize(ctx.sql_to_logical(sql))
        checks += verify_logical(optimized, sql=sql).checks
        phys = ctx.create_physical_plan(optimized, sql=sql)
        checks += verify_physical(phys, sql=sql).checks
        dist = PhysicalPlanner(
            ctx, 2, config=ctx.config, distributed=True
        ).plan(optimized)
        stages = DistributedPlanner().plan_query_stages(f"job-q{i}", dist)
        checks += verify_stages(stages, sql=sql).checks
    return True, f"{len(qs)} TPC-H queries verified ({checks} checks)"


def run_serde_audit() -> tuple[bool, str]:
    from ballista_tpu.analysis.serde_audit import (
        audit_expressions,
        audit_logical,
        audit_physical,
    )

    results = [audit_expressions(), audit_logical(), audit_physical()]
    ok = all(r.ok for r in results)
    return ok, "; ".join(r.summary() for r in results)


def run_jaxlint() -> tuple[bool, str]:
    from ballista_tpu.analysis import jaxlint

    diags = jaxlint.lint_paths()
    sup = jaxlint.suppression_count()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    if sup > 5:
        return False, f"suppression budget exceeded: {sup} > 5"
    return True, f"0 hazards, {sup} suppressions"


def run_racelint() -> tuple[bool, str]:
    from ballista_tpu.analysis import racelint

    analysis = racelint.analyze()  # one parse+fixpoint for all three views
    diags = analysis.diagnostics()
    sup = analysis.suppression_count()
    edges = analysis.lock_edges()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    if sup > 5:
        return False, f"suppression budget exceeded: {sup} > 5"
    return True, (
        f"0 findings, {sup} suppressions, lock-order graph: "
        f"{len(edges)} edges, acyclic"
    )


def run_compile_vocab(queries=None) -> tuple[bool, str]:
    """Closed-vocabulary gate: the source-derived jit-site report must
    match compilecache.registry.VOCABULARY, and every operator class in
    the TPC-H physical/stage plans must be mapped in OPERATOR_KERNELS."""
    import pathlib

    from ballista_tpu.compilecache import registry
    from ballista_tpu.distributed_plan import DistributedPlanner
    from ballista_tpu.exec.context import TpuContext
    from ballista_tpu.exec.planner import PhysicalPlanner
    from ballista_tpu.plan.optimizer import optimize
    from ballista_tpu.tpch import gen_all

    problems = registry.check_vocabulary()

    qdir = (
        pathlib.Path(__file__).resolve().parents[2]
        / "benchmarks" / "queries"
    )
    ctx = TpuContext()
    for name, tab in gen_all(scale=0.001).items():
        ctx.register_table(name, tab)
    qs = list(queries) if queries else list(range(1, 23))
    kernels: set[str] = set()
    for i in qs:
        sql = (qdir / f"q{i}.sql").read_text()
        optimized = optimize(ctx.sql_to_logical(sql))
        phys = ctx.create_physical_plan(optimized, sql=sql)
        problems += [
            f"q{i} (physical): {p}" for p in registry.check_plan(phys)
        ]
        kernels |= registry.plan_kernels(phys)
        dist = PhysicalPlanner(
            ctx, 2, config=ctx.config, distributed=True
        ).plan(optimized)
        stages = DistributedPlanner().plan_query_stages(f"job-q{i}", dist)
        for st in stages:
            problems += [
                f"q{i} (stage {st.stage_id}): {p}"
                for p in registry.check_plan(st.plan)
            ]
            kernels |= registry.plan_kernels(st.plan)
    if problems:
        return False, "\n".join(problems)
    return True, (
        f"{len(registry.VOCABULARY)} kernels registered; {len(qs)} TPC-H "
        f"queries lower onto {len(kernels)} of them, all in vocabulary"
    )


def run_lifelint() -> tuple[bool, str]:
    from ballista_tpu.analysis import lifelint

    diags = lifelint.lint_paths()
    sup = lifelint.suppression_count()
    transfers = lifelint.transfer_sites()
    if diags:
        return False, "\n".join(str(d) for d in diags)
    if sup > 5:
        return False, f"suppression budget exceeded: {sup} > 5"
    return True, (
        f"0 findings, {sup} suppressions, {len(transfers)} declared "
        "ownership transfers"
    )


def run_proto_drift() -> tuple[bool, str]:
    from ballista_tpu.analysis import protodrift

    return protodrift.run()


def run_config_registry() -> tuple[bool, str]:
    from ballista_tpu.analysis import configlint

    return configlint.run()


def run_all(
    skip=(), only=(), queries=None, out=print
) -> int:
    """Run the selected analyzers; returns the process exit code."""
    runners = {
        "planlint": lambda: run_planlint(queries),
        "serde-audit": run_serde_audit,
        "jaxlint": run_jaxlint,
        "racelint": run_racelint,
        "compile-vocab": lambda: run_compile_vocab(queries),
        "lifelint": run_lifelint,
        "proto-drift": run_proto_drift,
        "config-registry": run_config_registry,
    }
    failed = []
    for name in ANALYZERS:
        if name in skip or (only and name not in only):
            out(f"{name}: SKIPPED")
            continue
        try:
            ok, summary = runners[name]()
        except Exception as e:  # noqa: BLE001 — an analyzer crash is a fail
            ok, summary = False, f"analyzer crashed: {type(e).__name__}: {e}"
        out(f"{name}: {'OK' if ok else 'FAIL'} — {summary}")
        if not ok:
            failed.append(name)
    if failed:
        out(f"FAILED: {', '.join(failed)}")
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m ballista_tpu.analysis")
    ap.add_argument("--skip", default="", help="comma-separated analyzers")
    ap.add_argument("--only", default="", help="comma-separated analyzers")
    ap.add_argument(
        "--queries", default="",
        help="comma-separated TPC-H query numbers for planlint",
    )
    ap.add_argument(
        "--dot", action="store_true",
        help="print the racelint lock-order graph (Graphviz) and exit",
    )
    ap.add_argument(
        "--tables", action="store_true",
        help="print the canonical status state machines and exit",
    )
    ap.add_argument(
        "--write-config-docs", action="store_true",
        help="regenerate docs/config.md from the config registries and "
        "exit",
    )
    args = ap.parse_args(argv)
    if args.write_config_docs:
        from ballista_tpu.analysis import configlint

        configlint.docs_path().write_text(configlint.render_config_docs())
        print(f"wrote {configlint.docs_path()}")
        return 0
    if args.dot:
        from ballista_tpu.analysis import racelint

        print(racelint.lock_order_dot())
        return 0
    if args.tables:
        from ballista_tpu.analysis.statemachine import render_tables

        print(render_tables())
        return 0
    skip = tuple(s for s in args.skip.split(",") if s)
    only = tuple(s for s in args.only.split(",") if s)
    queries = [int(q) for q in args.queries.split(",") if q] or None
    return run_all(skip=skip, only=only, queries=queries)


if __name__ == "__main__":
    sys.exit(main())
