"""Pre-execution plan verification.

Proves a plan is executable BEFORE any stage is scheduled. The scheduler
otherwise trusts the physical plan it splits into stages — schema
mismatches, unresolved columns, illegal device dtypes, and partition-count
disagreements at shuffle boundaries only surface at task runtime on an
executor (the MeshSort ``fetch=None`` round-trip bug fixed in PR 1 is
exactly this class). Three entry points:

- :func:`verify_logical` — walk a logical plan DAG checking parent/child
  schema agreement, column resolution, expression typing, and TPU dtype
  legality.
- :func:`verify_physical` — the same over an ExecutionPlan tree, plus
  exchange-boundary checks (partitioned-join partition counts,
  final-aggregate state layout vs the partial's spec).
- :func:`verify_stages` — stage-DAG well-formedness over the distributed
  planner's output: unique ids, dependency-ordered (therefore acyclic)
  references, and schema/partition-count agreement between every
  ``UnresolvedShuffleExec`` placeholder and the writer stage it reads.

All raise :class:`~ballista_tpu.errors.PlanVerificationError` carrying the
operator path root -> offender and, when the source SQL is supplied and the
offending token can be located in it, a (line, column) span.
"""

from __future__ import annotations

import dataclasses
import re

from ballista_tpu.datatypes import DataType, Schema, common_type, _DEVICE_DTYPE
from ballista_tpu.errors import BallistaError, PlanVerificationError
from ballista_tpu.expr import logical as L
from ballista_tpu.plan import logical as P

# Aggregates whose argument must be numeric (or bool, which sums/averages
# as 0/1 on device). MIN/MAX order any comparable type; COUNT takes
# anything including the wildcard.
_NUMERIC_ONLY_AGGS = frozenset(
    {
        L.AggFunc.SUM,
        L.AggFunc.AVG,
        L.AggFunc.STDDEV,
        L.AggFunc.STDDEV_POP,
        L.AggFunc.VARIANCE,
        L.AggFunc.VAR_POP,
        L.AggFunc.CORR,
    }
)


def sql_span(sql: str | None, token: str | None) -> tuple[int, int] | None:
    """1-based (line, column) of ``token``'s first occurrence in ``sql``.

    Tries the token verbatim, then its unqualified tail (``l.x`` -> ``x``).
    None when the SQL is unknown or the token does not appear (plans built
    via the DataFrame API have no SQL to point into)."""
    if not sql or not token:
        return None
    candidates = [token]
    base = token.rsplit(".", 1)[-1]
    if base != token:
        candidates.append(base)
    for t in candidates:
        if not t or not re.match(r"^[A-Za-z_][A-Za-z_0-9.]*$", t):
            continue
        m = re.search(rf"(?i)(?<![A-Za-z_0-9]){re.escape(t)}(?![A-Za-z_0-9])", sql)
        if m:
            line = sql.count("\n", 0, m.start()) + 1
            col = m.start() - (sql.rfind("\n", 0, m.start()) + 1) + 1
            return (line, col)
    return None


@dataclasses.dataclass
class VerifyReport:
    """Outcome of one verification pass, for ``EXPLAIN VERIFY`` output."""

    kind: str  # "logical" | "physical" | "stages"
    nodes: int = 0
    checks: int = 0
    detail: list[str] = dataclasses.field(default_factory=list)

    def summary(self) -> str:
        extra = f", {d}" if (d := "; ".join(self.detail)) else ""
        return (
            f"{self.kind} plan: OK — {self.nodes} operators, "
            f"{self.checks} checks{extra}"
        )


class _Walk:
    """Shared walk state: operator path, check counter, SQL span lookup."""

    def __init__(self, kind: str, sql: str | None = None):
        self.report = VerifyReport(kind)
        self.sql = sql
        self.path: list[str] = []

    def fail(self, message: str, token: str | None = None) -> None:
        raise PlanVerificationError(
            message, path=tuple(self.path), span=sql_span(self.sql, token)
        )

    def check(self) -> None:
        self.report.checks += 1

    def resolve(self, expr: L.Expr, schema: Schema, what: str) -> DataType:
        """Type an expression against a schema; unresolved columns and
        type errors become verification failures naming the column.
        Column lookup is the ENGINE's rule (exact, then unique
        unqualified-suffix, then base-name — expr.logical
        resolve_field_index), so the verifier accepts exactly the plans
        execution accepts."""
        self.check()
        for cname in L.find_columns(expr):
            try:
                L.resolve_field_index(schema, cname)
            except BallistaError as e:
                self.fail(f"{what}: {e}", token=cname)
        try:
            return expr.data_type(schema)
        except BallistaError as e:
            self.fail(f"{what} {expr.name()!r} does not type-check: {e}")

    def legal_fields(self, schema: Schema) -> None:
        """Every output field must map onto a TPU-representable dtype."""
        self.check()
        for f in schema:
            if not isinstance(f.dtype, DataType) or f.dtype not in _DEVICE_DTYPE:
                self.fail(
                    f"column {f.name!r} has no TPU device representation "
                    f"for dtype {f.dtype!r}",
                    token=f.name,
                )

    def schema_of(self, node, describe: str) -> Schema:
        self.check()
        try:
            return node.schema()
        except BallistaError as e:
            # surface the offending column as the span token when the
            # underlying SchemaError names one
            m = re.search(r"column '([^']+)'", str(e))
            self.fail(
                f"schema computation failed: {e}",
                token=m.group(1) if m else None,
            )


# ------------------------------------------------------------- logical ----


def verify_logical(plan: P.LogicalPlan, sql: str | None = None) -> VerifyReport:
    """Statically verify a logical plan; raises PlanVerificationError."""
    w = _Walk("logical", sql)
    _verify_logical_node(w, plan)
    return w.report


def _check_aggregate_expr(w: _Walk, agg: L.AggregateExpr, ins: Schema) -> None:
    if isinstance(agg.arg, L.Wildcard):
        if agg.func != L.AggFunc.COUNT:
            w.fail(f"{agg.func.value.upper()}(*) is only valid for COUNT")
        return
    at = w.resolve(agg.arg, ins, f"aggregate {agg.name()!r} argument")
    w.check()
    if agg.func in _NUMERIC_ONLY_AGGS and not (
        at.is_numeric or at == DataType.BOOL or at == DataType.NULL
    ):
        w.fail(
            f"{agg.func.value.upper()} over non-numeric dtype {at.value} "
            f"({agg.arg.name()!r}) is illegal on device",
            token=L.find_columns(agg.arg)[0] if L.find_columns(agg.arg) else None,
        )
    if agg.arg2 is not None:
        w.resolve(agg.arg2, ins, f"aggregate {agg.name()!r} second argument")


def _verify_logical_node(w: _Walk, node: P.LogicalPlan) -> None:
    w.report.nodes += 1
    w.path.append(node.describe())
    try:
        # expression-level checks run FIRST: they pinpoint the offending
        # column (token -> SQL span) where a bare node.schema() failure
        # could only say "schema computation failed"
        _logical_node_checks(w, node)
        schema = w.schema_of(node, node.describe())
        w.legal_fields(schema)
        for child in node.children():
            _verify_logical_node(w, child)
    finally:
        w.path.pop()


def _logical_node_checks(w: _Walk, node: P.LogicalPlan) -> None:
    if isinstance(node, P.TableScan):
        if node.projection is not None:
            for cname in node.projection:
                w.check()
                if not node.source_schema.has(cname):
                    w.fail(
                        f"scan projection drops through unknown column "
                        f"{cname!r}; table {node.table_name!r} has: "
                        f"{node.source_schema.names}",
                        token=cname,
                    )
        for f in node.filters:
            dt = w.resolve(f, node.schema(), "pushed-down filter")
            if dt not in (DataType.BOOL, DataType.NULL):
                w.fail(
                    f"pushed-down filter {f.name()!r} is {dt.value}, "
                    "not boolean"
                )
    elif isinstance(node, P.Projection):
        ins = w.schema_of(node.input, "input")
        for e in node.exprs:
            w.resolve(e, ins, "projection expression")
    elif isinstance(node, P.Filter):
        ins = w.schema_of(node.input, "input")
        dt = w.resolve(node.predicate, ins, "filter predicate")
        if dt not in (DataType.BOOL, DataType.NULL):
            w.fail(
                f"filter predicate {node.predicate.name()!r} is "
                f"{dt.value}, not boolean"
            )
    elif isinstance(node, P.Aggregate):
        ins = w.schema_of(node.input, "input")
        for g in node.group_exprs:
            # NULL-typed keys (e.g. GROUP BY NULL) execute fine — the
            # device maps NULL to a bool placeholder — so dtype is NOT
            # checked here: the verifier accepts what execution accepts
            w.resolve(g, ins, "group expression")
            if L.find_aggregates(g):
                w.fail(
                    f"group expression {g.name()!r} contains an "
                    "aggregate"
                )
        for e in node.agg_exprs:
            aggs = L.find_aggregates(e)
            w.check()
            if not aggs:
                w.fail(
                    f"aggregate list expression {e.name()!r} contains "
                    "no aggregate function"
                )
            for agg in aggs:
                _check_aggregate_expr(w, agg, ins)
    elif isinstance(node, P.Sort):
        ins = w.schema_of(node.input, "input")
        for s in node.sort_exprs:
            w.resolve(s.expr, ins, "sort key")
    elif isinstance(node, P.Limit):
        w.check()
        if node.skip < 0 or (node.fetch is not None and node.fetch < 0):
            w.fail(
                f"limit bounds out of range: skip={node.skip}, "
                f"fetch={node.fetch}"
            )
    elif isinstance(node, P.Join):
        ls = w.schema_of(node.left, "left input")
        rs = w.schema_of(node.right, "right input")
        w.check()
        if not node.on:
            w.fail("equi-join with empty key list (use CROSS JOIN)")
        for a, b in node.on:
            ta = w.resolve(a, ls, "left join key")
            tb = w.resolve(b, rs, "right join key")
            w.check()
            try:
                common_type(ta, tb)
            except BallistaError:
                w.fail(
                    f"join key dtype mismatch: {a.name()} is "
                    f"{ta.value} but {b.name()} is {tb.value}",
                    token=a.name(),
                )
        if node.filter is not None:
            combined = Schema(list(ls.fields) + list(rs.fields))
            dt = w.resolve(node.filter, combined, "join residual filter")
            if dt not in (DataType.BOOL, DataType.NULL):
                w.fail(
                    f"join residual filter {node.filter.name()!r} is "
                    f"{dt.value}, not boolean"
                )
    elif isinstance(node, P.Union):
        first = w.schema_of(node.inputs[0], "input")
        for other in node.inputs[1:]:
            os_ = w.schema_of(other, "input")
            w.check()
            if len(os_) != len(first):
                w.fail(
                    f"UNION inputs disagree on arity: {len(first)} vs "
                    f"{len(os_)} columns"
                )
            for fa, fb in zip(first, os_):
                w.check()
                try:
                    common_type(fa.dtype, fb.dtype)
                except BallistaError:
                    w.fail(
                        f"UNION column {fa.name!r} has no common type: "
                        f"{fa.dtype.value} vs {fb.dtype.value}",
                        token=fa.name,
                    )
    elif isinstance(node, P.Window):
        ins = w.schema_of(node.input, "input")
        w.check()
        if len(node.names) != len(node.window_exprs):
            w.fail(
                f"window emits {len(node.window_exprs)} expressions "
                f"but {len(node.names)} names"
            )
        for wx in node.window_exprs:
            w.resolve(wx, ins, "window expression")
    elif isinstance(node, P.Percentile):
        ins = w.schema_of(node.input, "input")
        w.check()
        if len(node.group_names) != len(node.group_exprs):
            w.fail("percentile group names/exprs length mismatch")
        for g in node.group_exprs:
            w.resolve(g, ins, "percentile group key")
        for v, q, _name in node.requests:
            vt = w.resolve(v, ins, "percentile value expression")
            if not (vt.is_numeric or vt in (DataType.BOOL, DataType.NULL)):
                w.fail(
                    f"percentile over non-numeric dtype {vt.value} "
                    f"({v.name()!r})"
                )
            w.check()
            if not (0.0 <= q <= 1.0):
                w.fail(f"percentile q={q} outside [0, 1]")


# ------------------------------------------------------------ physical ----


def verify_physical(plan, sql: str | None = None) -> VerifyReport:
    """Statically verify an ExecutionPlan tree; raises
    PlanVerificationError. Exchange-boundary checks (partitioned-join
    partition counts, final-aggregate layout vs the partial spec) are the
    physical tier's additions over the logical walk."""
    w = _Walk("physical", sql)
    _verify_physical_node(w, plan)
    return w.report


def _verify_physical_node(w: _Walk, node) -> None:
    # imported here: analysis must stay importable without pulling the
    # whole exec layer in at module-import time (jit caches, jax)
    from ballista_tpu.distributed_plan import UnresolvedShuffleExec
    from ballista_tpu.exec.aggregate import HashAggregateExec
    from ballista_tpu.exec.joins import HashJoinExec, UnionExec
    from ballista_tpu.exec.mesh import (
        MeshAggregateExec,
        MeshJoinExec,
        MeshSortExec,
        MeshWindowExec,
    )
    from ballista_tpu.exec.pipeline import FilterExec, ProjectionExec
    from ballista_tpu.exec.percentile import PercentileExec
    from ballista_tpu.exec.repartition import HashRepartitionExec
    from ballista_tpu.exec.sort import GlobalLimitExec, SortExec
    from ballista_tpu.exec.window import WindowExec
    from ballista_tpu.executor.shuffle import ShuffleWriterExec

    w.report.nodes += 1
    w.path.append(node.describe())
    try:
        schema = w.schema_of(node, node.describe())
        w.legal_fields(schema)

        if isinstance(node, FilterExec):
            dt = w.resolve(node.predicate, node.input.schema(), "filter predicate")
            if dt not in (DataType.BOOL, DataType.NULL):
                w.fail(
                    f"filter predicate {node.predicate.name()!r} is "
                    f"{dt.value}, not boolean"
                )
        elif isinstance(node, ProjectionExec):
            ins = w.schema_of(node.input, "input")
            for e in node.exprs:
                w.resolve(e, ins, "projection expression")
        elif isinstance(node, (HashJoinExec, MeshJoinExec)):
            ls = w.schema_of(node.left, "left input")
            rs = w.schema_of(node.right, "right input")
            for a, b in node.on:
                ta = w.resolve(a, ls, "left join key")
                tb = w.resolve(b, rs, "right join key")
                w.check()
                try:
                    common_type(ta, tb)
                except BallistaError:
                    w.fail(
                        f"join key dtype mismatch: {a.name()} is "
                        f"{ta.value} but {b.name()} is {tb.value}",
                        token=a.name(),
                    )
            if (
                isinstance(node, HashJoinExec)
                and node.partition_mode == "partitioned"
            ):
                # both sides must present the same bucket count, or task K
                # of one side probes a bucket the other side never wrote
                nl = node.left.output_partitioning().n
                nr = node.right.output_partitioning().n
                w.check()
                if nl != nr:
                    w.fail(
                        "partitioned join inputs disagree on partition "
                        f"count: left={nl}, right={nr}"
                    )
        elif isinstance(node, (HashAggregateExec, MeshAggregateExec)):
            ins = w.schema_of(node.input, "input")
            if isinstance(node, HashAggregateExec) and node.mode == "final":
                # the final merge consumes the partial's wire layout
                # (group keys then state slots); a stage boundary or serde
                # drift that changes it must fail here, not on-device
                spec = node.spec
                expected = list(spec.group_names) + [s.name for s in spec.slots]
                w.check()
                if ins.names != expected:
                    w.fail(
                        "final aggregate input layout does not match the "
                        f"partial spec: got {ins.names}, expected {expected}"
                    )
            else:
                for g in node.group_exprs:
                    w.resolve(g, ins, "group expression")
                for e in node.agg_exprs:
                    for agg in L.find_aggregates(e):
                        _check_aggregate_expr(w, agg, ins)
        elif isinstance(node, (SortExec, MeshSortExec)):
            ins = w.schema_of(node.input, "input")
            for s in node.sort_exprs:
                w.resolve(s.expr, ins, "sort key")
            w.check()
            if node.fetch is not None and node.fetch < 0:
                w.fail(f"sort fetch out of range: {node.fetch}")
        elif isinstance(node, GlobalLimitExec):
            w.check()
            if node.skip < 0 or (node.fetch is not None and node.fetch < 0):
                w.fail(
                    f"limit bounds out of range: skip={node.skip}, "
                    f"fetch={node.fetch}"
                )
        elif isinstance(node, UnionExec):
            first = w.schema_of(node.inputs[0], "input")
            for other in node.inputs[1:]:
                os_ = w.schema_of(other, "input")
                w.check()
                if len(os_) != len(first):
                    w.fail(
                        f"union inputs disagree on arity: {len(first)} vs "
                        f"{len(os_)} columns"
                    )
                for fa, fb in zip(first, os_):
                    w.check()
                    try:
                        common_type(fa.dtype, fb.dtype)
                    except BallistaError:
                        w.fail(
                            f"union column {fa.name!r} has no common type: "
                            f"{fa.dtype.value} vs {fb.dtype.value}",
                            token=fa.name,
                        )
        elif isinstance(node, HashRepartitionExec):
            ins = w.schema_of(node.input, "input")
            for k in node.keys:
                w.resolve(k, ins, "repartition key")
            w.check()
            if node.partitions < 1:
                w.fail(f"repartition into {node.partitions} partitions")
        elif isinstance(node, (WindowExec, MeshWindowExec)):
            local = node._local if isinstance(node, MeshWindowExec) else node
            ins = w.schema_of(node.input, "input")
            for wx in local.window_exprs:
                w.resolve(wx, ins, "window expression")
        elif isinstance(node, PercentileExec):
            ins = w.schema_of(node.input, "input")
            for g in node.group_exprs:
                w.resolve(g, ins, "percentile group key")
            for v, q, _name in node.requests:
                w.resolve(v, ins, "percentile value expression")
                w.check()
                if not (0.0 <= q <= 1.0):
                    w.fail(f"percentile q={q} outside [0, 1]")
        elif isinstance(node, ShuffleWriterExec):
            ins = w.schema_of(node.input, "input")
            for k in node.partition_keys:
                w.resolve(k, ins, "shuffle partition key")
            w.check()
            if node.output_partitions < 1:
                w.fail(
                    f"shuffle writer with {node.output_partitions} output "
                    "partitions"
                )
            if not node.partition_keys and node.output_partitions != 1:
                w.fail(
                    "unkeyed shuffle writer must coalesce to 1 output "
                    f"partition, got {node.output_partitions}"
                )
        elif isinstance(node, UnresolvedShuffleExec):
            w.check()
            if node.output_partition_count < 1 or node.input_partition_count < 1:
                w.fail(
                    "unresolved shuffle with non-positive partition counts: "
                    f"input={node.input_partition_count}, "
                    f"output={node.output_partition_count}"
                )

        for child in node.children():
            _verify_physical_node(w, child)
    finally:
        w.path.pop()


# -------------------------------------------------------------- stages ----


def verify_stages(stages, sql: str | None = None) -> VerifyReport:
    """Stage-DAG well-formedness over DistributedPlanner output (a list of
    QueryStage in dependency order). Verifies each stage's plan, then the
    cross-stage contract every UnresolvedShuffleExec placeholder carries:
    the referenced writer stage exists, appears earlier (so the DAG is
    acyclic), agrees on output partition count, and produces the schema
    the placeholder advertises. Raises PlanVerificationError."""
    from ballista_tpu.distributed_plan import find_unresolved_shuffles
    from ballista_tpu.executor.shuffle import ShuffleWriterExec

    w = _Walk("stages", sql)
    w.check()
    if not stages:
        w.fail("job has no stages")
    by_id: dict[int, object] = {}
    order: dict[int, int] = {}
    for i, stage in enumerate(stages):
        w.check()
        if stage.stage_id in by_id:
            w.path.append(f"stage {stage.stage_id}")
            w.fail(f"duplicate stage id {stage.stage_id}")
        by_id[stage.stage_id] = stage
        order[stage.stage_id] = i
    for stage in stages:
        w.path.append(f"stage {stage.stage_id}")
        try:
            w.check()
            if not isinstance(stage.plan, ShuffleWriterExec):
                w.fail(
                    "stage plan root must be ShuffleWriterExec, got "
                    f"{type(stage.plan).__name__}"
                )
            try:
                sub = verify_physical(stage.plan, sql)
            except PlanVerificationError as e:
                # re-anchor the sub-verifier's operator path under the
                # owning stage so the diagnostic names both
                raise PlanVerificationError(
                    e.reason,
                    path=(f"stage {stage.stage_id}",) + e.path,
                    span=e.span,
                ) from None
            w.report.nodes += sub.nodes
            w.report.checks += sub.checks
            for u in find_unresolved_shuffles(stage.plan):
                w.check()
                ref = by_id.get(u.stage_id)
                if ref is None:
                    w.fail(
                        f"reads stage {u.stage_id}, which does not exist "
                        f"in this job (stages: {sorted(by_id)})"
                    )
                if order[u.stage_id] >= order[stage.stage_id]:
                    w.fail(
                        f"reads stage {u.stage_id}, which is not scheduled "
                        "before it (dependency cycle or mis-ordered plan)"
                    )
                w.check()
                if u.output_partition_count != ref.plan.output_partitions:
                    w.fail(
                        f"partition-count mismatch with stage {u.stage_id}: "
                        f"reader expects {u.output_partition_count} "
                        f"partitions, writer produces "
                        f"{ref.plan.output_partitions}"
                    )
                upstream = ref.plan.input.schema()
                mine = u.schema()
                w.check()
                if [
                    (f.name, f.dtype) for f in mine
                ] != [(f.name, f.dtype) for f in upstream]:
                    w.fail(
                        f"schema mismatch with stage {u.stage_id}: reader "
                        f"expects {mine!r}, writer produces {upstream!r}"
                    )
        finally:
            w.path.pop()
    w.report.detail.append(f"{len(stages)} stages")
    return w.report
