"""AST lint for JAX/TPU hazards (``planlint`` rules).

Static, import-free analysis over Python sources (by default
``ballista_tpu/ops/`` and ``ballista_tpu/exec/``) that flags the coding
patterns that silently destroy TPU throughput or fail only at trace time:

==================  =========================================================
rule                rationale
==================  =========================================================
tracer-branch       Python ``if``/``while`` on a traced array argument inside
                    a jitted function raises ConcretizationTypeError at best
                    and forces a host sync at worst. Branch on static args
                    (``static_argnames``) or use ``jnp.where``/``lax.cond``.
host-sync           ``.item()``, ``np.asarray``/``np.array``, ``float()/
                    int()/bool()`` on a traced argument, and
                    ``jax.device_get`` inside a jitted kernel block the
                    device queue for a full host round trip (~100ms over a
                    tunnelled TPU) per call.
missing-static      An argument used in a shape position (``jnp.zeros(n)``,
                    ``x.reshape(n, -1)``, ``jnp.arange(n)``...) must be in
                    ``static_argnames`` — a traced shape either fails to
                    compile or retraces per distinct value without caching.
dynamic-shape       ``jnp.nonzero``/``jnp.unique``/``jnp.flatnonzero``/
                    one-argument ``jnp.where`` without ``size=`` have
                    value-dependent output shapes: illegal under jit, and a
                    retrace-per-shape hazard outside it. Pad to a static
                    bound and pass ``size=``.
==================  =========================================================

Suppression: append ``# planlint: disable=<rule>[,<rule>...]`` (or
``disable=all``) to the offending line, or to the ``def`` line of a jitted
function to suppress within the whole function. The tier-1 suite asserts
suppressions stay rare.

Also exposed: :func:`static_signature_report` — a per-kernel report of
every jitted function's parameters and which are static, consumable by
``parallel/dryrun.py`` to print the compiled-kernel surface next to the
multi-chip pipeline check.
"""

from __future__ import annotations

import ast
import dataclasses
import pathlib
import re

RULES: dict[str, str] = {
    "tracer-branch": "Python branch on a traced argument inside a jitted "
    "function (use static_argnames, jnp.where, or lax.cond)",
    "host-sync": "host materialization (.item()/float()/np.asarray/"
    "device_get) inside a jitted function",
    "missing-static": "argument used as a shape but not listed in "
    "static_argnames",
    "dynamic-shape": "value-dependent output shape (nonzero/unique/"
    "1-arg where) without size= inside a jitted function",
}

_SUPPRESS_RE = re.compile(r"#\s*planlint:\s*disable=([A-Za-z0-9_,\- ]+)")

# call names (as dotted strings) that force a host round trip
_HOST_SYNC_CALLS = {
    "np.asarray",
    "np.array",
    "numpy.asarray",
    "numpy.array",
    "jax.device_get",
}
# jnp constructors whose FIRST positional argument is a shape/length
_SHAPE_FIRST_ARG = {
    "jnp.zeros",
    "jnp.ones",
    "jnp.empty",
    "jnp.full",
    "jnp.arange",
    "jax.numpy.zeros",
    "jax.numpy.ones",
    "jax.numpy.full",
    "jax.numpy.arange",
}
# methods whose arguments are shapes
_SHAPE_METHODS = {"reshape", "broadcast_to"}
# value-dependent-output-shape primitives needing size=
_DYNAMIC_SHAPE_CALLS = {
    "jnp.nonzero",
    "jnp.flatnonzero",
    "jnp.unique",
    "jax.numpy.nonzero",
    "jax.numpy.flatnonzero",
    "jax.numpy.unique",
}


@dataclasses.dataclass(frozen=True)
class LintDiagnostic:
    file: str
    line: int
    rule: str
    message: str
    kernel: str = ""  # enclosing jitted function, when any

    def __str__(self) -> str:
        where = f" [{self.kernel}]" if self.kernel else ""
        return f"{self.file}:{self.line}: {self.rule}{where}: {self.message}"


@dataclasses.dataclass
class JitKernel:
    """One statically-discovered jitted function."""

    name: str
    file: str
    line: int
    params: tuple[str, ...]
    static: frozenset[str]
    hazards: tuple[LintDiagnostic, ...] = ()


def _dotted(node: ast.AST) -> str | None:
    """'jax.numpy.zeros' for Attribute/Name chains, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _static_argnames(call: ast.Call) -> frozenset[str] | None:
    """The static_argnames tuple of a jax.jit/partial(jax.jit) call, or
    None when absent/undecidable."""
    for kw in call.keywords:
        if kw.arg != "static_argnames":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            return frozenset({v.value})
        if isinstance(v, (ast.Tuple, ast.List)):
            names = set()
            for elt in v.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    names.add(elt.value)
            return frozenset(names)
        return None  # computed dynamically: treat every arg as static
    return frozenset()


def _is_jit_name(node: ast.AST) -> bool:
    d = _dotted(node)
    return d in ("jax.jit", "jit")


def _jit_decoration(
    fn: ast.FunctionDef,
) -> tuple[bool, frozenset[str] | None]:
    """(is-jitted, static_argnames) for a decorated function; static
    None = jitted but statics undecidable (computed expression).

    Recognizes ``@jax.jit``, ``@jax.jit(...)``, and
    ``@[functools.]partial(jax.jit, ...)``."""
    for dec in fn.decorator_list:
        if _is_jit_name(dec):
            return True, frozenset()
        if isinstance(dec, ast.Call):
            if _is_jit_name(dec.func):
                return True, _static_argnames(dec)
            if _dotted(dec.func) in ("partial", "functools.partial"):
                if dec.args and _is_jit_name(dec.args[0]):
                    return True, _static_argnames(dec)
    return False, None


def _jit_call_sites(tree: ast.Module) -> dict[str, frozenset[str] | None]:
    """function-name -> static_argnames for every ``jax.jit(f, ...)`` /
    ``partial(jax.jit, ...)``-style call anywhere in the module (module
    level, class bodies, inside wrapper functions)."""
    sites: dict[str, frozenset[str] | None] = {}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_jit_name(node.func):
            continue
        if node.args and isinstance(node.args[0], ast.Name):
            sites[node.args[0].id] = _static_argnames(node)
    return sites


def _suppressed(source_lines: list[str], lineno: int) -> frozenset[str]:
    line = source_lines[lineno - 1] if 0 < lineno <= len(source_lines) else ""
    m = _SUPPRESS_RE.search(line)
    if not m:
        return frozenset()
    return frozenset(p.strip() for p in m.group(1).split(","))


class _KernelLinter(ast.NodeVisitor):
    """Lints ONE jitted function body."""

    def __init__(
        self,
        fn: ast.FunctionDef,
        static: frozenset[str] | None,
        file: str,
        source_lines: list[str],
    ):
        self.fn = fn
        self.file = file
        self.lines = source_lines
        args = fn.args
        self.params = tuple(
            a.arg
            for a in (args.posonlyargs + args.args + args.kwonlyargs)
            if a.arg not in ("self", "cls")
        )
        # static_argnames undecidable -> assume everything static (no
        # false positives from computed static sets)
        self.static = frozenset(self.params) if static is None else static
        self.traced = frozenset(self.params) - self.static
        self.fn_suppress = _suppressed(source_lines, fn.lineno)
        self.diags: list[LintDiagnostic] = []

    # -- helpers -------------------------------------------------------------
    def _traced_in(self, node: ast.AST) -> set[str]:
        """Traced parameter names used BY VALUE under ``node``.

        Skips two statically-safe shapes: attribute access on a traced
        name (``x.shape``, ``batch.capacity`` — aux/structure data, not a
        tracer), and ``is``/``is not`` identity comparisons (``x is None``
        branches on pytree structure, which jit specializes on)."""
        out: set[str] = set()

        def walk(n: ast.AST) -> None:
            if isinstance(n, ast.Attribute):
                return  # x.attr is static metadata, not the traced value
            if isinstance(n, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in n.ops
            ):
                return
            if isinstance(n, ast.Name) and n.id in self.traced:
                out.add(n.id)
            for c in ast.iter_child_nodes(n):
                walk(c)

        walk(node)
        return out

    def _emit(self, rule: str, lineno: int, message: str) -> None:
        sup = _suppressed(self.lines, lineno) | self.fn_suppress
        if rule in sup or "all" in sup:
            return
        self.diags.append(
            LintDiagnostic(self.file, lineno, rule, message, self.fn.name)
        )

    # -- rules ---------------------------------------------------------------
    def visit_If(self, node: ast.If) -> None:
        traced = self._traced_in(node.test)
        if traced:
            self._emit(
                "tracer-branch",
                node.lineno,
                f"if-branch on traced argument(s) {sorted(traced)}",
            )
        self.generic_visit(node)

    def visit_While(self, node: ast.While) -> None:
        traced = self._traced_in(node.test)
        if traced:
            self._emit(
                "tracer-branch",
                node.lineno,
                f"while-loop on traced argument(s) {sorted(traced)}",
            )
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        # .item() on anything inside a jitted body
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr == "item"
            and not node.args
        ):
            self._emit("host-sync", node.lineno, ".item() inside jitted kernel")
        if d in _HOST_SYNC_CALLS:
            self._emit(
                "host-sync", node.lineno, f"{d}() inside jitted kernel"
            )
        if (
            isinstance(node.func, ast.Name)
            and node.func.id in ("float", "int", "bool")
            and node.args
            and isinstance(node.args[0], ast.Name)
            and node.args[0].id in self.traced
        ):
            self._emit(
                "host-sync",
                node.lineno,
                f"{node.func.id}({node.args[0].id}) materializes a traced "
                "argument",
            )
        # shape positions fed by traced params
        if d in _SHAPE_FIRST_ARG and node.args:
            traced = self._traced_in(node.args[0])
            if traced:
                self._emit(
                    "missing-static",
                    node.lineno,
                    f"{d}() shape uses traced argument(s) {sorted(traced)} "
                    "— add them to static_argnames",
                )
        if (
            isinstance(node.func, ast.Attribute)
            and node.func.attr in _SHAPE_METHODS
        ):
            traced = set()
            for a in node.args:
                traced |= self._traced_in(a)
            if traced:
                self._emit(
                    "missing-static",
                    node.lineno,
                    f".{node.func.attr}() shape uses traced argument(s) "
                    f"{sorted(traced)} — add them to static_argnames",
                )
        # value-dependent output shapes
        has_size = any(kw.arg == "size" for kw in node.keywords)
        if d in _DYNAMIC_SHAPE_CALLS and not has_size:
            self._emit(
                "dynamic-shape",
                node.lineno,
                f"{d}() without size= has a value-dependent output shape",
            )
        if (
            d in ("jnp.where", "jax.numpy.where")
            and len(node.args) == 1
            and not has_size
        ):
            self._emit(
                "dynamic-shape",
                node.lineno,
                "one-argument jnp.where() without size= has a "
                "value-dependent output shape",
            )
        self.generic_visit(node)


def lint_source(
    source: str, filename: str = "<string>"
) -> tuple[list[LintDiagnostic], list[JitKernel]]:
    """Lint one module's source. Returns (diagnostics, jitted kernels)."""
    tree = ast.parse(source, filename=filename)
    lines = source.splitlines()
    sites = _jit_call_sites(tree)
    diags: list[LintDiagnostic] = []
    kernels: list[JitKernel] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.FunctionDef):
            continue
        jitted, static = _jit_decoration(node)
        if not jitted and node.name in sites:
            jitted, static = True, sites[node.name]
        if not jitted:
            continue
        linter = _KernelLinter(node, static, filename, lines)
        for stmt in node.body:
            linter.visit(stmt)
        kernels.append(
            JitKernel(
                name=node.name,
                file=filename,
                line=node.lineno,
                params=linter.params,
                static=frozenset(linter.static & set(linter.params)),
                hazards=tuple(linter.diags),
            )
        )
        diags.extend(linter.diags)
    return diags, kernels


_DEFAULT_TARGETS = ("ops", "exec", "obs")


def _target_files(paths=None) -> list[pathlib.Path]:
    if paths is not None:
        out = []
        for p in paths:
            p = pathlib.Path(p)
            out.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
        return out
    root = pathlib.Path(__file__).resolve().parent.parent
    files: list[pathlib.Path] = []
    for sub in _DEFAULT_TARGETS:
        files.extend(sorted((root / sub).rglob("*.py")))
    return files


def lint_paths(paths=None) -> list[LintDiagnostic]:
    """Lint files/directories (default: ballista_tpu/{ops,exec})."""
    diags: list[LintDiagnostic] = []
    for f in _target_files(paths):
        d, _ = lint_source(f.read_text(), str(f))
        diags.extend(d)
    return diags


def static_signature_report(paths=None) -> dict[str, dict]:
    """Per-kernel static signature report over the target sources:
    ``{"module.function": {"file", "line", "params", "static",
    "hazards"}}``. parallel/dryrun.py prints this next to the multi-chip
    pipeline check so the compiled-kernel surface (and its static/traced
    split) is visible in the same place mesh placement is asserted."""
    report: dict[str, dict] = {}
    for f in _target_files(paths):
        _, kernels = lint_source(f.read_text(), str(f))
        for k in kernels:
            p = pathlib.Path(k.file)
            # qualify with the package dir: ops/aggregate.py and
            # exec/aggregate.py must not collide in the report
            key = f"{p.parent.name}.{p.stem}.{k.name}"
            report[key] = {
                "file": k.file,
                "line": k.line,
                "params": list(k.params),
                "static": sorted(k.static),
                "hazards": [str(h) for h in k.hazards],
            }
    return report


def suppression_count(paths=None) -> int:
    """Number of ``# planlint: disable=`` escape hatches in the targets
    (the tier-1 suite asserts this stays rare)."""
    n = 0
    for f in _target_files(paths):
        n += len(_SUPPRESS_RE.findall(f.read_text()))
    return n
