"""Runtime staleness witness: cache coherence as a checkable invariant.

The static half (stalelint + the declared cache registry) proves the
TREE obeys the coherence contracts; this witness proves the RUNNING
SYSTEM does — the cache analogue of the lock, resource, and replay
witnesses. When enabled, instrumented caches record ``(cache, key,
content-hash-of-value, source-version)`` on every hit, and a SAMPLED
subset of hits must hash-match a fresh re-derivation:

- **result cache** (scheduler serve path): a sampled hit is demoted to a
  miss — the query runs fresh through the full stage machinery, and the
  committed repopulation (:meth:`SchedulerServer._populate_result_cache`)
  must produce the same canonical content hash the cached payload held
  (:func:`expect` at the demotion, :func:`resolve` at repopulation).
- **physical-plan cache** (TpuContext): a sampled hit re-plans the
  logical plan fresh and the structural render of the cached operator
  tree must match the fresh one (:func:`check` with both hashes).

A hash mismatch is a STALE HIT — recorded, counted per cache, and fatal
to :func:`assert_no_stale`. Like the other witnesses, "zero stale" must
never silently mean "zero checks": ``assert_no_stale`` demands a nonzero
check count by default.

One legitimate divergence is carved out: certified **multiset-exact**
rewrites (AQE) re-associate float folds, so a fresh re-derivation may
differ from the served payload in the final ULP of float aggregates
(docs/analysis.md "Exactness") while being byte-identical everywhere
else. The canonical hash is bit-exact and would misread that drift as
staleness, so the result-cache protocol carries the served payload:
on hash mismatch, :func:`resolve` falls back to a value-level
comparison (:func:`tables_equivalent` — exact for non-float columns,
relative tolerance for floats) before declaring a stale hit. A wrong
row, a missing row, or a drifted non-float value still fails.

Sampling is DETERMINISTIC (detlint: no RNG in the data plane): per-cache
hit counters sample the k-th hit whenever ``floor(k*rate)`` crosses an
integer boundary, so ``rate=1`` checks every hit (the test default) and
``rate=0.25`` checks every 4th, reproducibly.

Default OFF: ``BALLISTA_CACHE_WITNESS=1`` (or :func:`enable`) turns it
on; ``BALLISTA_CACHE_WITNESS_SAMPLE`` sets the rate. Exposed on
``/api/metrics`` as ``ballista_cache_witness_checks_total``
(obs/prometheus.py) so chaos/soak runs scrape coherence the same way
they scrape replay/reswitness state."""

from __future__ import annotations

import logging
import math
import os
import threading

ENV_WITNESS = "BALLISTA_CACHE_WITNESS"
ENV_SAMPLE = "BALLISTA_CACHE_WITNESS_SAMPLE"

log = logging.getLogger(__name__)

_enabled = os.environ.get(ENV_WITNESS, "") in ("1", "true", "yes")


def _env_rate() -> float:
    raw = os.environ.get(ENV_SAMPLE, "") or "1"
    try:
        rate = float(raw)
    except ValueError:
        return 1.0
    return min(1.0, max(0.0, rate))


_sample_rate = _env_rate()

_lock = threading.Lock()
_hits: dict[str, int] = {}  # lifetime hits observed per cache
_checks: dict[tuple[str, str], int] = {}  # (cache, match|stale) -> count
# (cache, key) -> (expected hash, served payload bytes | None)
_pending: dict[tuple[str, str], tuple[str, bytes | None]] = {}
_stale: list[dict] = []

# float drift tolerance for the value-level fallback compare: certified
# multiset-exact rewrites shift float sums by ~1e-15 relative (measured
# on q3); a genuinely stale value — one missing row of the sum — is
# orders of magnitude past this
FLOAT_REL_TOL = 1e-9


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = flag


def enabled() -> bool:
    return _enabled


def set_sample_rate(rate: float) -> None:
    global _sample_rate
    _sample_rate = min(1.0, max(0.0, rate))


def sample_rate() -> float:
    return _sample_rate


def should_sample(cache: str) -> bool:
    """Count one hit for ``cache``; True when this hit is in the sampled
    subset (deterministic — no RNG, reproducible across replays)."""
    if not _enabled:
        return False
    with _lock:
        n = _hits.get(cache, 0) + 1
        _hits[cache] = n
    rate = _sample_rate
    if rate <= 0.0:
        return False
    return math.floor(n * rate) > math.floor((n - 1) * rate)


def expect(
    cache: str, key, expected_hash: str, version=None, payload=None
) -> None:
    """Register the content hash a demoted (sampled) hit WOULD have
    served; the fresh re-derivation resolves it. ``payload`` (the served
    IPC bytes) enables the value-level fallback compare on hash
    mismatch — without it, any mismatch is stale."""
    with _lock:
        _pending[(cache, repr(key))] = (expected_hash, payload)


def tables_equivalent(served, fresh, rel_tol: float = FLOAT_REL_TOL) -> bool:
    """Value-level equivalence: identical schema/rows, non-float columns
    bit-exact, float columns within ``rel_tol`` relative — the drift
    envelope certified multiset-exact rewrites are allowed
    (docs/analysis.md "Exactness"). Rows are aligned by sorting on the
    non-float columns first, so a last-ULP float shift cannot shuffle
    the comparison."""
    import pyarrow as pa

    if served.schema != fresh.schema or served.num_rows != fresh.num_rows:
        return False
    float_cols = [
        f.name for f in served.schema if pa.types.is_floating(f.type)
    ]
    other = [f.name for f in served.schema if f.name not in float_cols]
    keys = [(n, "ascending") for n in other + float_cols]
    s = served.combine_chunks().sort_by(keys)
    f2 = fresh.combine_chunks().sort_by(keys)
    for name in other:
        if not s.column(name).equals(f2.column(name)):
            return False
    for name in float_cols:
        for x, y in zip(
            s.column(name).to_pylist(), f2.column(name).to_pylist()
        ):
            if x is None or y is None:
                if x is not y:
                    return False
            elif x != y and abs(x - y) > rel_tol * max(
                abs(x), abs(y), 1.0
            ):
                return False
    return True


def resolve(cache: str, key, actual_hash: str, version=None, table=None) -> None:
    """Compare a fresh re-derivation against a pending expectation for
    the same key. No pending expectation -> no check recorded (ordinary
    repopulation, nothing was served from cache). On hash mismatch,
    falls back to :func:`tables_equivalent` when the demotion carried
    the served payload and ``table`` is the fresh result."""
    with _lock:
        rec = _pending.pop((cache, repr(key)), None)
    if rec is None:
        return
    expected, payload = rec
    if expected != actual_hash and payload is not None and table is not None:
        try:
            from ballista_tpu.scheduler.result_cache import ipc_to_table

            if tables_equivalent(ipc_to_table(payload), table):
                # certified float drift, not staleness: count the check
                # as a match by reusing the expected hash
                _record(cache, key, expected, expected, version)
                return
        except Exception:  # noqa: BLE001 — a broken fallback compare
            # must report as stale, never crash the serve path
            log.exception("stalewitness fallback compare failed")
    _record(cache, key, expected, actual_hash, version)


def check(
    cache: str, key, served_hash: str, fresh_hash: str, version=None
) -> None:
    """Direct compare for synchronous re-derivation sites (the cached
    value and the fresh one are both in hand)."""
    _record(cache, key, served_hash, fresh_hash, version)


def _record(cache, key, expected, got, version) -> None:
    outcome = "match" if expected == got else "stale"
    with _lock:
        k = (cache, outcome)
        _checks[k] = _checks.get(k, 0) + 1
        if outcome == "stale":
            _stale.append({
                "cache": cache,
                "key": repr(key),
                "expected": expected,
                "got": got,
                "version": repr(version),
            })
    if outcome == "stale":
        log.error(
            "cache witness STALE HIT in %s at %r: served %s, fresh %s",
            cache, key, expected, got,
        )


def counters() -> dict[tuple[str, str], int]:
    """(cache, outcome) -> count, for the prometheus family."""
    with _lock:
        return dict(_checks)


def hit_counts() -> dict[str, int]:
    with _lock:
        return dict(_hits)


def pending_count() -> int:
    """Demotions whose fresh run has not repopulated yet (a chaos test
    drains this to zero before asserting)."""
    with _lock:
        return len(_pending)


def stale_hits() -> list[dict]:
    with _lock:
        return [dict(s) for s in _stale]


def summary() -> str:
    cs = counters()
    total = sum(cs.values())
    stale = sum(n for (c, o), n in cs.items() if o == "stale")
    per = ", ".join(
        f"{c}:{o}={n}" for (c, o), n in sorted(cs.items())
    )
    return (
        f"{total} checks ({per or 'none'}), {stale} stale, "
        f"{pending_count()} pending"
    )


def assert_no_stale(require_checks: bool = True) -> None:
    """Zero stale hits (and, by default, a nonzero check count — a
    witness that saw no traffic proves nothing)."""
    bad = stale_hits()
    if bad:
        lines = [
            f"{s['cache']}[{s['key']}]: served {s['expected']}, "
            f"fresh {s['got']}"
            for s in bad
        ]
        raise AssertionError(
            f"{len(bad)} stale cache hits:\n" + "\n".join(lines)
        )
    if require_checks and not counters():
        raise AssertionError(
            "cache witness checked nothing — enable() before the run, "
            "or the instrumentation points were never reached"
        )


def reset() -> None:
    with _lock:
        _hits.clear()
        _checks.clear()
        _pending.clear()
        _stale.clear()
