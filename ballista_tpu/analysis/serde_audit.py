"""Serde-closure audit: prove the proto vocabulary is TOTAL.

Structurally enumerates every logical plan node class, physical operator
class, and expression class the engine defines, auto-generates round-trip
exemplar instances for each, and asserts:

1. **Coverage** — every class either round-trips through the codec or is
   named in an explicit exemption table with a reason. A new node class
   added without serde (or without a deliberate exemption) fails the
   tier-1 suite at collection time instead of failing a distributed job at
   executor runtime (the MeshSort ``fetch=None`` class of bug, PR 1).
2. **Byte stability** — ``encode(decode(encode(x))) == encode(x)``, which
   catches defaulted/optional proto fields silently dropped on one side of
   the round trip (display-string comparison alone misses fields that do
   not render).
3. **Display fidelity** — the decoded plan renders identically.

Run as a tier-1 test (tests/test_serde_closure.py) or ad hoc via
``python -m ballista_tpu.analysis.serde_audit``.
"""

from __future__ import annotations

import dataclasses

from ballista_tpu.datatypes import DataType, Field, Schema
from ballista_tpu.expr import logical as L
from ballista_tpu.plan import logical as P

# classes deliberately OUTSIDE the serde vocabulary; each needs a reason
# (the audit fails on any class that is neither covered nor listed here)
EXEMPT_PHYSICAL: dict[str, str] = {
    "_StagedFileScanExec": "abstract staged-scan base; csv/avro subclasses "
    "carry the wire format",
}
EXEMPT_LOGICAL: dict[str, str] = {}
EXEMPT_EXPR: dict[str, str] = {
    "WindowFunction": "serialized via WindowExprNode inside Window plan "
    "nodes (audited separately below), never as a bare ExprNode",
}


@dataclasses.dataclass
class AuditResult:
    domain: str  # "expr" | "logical" | "physical"
    covered: list[str]
    exempt: dict[str, str]
    missing: list[str]  # classes with neither round-trip nor exemption
    failures: list[str]  # round-trip breakages

    @property
    def ok(self) -> bool:
        return not self.missing and not self.failures

    def summary(self) -> str:
        s = (
            f"{self.domain}: {len(self.covered)} classes round-tripped, "
            f"{len(self.exempt)} exempt"
        )
        if self.missing:
            s += f"; MISSING serde coverage: {sorted(self.missing)}"
        if self.failures:
            s += "; FAILURES:\n  " + "\n  ".join(self.failures)
        return s


def _subclasses(base: type) -> set[type]:
    out: set[type] = set()

    def walk(c: type) -> None:
        for s in c.__subclasses__():
            if s not in out:
                out.add(s)
                walk(s)

    walk(base)
    return out


def _import_operator_modules() -> None:
    """Import every module that may define ExecutionPlan subclasses.

    ``__subclasses__`` only sees classes whose defining module has been
    imported — without this sweep, a new ``exec/newop.py`` operator would
    be invisible to the closure audit and the 'vocabulary is TOTAL' claim
    would be silently hollow. Import errors propagate: a broken operator
    module must fail the audit loudly, not hide its classes."""
    import importlib
    import pkgutil

    import ballista_tpu.distributed_plan  # noqa: F401
    import ballista_tpu.exec as exec_pkg
    import ballista_tpu.executor as executor_pkg

    for pkg in (exec_pkg, executor_pkg):
        for m in pkgutil.iter_modules(pkg.__path__):
            if m.name.startswith("__"):
                continue
            importlib.import_module(f"{pkg.__name__}.{m.name}")


# -------------------------------------------------------------- exprs -----

_COL = L.Column("a")
_COLB = L.Column("b")
_LIT = L.Literal(3, DataType.INT64)
_PRED = L.BinaryExpr(_COL, L.Operator.GT, _LIT)


def _expr_exemplars() -> dict[str, list[L.Expr]]:
    return {
        "Column": [L.Column("a"), L.Column("t.a")],
        "Literal": [
            L.Literal(None, DataType.NULL),
            L.Literal(None, DataType.INT64),
            L.Literal(False, DataType.BOOL),
            L.Literal(0, DataType.INT32),
            L.Literal(-7, DataType.INT64),
            L.Literal(0.0, DataType.FLOAT64),
            L.Literal(1.5, DataType.FLOAT32),
            L.Literal("", DataType.STRING),
            L.Literal("x'y", DataType.STRING),
            L.Literal(0, DataType.DATE32),
            L.Literal(-1, DataType.TIMESTAMP_US),
        ],
        "IntervalLiteral": [L.IntervalLiteral(0, 0), L.IntervalLiteral(13, -2)],
        "BinaryExpr": [
            L.BinaryExpr(_COL, op, _LIT) for op in L.Operator
        ],
        "Not": [L.Not(_PRED)],
        "Negative": [L.Negative(_COL)],
        "IsNull": [L.IsNull(_COL)],
        "IsNotNull": [L.IsNotNull(_COL)],
        "Cast": [L.Cast(_COL, dt) for dt in DataType],
        "Case": [
            L.Case((), _LIT),
            L.Case(((_PRED, _LIT),), None),
            L.Case(((_PRED, _LIT), (L.IsNull(_COL), _COLB)), _COL),
        ],
        "InList": [
            L.InList(_COL, (), False),
            L.InList(_COL, (_LIT, L.Literal(4, DataType.INT64)), True),
        ],
        "Between": [L.Between(_COL, _LIT, _COLB, True)],
        "Like": [L.Like(_COL, "a%_b", True), L.Like(_COL, "", False)],
        "Alias": [L.Alias(_PRED, "p")],
        "Wildcard": [L.Wildcard()],
        "AggregateExpr": (
            [L.AggregateExpr(f, _COL) for f in L.AggFunc]
            + [
                L.AggregateExpr(L.AggFunc.COUNT, L.Wildcard()),
                L.AggregateExpr(L.AggFunc.SUM, _COL, distinct=True),
                L.AggregateExpr(L.AggFunc.CORR, _COL, arg2=_COLB),
            ]
        ),
        "PercentileExpr": [
            L.PercentileExpr(_COL, 0.0),
            L.PercentileExpr(_COL, 0.5),
            L.PercentileExpr(_COL, 1.0),
        ],
        "UdafExpr": [L.UdafExpr("my_agg", _COL)],
        "ScalarFunction": [
            L.ScalarFunction("abs", (_COL,)),
            L.ScalarFunction("coalesce", (_COL, _LIT)),
            L.ScalarFunction("substr", (_COL, _LIT, _LIT)),
        ],
    }


def _window_exemplars() -> list[L.WindowFunction]:
    return [
        L.WindowFunction("row_number", (), ((_COLB, False, None),)),
        L.WindowFunction(
            "dense_rank", (_COL,), ((_COLB, True, True),), offset=1
        ),
        L.WindowFunction("lag", (_COL,), ((_COLB, True, False),), arg=_COLB,
                         offset=0),
        L.WindowFunction("lead", (), ((_COLB, True, None),), arg=_COLB,
                         offset=3),
        L.WindowFunction(
            "sum",
            (_COL,),
            ((_COLB, True, None),),
            arg=_COLB,
            frame=L.WindowFrame("rows", "p", 2, "f", 1),
        ),
        L.WindowFunction(
            "count",
            (),
            (),
            arg=_COL,
            frame=L.WindowFrame("range", "up", 0, "cur", 0),
        ),
    ]


def audit_expressions() -> AuditResult:
    from ballista_tpu.proto import pb
    from ballista_tpu.serde import (
        _window_expr_from_proto,
        _window_expr_to_proto,
        expr_from_proto,
        expr_to_proto,
    )

    exemplars = _expr_exemplars()
    covered: list[str] = []
    failures: list[str] = []
    for cname, instances in exemplars.items():
        ok = True
        for e in instances:
            try:
                enc = expr_to_proto(e).SerializeToString()
                back = expr_from_proto(pb.ExprNode.FromString(enc))
                enc2 = expr_to_proto(back).SerializeToString()
            except Exception as exc:  # noqa: BLE001 — report, don't abort
                failures.append(f"{cname} {e!r}: {type(exc).__name__}: {exc}")
                ok = False
                continue
            if enc2 != enc:
                failures.append(
                    f"{cname} {e.name()!r}: re-encode differs (field "
                    "dropped or defaulted across the round trip)"
                )
                ok = False
            elif back.name() != e.name():
                failures.append(
                    f"{cname}: display drift {e.name()!r} -> {back.name()!r}"
                )
                ok = False
        if ok:
            covered.append(cname)
    # WindowFunction rides WindowExprNode
    for wf in _window_exemplars():
        try:
            enc = _window_expr_to_proto(wf).SerializeToString()
            back = _window_expr_from_proto(pb.WindowExprNode.FromString(enc))
            enc2 = _window_expr_to_proto(back).SerializeToString()
        except Exception as exc:  # noqa: BLE001
            failures.append(
                f"WindowFunction {wf.name()!r}: {type(exc).__name__}: {exc}"
            )
            continue
        if enc2 != enc or back.name() != wf.name():
            failures.append(
                f"WindowFunction {wf.name()!r}: round trip drift"
            )
    all_classes = {
        c.__name__ for c in _subclasses(L.Expr) if c.__module__ == L.__name__
    }
    missing = sorted(
        all_classes - set(covered) - set(EXEMPT_EXPR) - set(exemplars)
    )
    return AuditResult("expr", covered, EXEMPT_EXPR, missing, failures)


# ------------------------------------------------------------ logical -----

_SCHEMA = Schema(
    [
        Field("a", DataType.INT64, False),
        Field("b", DataType.FLOAT64),
        Field("s", DataType.STRING),
    ]
)
_SCHEMA2 = Schema([Field("k", DataType.INT64, False), Field("w", DataType.FLOAT64)])


def _logical_exemplars() -> dict[str, list[P.LogicalPlan]]:
    scan = P.TableScan("t", _SCHEMA)
    scan2 = P.TableScan("d", _SCHEMA2)
    fscan = P.TableScan(
        "f",
        _SCHEMA,
        projection=("a", "b"),
        filters=(_PRED,),
        source=("csv", "/data/f.csv", True, "|"),
    )
    return {
        "TableScan": [scan, fscan, P.TableScan("p", _SCHEMA, (),
                      source=("parquet", "/data/p.parquet", False, ","))],
        "EmptyRelation": [
            P.EmptyRelation(True, Schema([])),
            P.EmptyRelation(False, _SCHEMA2),
        ],
        "Projection": [P.Projection(scan, (_COL, L.Alias(_PRED, "p")))],
        "Filter": [P.Filter(scan, _PRED)],
        "Aggregate": [
            P.Aggregate(
                scan,
                (_COL,),
                (L.AggregateExpr(L.AggFunc.SUM, _COLB),),
            ),
            P.Aggregate(scan, (), (L.AggregateExpr(L.AggFunc.COUNT, L.Wildcard()),)),
        ],
        "Sort": [
            P.Sort(scan, (P.SortExpr(_COL, False, True),
                          P.SortExpr(_COLB, True, False))),
        ],
        "Limit": [P.Limit(scan, 0, None), P.Limit(scan, 5, 0), P.Limit(scan, 0, 7)],
        "Join": [
            P.Join(scan, scan2, ((_COL, L.Column("k")),), P.JoinType.INNER),
            P.Join(
                scan, scan2, ((_COL, L.Column("k")),), P.JoinType.LEFT,
                filter=L.BinaryExpr(_COLB, L.Operator.LT, L.Column("w")),
            ),
            P.Join(scan, scan2, ((_COL, L.Column("k")),), P.JoinType.ANTI),
        ],
        "CrossJoin": [P.CrossJoin(scan, scan2)],
        "Union": [P.Union((scan, scan), all=True), P.Union((scan, scan), all=False)],
        "Distinct": [P.Distinct(scan)],
        "Window": [
            P.Window(scan, tuple(_window_exemplars()[:2]), ("rn", "dr")),
        ],
        "Percentile": [
            P.Percentile(
                scan, (_COL,), ("g0",), ((_COLB, 0.5, "p50"), (_COLB, 0.9, "p90"))
            ),
        ],
        "SubqueryAlias": [P.SubqueryAlias(scan, "x")],
    }


def audit_logical() -> AuditResult:
    from ballista_tpu.proto import pb
    from ballista_tpu.serde import logical_from_proto, logical_to_proto

    covered: list[str] = []
    failures: list[str] = []
    exemplars = _logical_exemplars()
    for cname, plans in exemplars.items():
        ok = True
        for plan in plans:
            try:
                enc = logical_to_proto(plan).SerializeToString()
                back = logical_from_proto(pb.LogicalPlanNode.FromString(enc))
                enc2 = logical_to_proto(back).SerializeToString()
            except Exception as exc:  # noqa: BLE001
                failures.append(
                    f"{cname} [{plan.describe()}]: {type(exc).__name__}: {exc}"
                )
                ok = False
                continue
            if enc2 != enc:
                failures.append(
                    f"{cname} [{plan.describe()}]: re-encode differs (field "
                    "dropped or defaulted across the round trip)"
                )
                ok = False
            elif back.display() != plan.display():
                failures.append(
                    f"{cname}: display drift\n{plan.display()}\n--\n"
                    f"{back.display()}"
                )
                ok = False
        if ok:
            covered.append(cname)
    all_classes = {
        c.__name__
        for c in _subclasses(P.LogicalPlan)
        if c.__module__ == P.__name__
    }
    missing = sorted(
        all_classes - set(covered) - set(EXEMPT_LOGICAL) - set(exemplars)
    )
    return AuditResult("logical", covered, EXEMPT_LOGICAL, missing, failures)


# ----------------------------------------------------------- physical -----


def _physical_exemplars(ctx):
    """Exemplar ExecutionPlan trees covering the full serde vocabulary.

    ``ctx`` is a TpuContext with tables 't' (_SCHEMA) and 'd' (_SCHEMA2)
    registered — memory scans resolve through it on decode, mirroring the
    distributed provider contract."""
    from ballista_tpu.distributed_plan import UnresolvedShuffleExec
    from ballista_tpu.exec.aggregate import HashAggregateExec
    from ballista_tpu.exec.joins import (
        CrossJoinExec,
        EmptyExec,
        HashJoinExec,
        UnionExec,
    )
    from ballista_tpu.exec.percentile import PercentileExec
    from ballista_tpu.exec.pipeline import (
        CoalescePartitionsExec,
        FilterExec,
        ProjectionExec,
        RenameExec,
    )
    from ballista_tpu.exec.repartition import HashRepartitionExec
    from ballista_tpu.exec.scan import AvroScanExec, CsvScanExec, ParquetScanExec
    from ballista_tpu.exec.sort import GlobalLimitExec, SortExec
    from ballista_tpu.exec.window import WindowExec
    from ballista_tpu.executor.shuffle import ShuffleWriterExec
    from ballista_tpu.executor.reader import ShuffleReaderExec
    from ballista_tpu.scheduler_types import PartitionLocation

    def mem():
        s = ctx.scan("t", None, 2)
        s.table_name = "t"  # the physical planner stamps this on real plans
        return s

    def mem2():
        s = ctx.scan("d", None, 2)
        s.table_name = "d"
        return s

    csv = CsvScanExec("/data/f.csv", _SCHEMA, True, "|", ["a", "b"], 2)
    csv.table_name = "f"  # planner-stamped; decode must preserve it
    pq = ParquetScanExec("/data/p.parquet", _SCHEMA, None, 3, predicates=[_PRED])
    pq.table_name = "p"
    avro = AvroScanExec("/data/a.avro", _SCHEMA, None, 1)
    partial = HashAggregateExec(
        mem(), [_COL], [L.AggregateExpr(L.AggFunc.SUM, _COLB)], mode="partial"
    )
    final = HashAggregateExec(
        CoalescePartitionsExec(partial),
        [_COL],
        [L.AggregateExpr(L.AggFunc.SUM, _COLB)],
        mode="final",
        spec=partial.spec,
        planned_input_schema=partial.planned_input_schema,
    )
    join_on = [(_COL, L.Column("k"))]
    loc = PartitionLocation(
        job_id="j1", stage_id=1, partition=0, executor_id="e1",
        host="h", port=50050, path="/w/p0.arrow",
    )
    plans = [
        mem(),
        csv,
        pq,
        avro,
        FilterExec(mem(), _PRED),
        ProjectionExec(mem(), [_COL, L.Alias(_PRED, "p")]),
        partial,
        final,
        SortExec(mem(), [P.SortExpr(_COL, False, True)], None),
        SortExec(mem(), [P.SortExpr(_COL)], 5),
        GlobalLimitExec(CoalescePartitionsExec(mem()), 2, 9),
        GlobalLimitExec(CoalescePartitionsExec(mem()), 0, None),
        HashJoinExec(mem(), mem2(), join_on, P.JoinType.INNER),
        HashJoinExec(
            mem(), mem2(), join_on, P.JoinType.LEFT,
            filter=L.BinaryExpr(_COLB, L.Operator.LT, L.Column("w")),
        ),
        HashJoinExec(
            HashRepartitionExec(mem(), [_COL], 4),
            HashRepartitionExec(mem2(), [L.Column("k")], 4),
            join_on, P.JoinType.SEMI, partition_mode="partitioned",
        ),
        HashRepartitionExec(mem(), [_COL, _COLB], 3),
        CrossJoinExec(mem(), mem2()),
        UnionExec([mem(), mem()]),
        RenameExec(mem(), Schema([Field(f"x.{f.name}", f.dtype, f.nullable)
                                  for f in _SCHEMA])),
        CoalescePartitionsExec(mem()),
        WindowExec(mem(), list(_window_exemplars()[:2]), ["rn", "dr"]),
        PercentileExec(mem(), [_COL], ["g0"], [(_COLB, 0.5, "p50")]),
        EmptyExec(True, Schema([])),
        EmptyExec(False, _SCHEMA2),
        ShuffleWriterExec("job1", 3, HashRepartitionExec(mem(), [_COL], 4),
                          [_COL], 4),
        ShuffleWriterExec("job1", 4, mem(), [], 1),
        ShuffleReaderExec([[loc], []], _SCHEMA),
        UnresolvedShuffleExec(2, _SCHEMA, 3, 4),
    ]
    # mesh tier: planned by a mesh-capable scheduler, decoded by the
    # executor against ITS device mesh — must cross serde
    from ballista_tpu.exec.mesh import (
        MeshAggregateExec,
        MeshJoinExec,
        MeshSortExec,
        MeshWindowExec,
    )

    class _PlanningHandle:
        """Planning-only stand-in (the scheduler never executes these)."""

    rt = _PlanningHandle()
    plans += [
        MeshAggregateExec(
            mem(), [_COL], [L.AggregateExpr(L.AggFunc.SUM, _COLB)], rt
        ),
        MeshJoinExec(mem(), mem2(), join_on, P.JoinType.INNER, None, rt),
        MeshSortExec(mem(), [P.SortExpr(_COL)], None, rt),
        MeshSortExec(mem(), [P.SortExpr(_COL)], 10, rt),
        MeshWindowExec(
            mem(),
            [
                L.WindowFunction(
                    "row_number", (_COL,), ((_COLB, False, None),)
                )
            ],
            ["rn"],
            rt,
        ),
    ]
    return plans


def audit_physical(ctx=None) -> AuditResult:
    """Round-trip the physical vocabulary through BallistaCodec and check
    class coverage. A fresh single-process TpuContext serves as the memory
    provider when none is given."""
    from ballista_tpu.proto import pb
    from ballista_tpu.serde import BallistaCodec

    if ctx is None:
        import pyarrow as pa

        from ballista_tpu.exec.context import TpuContext

        ctx = TpuContext()
        ctx.register_table(
            "t", pa.table({"a": [1, 2], "b": [0.5, 1.5], "s": ["x", "y"]})
        )
        ctx.register_table("d", pa.table({"k": [1], "w": [2.0]}))

    class _NoMesh:
        """Decode-side mesh handle: the audit checks the WIRE, it never
        executes — building a real device mesh here would drag jax into
        a pure-serde test."""

    codec = BallistaCodec(provider=ctx, mesh_runtime=_NoMesh())
    covered: set[str] = set()
    failures: list[str] = []
    for plan in _physical_exemplars(ctx):
        observed = {type(p).__name__ for p in _walk_plan(plan)}
        try:
            enc = codec.physical_to_proto(plan).SerializeToString()
            back = codec.physical_from_proto(pb.PhysicalPlanNode.FromString(enc))
            enc2 = codec.physical_to_proto(back).SerializeToString()
        except Exception as exc:  # noqa: BLE001
            failures.append(
                f"[{plan.describe()}]: {type(exc).__name__}: {exc}"
            )
            continue
        if enc2 != enc:
            failures.append(
                f"[{plan.describe()}]: re-encode differs (field dropped "
                "or defaulted across the round trip)"
            )
        elif back.display() != plan.display():
            failures.append(
                f"display drift:\n{plan.display()}\n--\n{back.display()}"
            )
        else:
            covered |= observed
    from ballista_tpu.exec.base import ExecutionPlan

    _import_operator_modules()
    all_classes = {
        c.__name__
        for c in _subclasses(ExecutionPlan)
        if c.__module__.startswith("ballista_tpu.")
    }
    missing = sorted(all_classes - covered - set(EXEMPT_PHYSICAL))
    return AuditResult(
        "physical", sorted(covered), EXEMPT_PHYSICAL, missing, failures
    )


def _walk_plan(plan):
    yield plan
    for c in plan.children():
        yield from _walk_plan(c)


def main() -> int:
    results = [audit_expressions(), audit_logical(), audit_physical()]
    ok = True
    for r in results:
        print(r.summary())
        ok = ok and r.ok
    return 0 if ok else 1


if __name__ == "__main__":
    raise SystemExit(main())
