"""protodrift: wire-schema drift detection for protoc-less proto edits.

The image carries no ``protoc``, so every wire change since PR 2 has been
made by mutating the serialized ``FileDescriptorProto`` inside
``ballista_tpu/proto/*_pb2.py`` and editing ``proto/*.proto`` **by hand,
in parallel** ("proto text updated in sync — trust me"). Three PRs of
descriptor mutations later (PhysicalMeshWindowNode, GetShuffleLocations,
heartbeat metrics), nothing mechanical proves the two views of the wire
format still agree. protodrift closes that:

- **text↔descriptor diff** — ``proto/ballista_tpu.proto`` (and
  ``etcd.proto``) is parsed with a minimal proto3 grammar and compared
  against the LIVE descriptor pool of the generated module: message set,
  per-field name/number/label/type, enum values, and service RPC
  signatures (incl. streaming flags) must all agree. The descriptor is
  what actually crosses the wire; the text is what humans review — drift
  between them is a silent protocol fork.
- **field-number ledger** — ``proto/field_numbers.json`` commits every
  ``(message, field) -> number`` assignment ever made. Numbers are the
  real wire contract (names never cross it): the ledger forbids
  *renumbering* an existing field, *reusing* a retired number for a new
  field, and *removing* a field without retiring its number into the
  ledger's ``__retired__`` section. A new field must be appended to the
  ledger in the same commit — which is exactly the reviewable artifact a
  protoc setup would have produced.

Run via ``python -m ballista_tpu.analysis`` (analyzer name
``proto-drift``) or :func:`run` directly; ``generate_ledger()`` emits the
current descriptor's ledger for bootstrap / intentional updates.
"""

from __future__ import annotations

import dataclasses
import json
import pathlib
import re

# descriptor FieldDescriptor.type -> proto text scalar name
_SCALAR_TYPES = {
    1: "double", 2: "float", 3: "int64", 4: "uint64", 5: "int32",
    6: "fixed64", 7: "fixed32", 8: "bool", 9: "string", 12: "bytes",
    13: "uint32", 15: "sfixed32", 16: "sfixed64", 17: "sint32",
    18: "sint64",
}
_TYPE_MESSAGE = 11
_TYPE_ENUM = 14
_LABEL_REPEATED = 3


@dataclasses.dataclass
class ProtoModel:
    """One file's wire surface, from either the text or the descriptor."""

    package: str = ""
    # message fq-name (dot-nested, package-relative) ->
    #   field name -> (number, repeated, type-terminal-name)
    messages: dict[str, dict[str, tuple[int, bool, str]]] = (
        dataclasses.field(default_factory=dict)
    )
    # enum name -> {value name -> number}
    enums: dict[str, dict[str, int]] = dataclasses.field(
        default_factory=dict
    )
    # service name -> {rpc name -> (in, out, in_stream, out_stream)}
    services: dict[str, dict[str, tuple[str, str, bool, bool]]] = (
        dataclasses.field(default_factory=dict)
    )


# --------------------------------------------------------------------------
# proto3 text parser (the subset these files use)
# --------------------------------------------------------------------------

_FIELD_RE = re.compile(
    r"^(repeated\s+|optional\s+)?"
    r"(map\s*<\s*[\w.]+\s*,\s*[\w.]+\s*>|[\w.]+)\s+"
    r"(\w+)\s*=\s*(\d+)\s*(?:\[[^\]]*\])?\s*;$"
)
_ENUM_VAL_RE = re.compile(r"^(\w+)\s*=\s*(\d+)\s*;$")
_RPC_RE = re.compile(
    r"^rpc\s+(\w+)\s*\(\s*(stream\s+)?([\w.]+)\s*\)\s*"
    r"returns\s*\(\s*(stream\s+)?([\w.]+)\s*\)\s*(?:\{\s*\})?;?$"
)


def _strip_comments(text: str) -> str:
    text = re.sub(r"//[^\n]*", "", text)
    return re.sub(r"/\*.*?\*/", "", text, flags=re.S)


def _norm_type(t: str) -> str:
    """Package-insensitive terminal type name ('.ballista_tpu.ExprNode' ->
    'ExprNode'); map types canonicalized without spaces."""
    t = t.strip()
    m = re.match(r"map\s*<\s*([\w.]+)\s*,\s*([\w.]+)\s*>", t)
    if m:
        return f"map<{_norm_type(m.group(1))},{_norm_type(m.group(2))}>"
    return t.split(".")[-1]


def _split_statements(body: str):
    """Yield (statement, block) at one brace depth: 'message Foo' with its
    braced body, or a plain ';'-terminated statement with block None."""
    i, n = 0, len(body)
    while i < n:
        ch = body[i]
        if ch in " \t\r\n":
            i += 1
            continue
        j = i
        depth = 0
        while j < n:
            c = body[j]
            if c == "{":
                if depth == 0:
                    head = body[i:j].strip()
                    depth = 1
                    k = j + 1
                    while k < n and depth:
                        if body[k] == "{":
                            depth += 1
                        elif body[k] == "}":
                            depth -= 1
                        k += 1
                    yield head, body[j + 1:k - 1]
                    i = k
                    break
            elif c == ";" and depth == 0:
                yield body[i:j + 1].strip(), None
                i = j + 1
                break
            j += 1
        else:
            leftover = body[i:].strip()
            if leftover:
                yield leftover, None
            return


def parse_proto_text(text: str) -> ProtoModel:
    model = ProtoModel()
    text = _strip_comments(text)
    for head, block in _split_statements(text):
        if head.startswith("package"):
            model.package = head.split()[1].rstrip(";")
        elif head.startswith("message "):
            _parse_message(head.split()[1], block or "", "", model)
        elif head.startswith("enum "):
            model.enums[head.split()[1]] = _parse_enum(block or "")
        elif head.startswith("service "):
            model.services[head.split()[1]] = _parse_service(block or "")
        # syntax / option / import: irrelevant to the wire surface here
    return model


def _parse_message(
    name: str, block: str, prefix: str, model: ProtoModel
) -> None:
    fq = f"{prefix}.{name}" if prefix else name
    fields: dict[str, tuple[int, bool, str]] = {}
    for head, sub in _split_statements(block):
        if head.startswith("message "):
            _parse_message(head.split()[1], sub or "", fq, model)
        elif head.startswith("enum "):
            model.enums[head.split()[1]] = _parse_enum(sub or "")
        elif head.startswith("oneof "):
            for oh, _os in _split_statements(sub or ""):
                m = _FIELD_RE.match(oh)
                if m:
                    fields[m.group(3)] = (
                        int(m.group(4)),
                        bool(m.group(1) and "repeated" in m.group(1)),
                        _norm_type(m.group(2)),
                    )
        elif head.startswith(("option ", "reserved ")):
            continue
        else:
            m = _FIELD_RE.match(head)
            if m:
                fields[m.group(3)] = (
                    int(m.group(4)),
                    bool(m.group(1) and "repeated" in m.group(1)),
                    _norm_type(m.group(2)),
                )
    model.messages[fq] = fields


def _parse_enum(block: str) -> dict[str, int]:
    out: dict[str, int] = {}
    for head, _sub in _split_statements(block):
        if head.startswith(("option ", "reserved ")):
            continue
        m = _ENUM_VAL_RE.match(head)
        if m:
            out[m.group(1)] = int(m.group(2))
    return out


def _parse_service(block: str) -> dict[str, tuple[str, str, bool, bool]]:
    out: dict[str, tuple[str, str, bool, bool]] = {}
    for head, sub in _split_statements(block):
        src = head if sub is None else f"{head} {{}}"
        m = _RPC_RE.match(re.sub(r"\s+", " ", src).strip())
        if m:
            out[m.group(1)] = (
                _norm_type(m.group(3)),
                _norm_type(m.group(5)),
                bool(m.group(2)),
                bool(m.group(4)),
            )
    return out


# --------------------------------------------------------------------------
# descriptor side
# --------------------------------------------------------------------------


def _is_repeated(fd) -> bool:
    rep = getattr(fd, "is_repeated", None)
    if rep is not None:  # modern spelling (label is deprecated); this is
        return bool(rep() if callable(rep) else rep)  # a property here
    return fd.label == _LABEL_REPEATED


def _field_type_name(fd) -> str:
    if fd.type == _TYPE_MESSAGE:
        mt = fd.message_type
        if mt.GetOptions().map_entry:
            return (
                f"map<{_field_type_name(mt.fields_by_name['key'])},"
                f"{_field_type_name(mt.fields_by_name['value'])}>"
            )
        return mt.name
    if fd.type == _TYPE_ENUM:
        return fd.enum_type.name
    return _SCALAR_TYPES.get(fd.type, f"type#{fd.type}")


def _walk_message(md, prefix: str, model: ProtoModel) -> None:
    fq = f"{prefix}.{md.name}" if prefix else md.name
    fields: dict[str, tuple[int, bool, str]] = {}
    for fd in md.fields:
        is_map = (
            fd.type == _TYPE_MESSAGE
            and fd.message_type.GetOptions().map_entry
        )
        fields[fd.name] = (
            fd.number,
            _is_repeated(fd) and not is_map,
            _field_type_name(fd),
        )
    model.messages[fq] = fields
    for nested in md.nested_types:
        if nested.GetOptions().map_entry:
            continue  # synthesized map entry, shown as map<> on the field
        _walk_message(nested, fq, model)
    for en in md.enum_types:
        model.enums[en.name] = {v.name: v.number for v in en.values}


def descriptor_model(pb2_module) -> ProtoModel:
    model = ProtoModel()
    fd = pb2_module.DESCRIPTOR
    model.package = fd.package
    for md in fd.message_types_by_name.values():
        _walk_message(md, "", model)
    for en in fd.enum_types_by_name.values():
        model.enums[en.name] = {v.name: v.number for v in en.values}
    for svc in fd.services_by_name.values():
        model.services[svc.name] = {
            m.name: (
                m.input_type.name,
                m.output_type.name,
                bool(m.client_streaming),
                bool(m.server_streaming),
            )
            for m in svc.methods
        }
    return model


# --------------------------------------------------------------------------
# diff + ledger
# --------------------------------------------------------------------------


def diff_models(text: ProtoModel, desc: ProtoModel) -> list[str]:
    """Human-readable drift between the .proto TEXT and the generated
    DESCRIPTOR (empty == in sync)."""
    out: list[str] = []
    if text.package != desc.package:
        out.append(
            f"package drift: text {text.package!r} vs descriptor "
            f"{desc.package!r}"
        )
    for name in sorted(set(text.messages) - set(desc.messages)):
        out.append(f"message {name}: in proto text but NOT in descriptor")
    for name in sorted(set(desc.messages) - set(text.messages)):
        out.append(f"message {name}: in descriptor but NOT in proto text")
    for name in sorted(set(text.messages) & set(desc.messages)):
        tf, df = text.messages[name], desc.messages[name]
        for f in sorted(set(tf) - set(df)):
            out.append(f"{name}.{f}: in proto text only")
        for f in sorted(set(df) - set(tf)):
            out.append(f"{name}.{f}: in descriptor only")
        for f in sorted(set(tf) & set(df)):
            tnum, trep, ttyp = tf[f]
            dnum, drep, dtyp = df[f]
            if tnum != dnum:
                out.append(
                    f"{name}.{f}: field NUMBER drift (text ={tnum}, "
                    f"descriptor ={dnum})"
                )
            if trep != drep:
                out.append(
                    f"{name}.{f}: repeated-label drift (text "
                    f"{'repeated' if trep else 'singular'}, descriptor "
                    f"{'repeated' if drep else 'singular'})"
                )
            if ttyp != dtyp:
                out.append(
                    f"{name}.{f}: type drift (text {ttyp}, descriptor "
                    f"{dtyp})"
                )
    for name in sorted(set(text.enums) ^ set(desc.enums)):
        side = "text" if name in text.enums else "descriptor"
        out.append(f"enum {name}: only in {side}")
    for name in sorted(set(text.enums) & set(desc.enums)):
        if text.enums[name] != desc.enums[name]:
            out.append(
                f"enum {name}: value drift (text {text.enums[name]} vs "
                f"descriptor {desc.enums[name]})"
            )
    for name in sorted(set(text.services) ^ set(desc.services)):
        side = "text" if name in text.services else "descriptor"
        out.append(f"service {name}: only in {side}")
    for name in sorted(set(text.services) & set(desc.services)):
        ts, ds = text.services[name], desc.services[name]
        for rpc in sorted(set(ts) ^ set(ds)):
            side = "text" if rpc in ts else "descriptor"
            out.append(f"service {name}.{rpc}: only in {side}")
        for rpc in sorted(set(ts) & set(ds)):
            if ts[rpc] != ds[rpc]:
                out.append(
                    f"service {name}.{rpc}: signature drift (text "
                    f"{ts[rpc]} vs descriptor {ds[rpc]})"
                )
    return out


def check_ledger(desc: ProtoModel, ledger: dict) -> list[str]:
    """Enforce the committed field-number ledger against the live
    descriptor: no renumber, no silent remove, no reuse of retired
    numbers, every new field appended."""
    out: list[str] = []
    pkg = ledger.get(desc.package)
    if pkg is None:
        return [f"ledger has no package section {desc.package!r}"]
    retired: dict[str, dict[str, int]] = pkg.get("__retired__", {})
    for msg, fields in sorted(desc.messages.items()):
        lfields = pkg.get(msg)
        if lfields is None:
            out.append(
                f"message {msg} missing from the field-number ledger — "
                "append it (analysis.protodrift.generate_ledger())"
            )
            continue
        for fname, (num, _rep, _typ) in sorted(fields.items()):
            lnum = lfields.get(fname)
            if lnum is None:
                out.append(
                    f"{msg}.{fname} (= {num}) not in the ledger — new "
                    "fields must be appended to proto/field_numbers.json "
                    "in the same commit"
                )
            elif int(lnum) != num:
                out.append(
                    f"{msg}.{fname}: RENUMBERED (ledger ={lnum}, "
                    f"descriptor ={num}) — field numbers are the wire "
                    "contract and may never change"
                )
            rnum = retired.get(msg, {}).get(fname)
            if rnum is not None:
                out.append(
                    f"{msg}.{fname}: name is retired in the ledger but "
                    "live in the descriptor"
                )
        for fname, lnum in sorted(lfields.items()):
            if fname in fields:
                continue
            out.append(
                f"{msg}.{fname} (= {lnum}) is in the ledger but gone "
                "from the descriptor — removed fields must move to "
                '"__retired__" (their number may never be reused)'
            )
        for fname, rnum in sorted(retired.get(msg, {}).items()):
            for live_name, (num, _r, _t) in fields.items():
                if num == int(rnum) and live_name != fname:
                    out.append(
                        f"{msg}.{live_name}: REUSES retired number "
                        f"{rnum} (was {fname}) — old peers would decode "
                        "it as the retired field"
                    )
    for msg in sorted(set(pkg) - {"__retired__"} - set(desc.messages)):
        out.append(
            f"ledger message {msg} is gone from the descriptor — move "
            'its fields to "__retired__"'
        )
    return out


def generate_ledger(pb2_modules=None) -> dict:
    """The CURRENT descriptor's ledger content (bootstrap / intentional
    update after review)."""
    out: dict = {}
    for _path, mod in _pairs(pb2_modules):
        desc = descriptor_model(mod)
        out[desc.package] = {
            msg: {f: num for f, (num, _r, _t) in sorted(fields.items())}
            for msg, fields in sorted(desc.messages.items())
        }
        out[desc.package]["__retired__"] = {}
    return out


# --------------------------------------------------------------------------
# entry points
# --------------------------------------------------------------------------


def _repo_root() -> pathlib.Path:
    return pathlib.Path(__file__).resolve().parents[2]


def ledger_path() -> pathlib.Path:
    return _repo_root() / "proto" / "field_numbers.json"


def _pairs(pb2_modules=None):
    if pb2_modules is not None:
        return pb2_modules
    from ballista_tpu.proto import ballista_tpu_pb2, etcd_pb2

    return [
        (_repo_root() / "proto" / "ballista_tpu.proto", ballista_tpu_pb2),
        (_repo_root() / "proto" / "etcd.proto", etcd_pb2),
    ]


def run(pb2_modules=None, ledger: dict | None = None) -> tuple[bool, str]:
    """Text↔descriptor diff + ledger check over every proto pair.
    Returns (ok, summary/problem report)."""
    problems: list[str] = []
    stats: list[str] = []
    if ledger is None:
        lp = ledger_path()
        if lp.exists():
            ledger = json.loads(lp.read_text())
        else:
            problems.append(
                f"missing {lp} — bootstrap with generate_ledger()"
            )
            ledger = {}
    for path, mod in _pairs(pb2_modules):
        text_model = parse_proto_text(pathlib.Path(path).read_text())
        desc_model = descriptor_model(mod)
        d = diff_models(text_model, desc_model)
        problems += [f"{pathlib.Path(path).name}: {p}" for p in d]
        problems += [
            f"{pathlib.Path(path).name}: {p}"
            for p in check_ledger(desc_model, ledger)
        ]
        stats.append(
            f"{pathlib.Path(path).name} ({len(desc_model.messages)} msgs, "
            f"{sum(len(f) for f in desc_model.messages.values())} fields)"
        )
    if problems:
        return False, "\n".join(problems)
    return True, "in sync: " + ", ".join(stats)
