"""Runtime durability witness: scheduler recovery as a checkable
invariant.

The static half (durlint + the declared state registry) proves the TREE
obeys the durability contracts; this witness proves the RUNNING SYSTEM
does — the durability analogue of the lock, resource, replay, and
staleness witnesses. When enabled, :func:`snapshot` canonicalizes the
declared state inventory of a live scheduler, and
:func:`verify_restart` diffs a RESTARTED scheduler (same sqlite/etcd
backend) against the durability classes:

- ``persisted`` fields must round-trip — with the one declared
  transform: queued/running jobs are closed out as ``failed`` by
  ``_recover_state`` (in-flight tasks died with the old scheduler).
- ``rebuilt`` fields must start empty and converge once their declared
  source replays (executors re-register → heartbeat/slot records for
  exactly the re-registered ids).
- ``ephemeral`` fields must start EMPTY — a result cache that survives
  a restart is a stale-serve bug, not a convenience.

Every comparison is recorded as a ``(field, outcome)`` check;
:func:`assert_no_divergence` is fatal on any ``divergent`` outcome and
— like the other witnesses — on a ZERO check count by default ("zero
divergence" must never silently mean "zero checks"). The two-scheduler
failover test records its watch-convergence and exactly-once-terminal
assertions through the same counters, and
:func:`terminal_history_counts` is the exactly-once probe: a job's
stamped history record must hold exactly one terminal row.

Default OFF: ``BALLISTA_DUR_WITNESS=1`` (or :func:`enable`) turns it
on. Exposed on ``/api/metrics`` as
``ballista_dur_witness_checks_total{field,outcome}``
(obs/prometheus.py) so chaos/soak runs scrape recovery state the same
way they scrape replay/staleness state."""

from __future__ import annotations

import logging
import os
import threading

from ballista_tpu.analysis import durreg

ENV_WITNESS = "BALLISTA_DUR_WITNESS"

log = logging.getLogger(__name__)

_enabled = os.environ.get(ENV_WITNESS, "") in ("1", "true", "yes")

_lock = threading.Lock()
_checks: dict[tuple[str, str], int] = {}  # (field, match|divergent) -> n
_divergences: list[dict] = []

# rebuilt entries that must be EMPTY post-restart regardless of executor
# re-registration (their source replays through new submissions, which a
# witness run does not perform between restart and verification)
_REBUILT_EMPTY = ("stage-state", "trace-index")
# rebuilt entries that must CONVERGE to exactly the re-registered ids
_REBUILT_CONVERGE = ("executor-heartbeats", "executor-slots")


def enable(flag: bool = True) -> None:
    global _enabled
    _enabled = flag


def enabled() -> bool:
    return _enabled


def record(field: str, outcome: str, detail: str = "") -> None:
    """Count one durability check; ``divergent`` outcomes carry their
    detail into the fatal report."""
    with _lock:
        k = (field, outcome)
        _checks[k] = _checks.get(k, 0) + 1
        if outcome == "divergent":
            _divergences.append({"field": field, "detail": detail})
    if outcome == "divergent":
        log.error("durability witness DIVERGENCE in %s: %s", field, detail)


def counters() -> dict[tuple[str, str], int]:
    """(field, outcome) -> count, for the prometheus family."""
    with _lock:
        return dict(_checks)


def divergences() -> list[dict]:
    with _lock:
        return [dict(d) for d in _divergences]


def summary() -> str:
    cs = counters()
    total = sum(cs.values())
    bad = sum(n for (f, o), n in cs.items() if o == "divergent")
    per = ", ".join(f"{f}:{o}={n}" for (f, o), n in sorted(cs.items()))
    return f"{total} checks ({per or 'none'}), {bad} divergent"


def assert_no_divergence(require_checks: bool = True) -> None:
    """Zero divergences (and, by default, a nonzero check count — a
    witness that saw no restart proves nothing)."""
    bad = divergences()
    if bad:
        lines = [f"{d['field']}: {d['detail']}" for d in bad]
        raise AssertionError(
            f"{len(bad)} durability divergences:\n" + "\n".join(lines)
        )
    if require_checks and not counters():
        raise AssertionError(
            "durability witness checked nothing — enable() and run "
            "verify_restart() (or record checks) before asserting"
        )


def reset() -> None:
    with _lock:
        _checks.clear()
        _divergences.clear()


# ---------------------------------------------------------------------------
# inventory snapshot (canonical, order-independent values per entry)
# ---------------------------------------------------------------------------

def snapshot(server) -> dict[str, object]:
    """Canonicalize every declared state entry of a live
    SchedulerServer. Values are hashable/comparable shapes (sorted
    tuples, counts) so two snapshots diff cleanly across processes."""
    with server._lock:
        jobs = dict(server.jobs)
        sessions = sorted(server.sessions)
        traces = sorted(server._traces)
        bypass = (
            len(server._bypass_pending),
            len(server._bypass_running),
            len(server._bypass_attempts),
        )
        obs_counts = (
            len(server.obs_task_counters),
            len(server._obs_retained),
            len(server.obs_straggler_total),
            len(server.obs_skew_total),
            len(server._recent_queue_waits),
            len(server._known_classes),
            len(server.obs_class_cost),
            len(server.obs_aqe_total),
        )
        clients = sorted(
            set(server.executor_clients)
            | set(server._executor_channels)
            | set(server._launch_failures)
        )
    em = server.executor_manager
    with em._lock:
        metadata = {
            eid: (m.host, m.port, m.grpc_port,
                  m.specification.task_slots)
            for eid, m in em._metadata.items()
        }
        heartbeats = sorted(em._heartbeats)
        slots = sorted(em._data)
        metrics = sorted(em._metrics)
    sm = server.stage_manager
    with sm._lock:
        stage_keys = sorted(sm._stages)
    return {
        "job-map": tuple(sorted(jobs)),
        "job-record": {
            jid: (j.status, j.final_stage_id,
                  tuple(sorted((k, tuple(sorted(v)))
                               for k, v in j.dependencies.items())))
            for jid, j in jobs.items()
        },
        "completed-locations": {
            jid: tuple(sorted(
                (loc.stage_id, loc.partition, loc.path)
                for loc in j.completed_locations
            ))
            for jid, j in jobs.items()
            if j.status == "completed"
        },
        "stage-plans": {
            jid: tuple(sorted(j.stages)) for jid, j in jobs.items()
        },
        "sessions": tuple(sessions),
        "executor-metadata": metadata,
        "executor-heartbeats": tuple(heartbeats),
        "executor-slots": tuple(slots),
        "executor-metrics": tuple(metrics),
        "executor-clients": tuple(clients),
        "stage-state": tuple(stage_keys),
        "trace-index": tuple(traces),
        "resolved-plan-bytes": sum(
            len(j.resolved_plan_bytes) for j in jobs.values()
        ),
        "eager-plan-bytes": sum(
            len(j.eager_plan_bytes) for j in jobs.values()
        ) + sum(1 for j in jobs.values() if j.eager),
        "result-cache-state": (
            server.result_cache.stats().get("entries", 0),
            sum(1 for j in jobs.values() if j.cache_key is not None),
            sum(1 for j in jobs.values() if j.result_ipc),
        ),
        "bypass-state": bypass + (
            sum(1 for j in jobs.values() if j.bypass),
        ),
        "job-run-counters": sum(
            j.total_retries + j.total_recomputes + j.total_rewrites
            + j.total_rewrite_rejects + len(j.rewrite_log)
            + len(j.rewritten_stages) + len(j.aqe_decisions)
            for j in jobs.values()
        ),
        "job-obs-payloads": sum(
            len(j.spans) + len(j.op_metrics) + len(j.stage_spans)
            + (1 if j.trace_id else 0)
            + (1 if j.stage_stats else 0)
            for j in jobs.values()
        ),
        "scheduler-obs-counters": obs_counts,
    }


def _is_empty(value) -> bool:
    if isinstance(value, (int, float)):
        return value == 0
    if isinstance(value, tuple) and all(
        isinstance(v, (int, float)) for v in value
    ):
        return all(v == 0 for v in value)
    return not value


def _expected_persisted(name: str, before):
    """The declared restart transform for persisted entries: in-flight
    jobs close out as failed (_recover_state), everything else
    round-trips bit-identically."""
    if name == "job-record":
        return {
            jid: ("failed" if status in ("queued", "running") else status,
                  final, deps)
            for jid, (status, final, deps) in before.items()
        }
    return before


def verify_restart(
    before: dict[str, object], server, reregistered=(),
) -> dict[str, str]:
    """Diff a restarted scheduler against a pre-restart snapshot,
    recording one check per declared entry. ``reregistered`` names the
    executor ids that re-registered between restart and verification
    (the rebuilt-class convergence source). Returns field -> outcome."""
    after = snapshot(server)
    rereg = frozenset(reregistered)
    outcomes: dict[str, str] = {}
    for e in durreg.STATE:
        b, a = before.get(e.name), after.get(e.name)
        if e.durability == "persisted":
            want = _expected_persisted(e.name, b)
            ok = a == want
            detail = f"expected {want!r}, recovered {a!r}"
        elif e.durability == "rebuilt":
            if e.name in _REBUILT_EMPTY:
                ok = _is_empty(a)
                detail = f"must start empty after restart, found {a!r}"
            elif e.name in _REBUILT_CONVERGE:
                ok = frozenset(a) == rereg
                detail = (
                    f"must converge to re-registered executors "
                    f"{sorted(rereg)}, found {a!r}"
                )
            else:
                ok = frozenset(a) <= rereg
                detail = (
                    f"rebuilt from re-registration only, but found "
                    f"{a!r} with re-registered {sorted(rereg)}"
                )
        else:  # ephemeral
            ok = _is_empty(a)
            detail = f"ephemeral state must start empty, found {a!r}"
        outcome = "match" if ok else "divergent"
        outcomes[e.name] = outcome
        record(e.name, outcome, "" if ok else f"{e.name}: {detail}")
    return outcomes


# ---------------------------------------------------------------------------
# exactly-once terminal history (the failover invariant)
# ---------------------------------------------------------------------------

def terminal_history_counts(history, job_id: str) -> dict[str, int]:
    """How many terminal history records a job holds, by kind — the
    exactly-once probe: sum(counts.values()) must be 1 for every job
    that reached a terminal state, across any number of scheduler
    restarts/failovers."""
    counts = {"completed": 0, "failed": 0}
    stamp = history._stamp_of(job_id)
    if stamp is None:
        return counts
    prefix = history._k("jobs", stamp) + "/"
    for key, _ in history.backend.get_from_prefix(prefix):
        kind = key.rsplit("/", 1)[-1]
        if kind in counts:
            counts[kind] += 1
    return counts
