"""Shared suppression-budget ledger for every AST analyzer.

planlint (jaxlint), racelint, and lifelint each grew their own
``suppressions <= 5`` rule with its own test — three places a budget
could silently be bumped analyzer-by-analyzer. This module is the single
source of truth: every analyzer's budget lives in :data:`BUDGETS`, the
combined gate (``python -m ballista_tpu.analysis``) enforces it through
:func:`check`, and ONE tier-1 test (tests/test_budget.py) walks
:func:`ledger` asserting every analyzer is within budget — growing any
budget means editing this file, in plain sight of that test.

eqlint and detlint register here from day one (both currently at zero
suppressions)."""

from __future__ import annotations

# analyzer name (as the combined gate spells it) -> max tree-wide
# ``# <tool>: disable=`` escape hatches. These are ceilings, not targets:
# the current counts are far below them and new suppressions need the
# same justification-in-a-comment discipline as always.
BUDGETS: dict[str, int] = {
    "jaxlint": 5,
    "racelint": 5,
    "lifelint": 5,
    "eqlint": 5,
    "detlint": 5,
    "stalelint": 5,
    "durlint": 5,
}


def budget_for(analyzer: str) -> int:
    return BUDGETS[analyzer]


def check(analyzer: str, used: int) -> str | None:
    """None when within budget, else the failure message the combined
    gate prints."""
    limit = BUDGETS[analyzer]
    if used > limit:
        return (
            f"suppression budget exceeded: {used} > {limit} "
            "(analysis/budget.py is the single ledger)"
        )
    return None


def ledger() -> dict[str, dict[str, int]]:
    """Live counts next to budgets for every registered analyzer — the
    payload the single budget test and ``--json`` report from."""
    from ballista_tpu.analysis import (
        detlint,
        durlint,
        eqlint,
        jaxlint,
        lifelint,
        racelint,
        stalelint,
    )

    counts = {
        "jaxlint": jaxlint.suppression_count(),
        "racelint": racelint.suppression_count(),
        "lifelint": lifelint.suppression_count(),
        "eqlint": eqlint.suppression_count(),
        "detlint": detlint.suppression_count(),
        "stalelint": stalelint.suppression_count(),
        "durlint": durlint.suppression_count(),
    }
    assert set(counts) == set(BUDGETS), (
        "budget ledger and analyzer set drifted apart"
    )
    return {
        name: {"budget": BUDGETS[name], "used": counts[name]}
        for name in sorted(BUDGETS)
    }
