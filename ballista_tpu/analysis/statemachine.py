"""Canonical task/stage/job status state machines — ONE source of truth.

PR 3 grew three status machines (task, stage-DAG membership, job record)
whose legal edges were encoded implicitly: a ``_LEGAL`` set inside
``stage_manager.py``, membership moves between the running/pending/
completed sets, and bare string assignments in ``server.py``. Any new
recovery path could add an undeclared transition that the runtime would
happily take (or silently drop) with nothing checking it.

This module declares every edge in one place, with the event that takes
it. Consumers:

- :mod:`ballista_tpu.scheduler.stage_manager` derives its legal-transition
  validator from :data:`TASK_TRANSITIONS` — code and spec cannot drift.
- :mod:`ballista_tpu.analysis.racelint` (rule ``undeclared-transition``)
  statically verifies every ``.state = TaskState.X`` assignment in the
  control plane is a declared edge, and every ``.status = "<s>"`` string
  is a declared job state.
- ``tests/test_stage_manager_properties.py`` drives randomized
  retry/recovery/promote sequences and asserts every observed hop is an
  edge of these tables.

Edges are ``(from, to) -> event description``. States are the enum VALUE
strings (``"pending"``, not ``"PENDING"``) so runtime checks need no
mapping layer.
"""

from __future__ import annotations

# -- task status (ref stage_manager.rs:536-586) -------------------------------
TASK_STATES = ("pending", "running", "failed", "completed")

TASK_TRANSITIONS: dict[tuple[str, str], str] = {
    ("pending", "running"): "scheduled onto an executor",
    ("running", "completed"): "executor reported success",
    ("running", "failed"): "executor reported failure",
    ("running", "pending"): "executor lost — reset for re-handout",
    ("failed", "pending"): "bounded retry requeue (attempts < cap)",
    ("completed", "pending"): "lost-shuffle re-open (output invalidated)",
}

# -- stage DAG membership (running/pending/completed sets) --------------------
STAGE_STATES = ("pending", "running", "completed")

STAGE_TRANSITIONS: dict[tuple[str, str], str] = {
    ("pending", "running"): "promote — every dependency completed",
    ("running", "pending"): "demote — a dependency's output was invalidated",
    ("running", "completed"): "every task completed",
    ("completed", "running"): "lost-shuffle rollback — output re-opened",
}

# -- job record (server.py JobInfo.status) ------------------------------------
JOB_STATES = ("queued", "running", "failed", "completed")

JOB_TRANSITIONS: dict[tuple[str, str], str] = {
    ("queued", "running"): "stages generated and submitted",
    ("queued", "failed"): "planning/stage-submission failed",
    ("running", "completed"): "final stage finished",
    ("running", "failed"): "task attempts / recompute bound exhausted",
}


def is_legal_task_transition(src: str, dst: str) -> bool:
    return (src, dst) in TASK_TRANSITIONS


def is_legal_stage_transition(src: str, dst: str) -> bool:
    return (src, dst) in STAGE_TRANSITIONS


def is_legal_job_transition(src: str, dst: str) -> bool:
    return (src, dst) in JOB_TRANSITIONS


def render_tables() -> str:
    """Human-readable dump (the ``python -m ballista_tpu.analysis``
    ``--tables`` output and the docs/analysis.md catalog source)."""
    out = []
    for title, table in (
        ("task", TASK_TRANSITIONS),
        ("stage", STAGE_TRANSITIONS),
        ("job", JOB_TRANSITIONS),
    ):
        out.append(f"{title} transitions:")
        for (src, dst), why in table.items():
            out.append(f"  {src:>9} -> {dst:<9}  {why}")
    return "\n".join(out)
