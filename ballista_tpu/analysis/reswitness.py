"""Runtime resource witness (the dynamic half of ``lifelint``).

lifelint proves statically that every *syntactic* acquisition has a
provable owner; this module checks what actually happens at runtime: the
tracked acquisition sites (gRPC channels, pooled Flight clients, thread
pools, spill managers, shuffle-fetch queues, served/mapped shuffle
files) register on acquire and deregister on release, and a clean
shutdown must leave **zero live tracked resources** — the resource
analogue of the PR 4 lock-order witness and the zero-thread-leak audit.

Default OFF: every instrumentation point is a single module-flag check
(``BALLISTA_RESOURCE_WITNESS=1`` in the environment, or :func:`enable`
before the resources are created). When on, each acquisition records
kind, name, owning thread, and the creation stack (trimmed), so a leak
report names the exact dial/open site instead of "something leaked".

Intended use (tests/test_shutdown_hygiene.py, tests/test_reswitness_chaos.py):

    reswitness.enable()
    ... start cluster, run queries, kill executors, stop cluster ...
    reswitness.assert_drained()   # names every still-live resource

Ownership-transfer notes: a pooled Flight client EVICTED after a
transport error is deliberately handed to GC (other threads may be
mid-stream on it — closing would break them), so eviction releases its
witness entry; the eviction is the ownership decision being witnessed.
"""

from __future__ import annotations

import itertools
import os
import threading
import traceback

ENV_WITNESS = "BALLISTA_RESOURCE_WITNESS"

_enabled = os.environ.get(ENV_WITNESS, "") in ("1", "true", "yes")

_lock = threading.Lock()
_live: dict[int, dict] = {}
_token = itertools.count(1)
# lifetime acquire counts per kind (diagnostics: proves the witness saw
# traffic, so "zero live" cannot silently mean "zero tracked")
_acquired: dict[str, int] = {}


def enable(flag: bool = True) -> None:
    """Turn the witness on/off for acquisitions AFTER this call."""
    global _enabled
    _enabled = flag


def enabled() -> bool:
    return _enabled


def acquire(kind: str, name: str):
    """Register a live resource; returns an opaque token to pass to
    :func:`release` (None when the witness is off — release tolerates
    it, so call sites stay one-liners)."""
    if not _enabled:
        return None
    tok = next(_token)
    entry = {
        "kind": kind,
        "name": name,
        "thread": threading.current_thread().name,
        # drop the acquire()/instrumentation frames, keep the caller's
        "stack": "".join(traceback.format_stack(limit=8)[:-1]),
    }
    with _lock:
        _live[tok] = entry
        _acquired[kind] = _acquired.get(kind, 0) + 1
    return tok


def release(token) -> None:
    """Deregister; tolerates None tokens and double-release (a close()
    called twice must not crash the witness)."""
    if token is None:
        return
    with _lock:
        _live.pop(token, None)


def live() -> list[dict]:
    with _lock:
        return [dict(v) for v in _live.values()]


def acquired_counts() -> dict[str, int]:
    with _lock:
        return dict(_acquired)


def summary() -> str:
    entries = live()
    counts = acquired_counts()
    if not entries:
        return (
            "0 live tracked resources ("
            + ", ".join(f"{k}:{n}" for k, n in sorted(counts.items()))
            + " acquired over lifetime)"
        )
    lines = [f"{len(entries)} LIVE tracked resources:"]
    for e in entries:
        lines.append(f"  {e['kind']} {e['name']} (thread {e['thread']})")
    return "\n".join(lines)


def assert_drained() -> None:
    """Zero live tracked resources, or an AssertionError naming each
    leak with its creation stack."""
    entries = live()
    if not entries:
        return
    lines = []
    for e in entries:
        lines.append(
            f"{e['kind']} {e['name']} acquired on thread "
            f"{e['thread']}:\n{e['stack']}"
        )
    raise AssertionError(
        f"{len(entries)} tracked resources still live at shutdown:\n"
        + "\n".join(lines)
    )


def reset() -> None:
    with _lock:
        _live.clear()
        _acquired.clear()
