"""On-pod (ICI) parallel tier: mesh-aware shuffle exchange + stage programs.

This package is the TPU-native replacement for the reference's network
shuffle hot path (ballista/rust/core/src/execution_plans/
shuffle_writer.rs:201-285 writing IPC files, shuffle_reader.rs:102-130
fetching them over Flight): inside one pod the exchange is a
``jax.lax.all_to_all`` over the ICI mesh inside a single jitted
``shard_map`` program — no files, no Flight, no host round-trip.

Layout:
- ``mesh``: device mesh construction + host<->mesh batch movement
- ``collective``: traceable bucket + all_to_all exchange kernels (must be
  called inside ``shard_map``)
- ``stage``: compiled mesh stage programs (repartitioned aggregate,
  partitioned join) — the on-pod analogues of the reference's
  hash-RepartitionExec stage boundaries (scheduler/src/planner.rs:133-157)
"""

from ballista_tpu.parallel.mesh import (  # noqa: F401
    SHARD_AXIS,
    is_row_sharded,
    make_mesh,
    shard_batch,
    unshard_batch,
)
from ballista_tpu.parallel.stage import MeshStageRunner  # noqa: F401
