"""Mesh stage programs: whole distributed stages as ONE jitted shard_map.

The reference executes a repartitioned aggregate / partitioned join as
three processes' worth of machinery — upstream tasks hash-partition to IPC
files (shuffle_writer.rs:142-292), the scheduler promotes the next stage
(query_stage_scheduler.rs:181-309), downstream tasks fetch over Flight
(shuffle_reader.rs:102-130). On-pod, the whole pipeline compiles into one
XLA program per mesh: local partial -> ``all_to_all`` over ICI -> local
final, with no host round-trip between stages.

Capacity/overflow discipline: every shape is static; bucket, group and
expansion overflows come back as SEPARATE per-device flags, checked
host-side after the step. Retryable overflows (bucket capacity, group
capacity, join-expansion output capacity) are retried here with grown
capacities — the mesh runner holds the inputs, so a retry is just a
re-dispatch of a differently-sized cached program. Non-retryable
conditions (hash-collision runs past the probe window) raise.

Join tier parity with the local kernels (ops/join.py): all three packing
modes (exact single-int key, exact2 two-int pack, hashed multi-key with
window-verified probes), m:n expansion joins for duplicate build keys, and
INNER-join residual filters — so q5/q18-class join shapes run PARTITIONED
on the mesh.
"""

from __future__ import annotations


import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

# Collective programs from CONCURRENT host threads (a multi-slot executor
# running two mesh stage-tasks at once) can interleave their per-device
# executions — device 0 enters program A's all_to_all while device 1 is in
# program B's, and the rendezvous deadlocks (observed on the virtual CPU
# mesh: "Expected 8 threads to join... not all arrived"). One program's
# collectives must fully complete before another dispatches, so every
# runner method holds this process-global lock through dispatch AND a
# completion barrier.
_COLLECTIVE_LOCK = threading.Lock()

from ballista_tpu.columnar.batch import DeviceBatch, round_capacity
from ballista_tpu.datatypes import DataType, Field, Schema
from ballista_tpu.errors import CapacityError, ExecutionError
from ballista_tpu.ops.aggregate import AggOp, group_aggregate
from ballista_tpu.ops.join import (
    JoinSide,
    _build_finish,
    _choose_pack_mode,
    _pack_key,
    expand_join,
    probe_counts,
)
from ballista_tpu.ops.perm import multi_key_perm
from ballista_tpu.parallel.collective import (
    all_to_all_rows,
    bucket_rows_by_pid,
    exchange_by_key,
)
from ballista_tpu.parallel.mesh import SHARD_AXIS

MAX_MESH_RETRIES = 6


def _sum_dtype_np(dtype: DataType) -> DataType:
    if dtype in (DataType.BOOL,) or dtype.is_integer:
        return DataType.INT64
    return DataType.FLOAT64


class MeshStageRunner:
    """Compiles and runs mesh-wide stage programs over a 1-D device mesh.

    Inputs are mesh-sharded batches (see ``parallel.mesh.shard_batch``);
    outputs stay sharded — each device holds the rows whose hash routes to
    it, exactly the invariant a downstream mesh stage needs.
    """

    def __init__(self, mesh, axis: str = SHARD_AXIS) -> None:
        self.mesh = mesh
        self.axis = axis
        self.n_dev = int(mesh.devices.size)
        self._programs: dict = {}

    # -- helpers -------------------------------------------------------------
    def _leaf_specs(self, tree):
        return jax.tree_util.tree_map(lambda _: P(self.axis), tree)

    # -- repartitioned aggregate ---------------------------------------------
    def aggregate(
        self,
        batch: DeviceBatch,
        key_idxs: list[int],
        val_idxs: list[int],
        ops: list[AggOp],
        capacity: int,
        bucket_cap: int | None = None,
    ) -> DeviceBatch:
        """Partial agg per device -> all_to_all exchange of group states by
        key hash -> final merge agg per device. Output: sharded batch of
        (keys ++ aggregated values); each group lives on exactly one device.

        Group-capacity overflow is retried with the exact required capacity
        (the kernel computes the true group count even on overflow)."""
        for attempt in range(MAX_MESH_RETRIES):
            # states per device never exceed `capacity`, so a bucket of
            # `capacity` slots can always hold one device's worth
            bcap = bucket_cap or capacity
            prog = self._aggregate_program(
                batch, tuple(key_idxs), tuple(val_idxs), tuple(ops),
                capacity, bcap,
            )
            with _COLLECTIVE_LOCK:
                out_cols, out_nulls, out_valid, grp_ovf, need = prog(
                    batch.columns, batch.nulls, batch.valid
                )
                from ballista_tpu.ops.fetch import fetch_arrays

                # the fetch doubles as the completion barrier the lock needs
                grp_ovf, need = fetch_arrays([grp_ovf, need])
                jax.block_until_ready(out_valid)
            if not np.any(grp_ovf):
                break
            required = int(np.max(need))
            new_cap = round_capacity(required + 1)
            if new_cap <= capacity:
                new_cap = capacity * 2
            if attempt == MAX_MESH_RETRIES - 1:
                raise CapacityError(
                    "mesh aggregate exceeded group capacity after retries",
                    required=required,
                )
            capacity = new_cap
        in_schema = batch.schema
        fields = [in_schema.fields[i] for i in key_idxs]
        dicts = {
            k: v
            for k, v in batch.dictionaries.items()
            if any(in_schema.fields[i].name == k for i in key_idxs)
        }
        for i, op in zip(val_idxs, ops):
            f = in_schema.fields[i]
            if op == AggOp.COUNT:
                fields.append(Field(f"{f.name}#count", DataType.INT64, False))
            elif op == AggOp.SUM:
                fields.append(
                    Field(f"{f.name}#sum", _sum_dtype_np(f.dtype), True)
                )
            else:
                out_name = f"{f.name}#{op.value}"
                fields.append(Field(out_name, f.dtype, True))
                if f.dtype == DataType.STRING:
                    # MIN/MAX over a dictionary-coded column: the codes ride
                    # through; the dictionary follows under the renamed field
                    d = batch.dictionaries.get(f.name)
                    if d is not None:
                        dicts[out_name] = d
        return DeviceBatch(
            schema=Schema(fields),
            columns=tuple(out_cols),
            valid=out_valid,
            nulls=tuple(out_nulls),
            dictionaries=dicts,
        )

    def _aggregate_program(
        self, batch, key_idxs, val_idxs, ops, capacity, bucket_cap
    ):
        key = (
            "agg",
            str(batch.schema),
            batch.capacity,
            key_idxs,
            val_idxs,
            ops,
            capacity,
            bucket_cap,
            tuple(m is None for m in batch.nulls),
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile_aggregate(
                batch, key_idxs, val_idxs, ops, capacity, bucket_cap
            )
            self._programs[key] = prog
        return prog

    def _compile_aggregate(
        self, batch, key_idxs, val_idxs, ops, capacity, bucket_cap
    ):
        axis, n_dev = self.axis, self.n_dev
        merge_ops = tuple(op.merge_op for op in ops)
        n_keys = len(key_idxs)

        def f(cols, nulls, valid):
            key_cols = [cols[i] for i in key_idxs]
            key_nulls = [nulls[i] for i in key_idxs]
            val_cols = [cols[i] for i in val_idxs]
            val_nulls = [nulls[i] for i in val_idxs]
            part = group_aggregate(
                key_cols, key_nulls, valid, val_cols, val_nulls,
                list(ops), capacity,
            )
            st_cols = tuple(part.keys) + tuple(part.values)
            st_nulls = tuple(part.key_nulls) + tuple(part.value_nulls)
            ex_cols, ex_nulls, ex_valid, b_ovf = exchange_by_key(
                st_cols, st_nulls, part.valid,
                tuple(range(n_keys)), axis, n_dev, bucket_cap,
            )
            fin = group_aggregate(
                list(ex_cols[:n_keys]),
                list(ex_nulls[:n_keys]),
                ex_valid,
                list(ex_cols[n_keys:]),
                list(ex_nulls[n_keys:]),
                list(merge_ops),
                capacity,
            )
            # bucket_cap == capacity makes bucket overflow impossible, but
            # keep the flag folded in as a backstop for explicit bucket_cap
            grp_ovf = (part.overflow | b_ovf | fin.overflow).reshape(1)
            need = jnp.maximum(
                part.n_groups.astype(jnp.int32), fin.n_groups.astype(jnp.int32)
            ).reshape(1)
            out_cols = tuple(fin.keys) + tuple(fin.values)
            # concrete (possibly all-false) masks so the output pytree has a
            # static structure for out_specs
            out_nulls = tuple(
                jnp.zeros(c.shape[0], dtype=bool) if m is None else m
                for c, m in zip(
                    out_cols, tuple(fin.key_nulls) + tuple(fin.value_nulls)
                )
            )
            return out_cols, out_nulls, fin.valid, grp_ovf, need

        in_specs = (
            self._leaf_specs(batch.columns),
            self._leaf_specs(batch.nulls),
            P(axis),
        )
        # outputs: all row-sharded (flags: one scalar per device)
        out_specs = (
            tuple(P(axis) for _ in range(n_keys + len(val_idxs))),
            tuple(P(axis) for _ in range(n_keys + len(val_idxs))),
            P(axis),
            P(axis),
            P(axis),
        )
        sm = shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(sm)

    # -- distributed TopK -----------------------------------------------------
    def topk(self, batch: DeviceBatch, keys, k: int) -> DeviceBatch:
        """ORDER BY ... LIMIT k as one mesh program: local sort + top-k on
        each shard, ``all_gather`` of the k-row candidates over ICI, final
        merge sort of the k*n_dev pool — every device computes the same
        replicated answer (SPMD), so the output is a single logical
        partition with no host hop. The shard-local top-k bounds the
        gather to k*n_dev rows regardless of input size (the mesh
        analogue of SortExec's fetch-sliced permutation)."""
        key_sig = tuple(
            (kk.col, kk.ascending, kk.nulls_first) for kk in keys
        )
        prog = self._topk_program(batch, key_sig, k)
        with _COLLECTIVE_LOCK:
            out_cols, out_nulls, out_valid = prog(
                batch.columns, batch.nulls, batch.valid
            )
            jax.block_until_ready(out_valid)
        return DeviceBatch(
            schema=batch.schema,
            columns=tuple(out_cols),
            valid=out_valid,
            nulls=tuple(out_nulls),
            dictionaries=dict(batch.dictionaries),
        )

    def _topk_program(self, batch, key_sig, k):
        key = (
            "topk", str(batch.schema), batch.capacity, key_sig, k,
            tuple(m is None for m in batch.nulls),
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile_topk(batch, key_sig, k)
            self._programs[key] = prog
        return prog

    def _compile_topk(self, batch, key_sig, k):
        from ballista_tpu.ops.perm import take_batch
        from ballista_tpu.ops.sort import SortKey, sort_passes

        axis = self.axis
        keys = [
            SortKey(col=c, ascending=a, nulls_first=nf)
            for c, a, nf in key_sig
        ]

        def local_topk(cols, nulls, valid, kk):
            # same pass construction as single-device sort_perm — shared
            # so mesh TopK order cannot drift from SortExec order
            perm = multi_key_perm(sort_passes(cols, nulls, valid, keys))[:kk]
            return take_batch(list(cols), list(nulls), valid, perm)

        def f(cols, nulls, valid):
            shard_k = min(k, cols[0].shape[0])
            tcols, tnulls, tvalid = local_topk(cols, nulls, valid, shard_k)

            def ag(x):
                return jax.lax.all_gather(x, axis, tiled=True)

            gcols = tuple(ag(c) for c in tcols)
            gnulls = tuple(None if m is None else ag(m) for m in tnulls)
            gvalid = ag(tvalid)
            fk = min(k, gcols[0].shape[0])
            ocols, onulls, ovalid = local_topk(gcols, gnulls, gvalid, fk)
            out_nulls = tuple(
                jnp.zeros(c.shape[0], dtype=bool) if m is None else m
                for c, m in zip(ocols, onulls)
            )
            return tuple(ocols), out_nulls, ovalid

        in_specs = (
            self._leaf_specs(batch.columns),
            self._leaf_specs(batch.nulls),
            P(axis),
        )
        n = len(batch.columns)
        # replicated outputs: every device computed the identical answer
        out_specs = (
            tuple(P() for _ in range(n)),
            tuple(P() for _ in range(n)),
            P(),
        )
        sm = shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(sm)

    # -- full sort (sample sort / range exchange) -----------------------------

    SORT_SAMPLES = 64  # splitter samples per device

    def sort_full(self, batch: DeviceBatch, keys) -> DeviceBatch:
        """Total ORDER BY (no LIMIT) over the mesh: sample split points on
        the primary key -> range all_to_all exchange -> local multi-key
        sort per shard. Device d ends up holding the d-th key range,
        locally sorted, so the sharded batch read in index order IS the
        total order (ties on the primary key route to one device and are
        broken there by the remaining keys). The reference serializes this
        shape through a single post-gather sort task (planner.rs:104-132);
        the mesh version never funnels.

        Skew (few distinct primary keys) shows up as bucket overflow and
        retries with grown bucket capacity up to the skew-proof bound
        (per-shard rows, where overflow is impossible)."""
        key_sig = tuple(
            (kk.col, kk.ascending, kk.nulls_first) for kk in keys
        )
        per = max(1, batch.capacity // self.n_dev)
        bcap = round_capacity(max(1, (2 * per) // self.n_dev))
        for attempt in range(MAX_MESH_RETRIES):
            bcap = min(bcap, round_capacity(per))
            prog = self._sort_full_program(batch, key_sig, bcap)
            with _COLLECTIVE_LOCK:
                out_cols, out_nulls, out_valid, ovf = prog(
                    batch.columns, batch.nulls, batch.valid
                )
                from ballista_tpu.ops.fetch import fetch_arrays

                (ovf_h,) = fetch_arrays([ovf])
                jax.block_until_ready(out_valid)
            if not np.any(ovf_h):
                break
            if bcap >= per or attempt == MAX_MESH_RETRIES - 1:
                raise CapacityError(
                    "mesh sort bucket overflow after retries",
                    required=per * self.n_dev,
                )
            bcap = round_capacity(bcap * 2)  # stay on the bucket ladder
        return DeviceBatch(
            schema=batch.schema,
            columns=tuple(out_cols),
            valid=out_valid,
            nulls=tuple(out_nulls),
            dictionaries=dict(batch.dictionaries),
        )

    def _sort_full_program(self, batch, key_sig, bcap):
        key = (
            "sortf", str(batch.schema), batch.capacity, key_sig, bcap,
            tuple(m is None for m in batch.nulls),
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile_sort_full(batch, key_sig, bcap)
            self._programs[key] = prog
        return prog

    def _compile_sort_full(self, batch, key_sig, bcap):
        from ballista_tpu.ops.perm import take_batch
        from ballista_tpu.ops.sort import SortKey, sort_passes

        axis, n_dev = self.axis, self.n_dev
        keys = [
            SortKey(col=c, ascending=a, nulls_first=nf)
            for c, a, nf in key_sig
        ]
        k0 = keys[0]
        S = self.SORT_SAMPLES

        def routing_key(cols, nulls):
            """Primary sort key as a widened scalar whose ASCENDING order
            equals the key's sort order: DESC flips sign, null-masked rows
            pin to the end the key's null placement dictates."""
            r = cols[k0.col]
            nm = nulls[k0.col]
            if jnp.issubdtype(r.dtype, jnp.floating):
                r = r.astype(jnp.float64)
                hi = jnp.array(jnp.inf, r.dtype)
                # raw NaNs (not null-masked) sort last like jnp.sort
                r = jnp.where(jnp.isnan(r), hi, r)
            elif r.dtype == jnp.dtype(bool):
                r = r.astype(jnp.int64)
                hi = jnp.array(jnp.iinfo(jnp.int64).max, r.dtype)
            else:
                r = r.astype(jnp.int64)
                hi = jnp.array(jnp.iinfo(jnp.int64).max, r.dtype)
            lo = -hi
            if not k0.ascending:
                r = -r
            if nm is not None:
                r = jnp.where(nm, lo if k0.nulls_first else hi, r)
            return r, hi

        def f(cols, nulls, valid):
            per = valid.shape[0]
            r, hi = routing_key(cols, nulls)
            # dead rows route nowhere; use the sentinel so local sorted
            # samples see only live keys in the prefix
            r_live = jnp.where(valid, r, hi)
            rs = jnp.sort(r_live)
            nlive = jnp.sum(valid).astype(jnp.int32)
            pos = jnp.clip(
                (jnp.arange(S, dtype=jnp.int32) * nlive) // S, 0, per - 1
            )
            samp = jnp.where(nlive > 0, rs[pos], hi)
            gs = jnp.sort(jax.lax.all_gather(samp, axis, tiled=True))
            tot = S * n_dev
            spl_pos = (
                jnp.arange(1, n_dev, dtype=jnp.int32) * tot
            ) // n_dev
            splitters = gs[spl_pos]
            pid = jnp.searchsorted(splitters, r_live, side="left").astype(
                jnp.int32
            )
            pid = jnp.where(valid, pid, n_dev)
            bcols, bnulls, bvalid, ovf = bucket_rows_by_pid(
                cols, nulls, valid, pid, n_dev, bcap
            )
            ecols, enulls, evalid = all_to_all_rows(
                bcols, bnulls, bvalid, axis, n_dev, bcap
            )
            perm = multi_key_perm(
                sort_passes(list(ecols), list(enulls), evalid, keys)
            )
            ocols, onulls, ovalid = take_batch(
                list(ecols), list(enulls), evalid, perm
            )
            out_nulls = tuple(
                jnp.zeros(c.shape[0], dtype=bool) if m is None else m
                for c, m in zip(ocols, onulls)
            )
            return tuple(ocols), out_nulls, ovalid, ovf.reshape(1)

        in_specs = (
            self._leaf_specs(batch.columns),
            self._leaf_specs(batch.nulls),
            P(axis),
        )
        n = len(batch.columns)
        out_specs = (
            tuple(P(axis) for _ in range(n)),
            tuple(P(axis) for _ in range(n)),
            P(axis),
            P(axis),
        )
        sm = shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(sm)

    # -- partition-keyed windows ----------------------------------------------

    def window(self, batch: DeviceBatch, key_idxs: list[int], local_fn,
               n_out: int, fn_key=None):
        """Partition-keyed window functions over the mesh: hash-exchange
        rows by PARTITION BY key so each partition lands whole on one
        device, then run ``local_fn`` — the single-device window program —
        per shard inside the same compiled program. The reference punts on
        distributed windows entirely (planner.rs:163-169 funnels through a
        coalesce); this keeps K-way parallelism.

        ``local_fn(cols, nulls, valid) -> (out_cols, out_nulls)`` must be
        traceable and return the INPUT columns plus ``n_out`` appended
        window columns (null mask per appended column or None)."""
        per = max(1, batch.capacity // self.n_dev)
        bcap = round_capacity(max(1, (2 * per) // self.n_dev))
        for attempt in range(MAX_MESH_RETRIES):
            bcap = min(bcap, round_capacity(per))
            prog = self._window_program(
                batch, tuple(key_idxs), local_fn, n_out, bcap, fn_key
            )
            with _COLLECTIVE_LOCK:
                out_cols, out_nulls, out_valid, ovf = prog(
                    batch.columns, batch.nulls, batch.valid
                )
                from ballista_tpu.ops.fetch import fetch_arrays

                (ovf_h,) = fetch_arrays([ovf])
                jax.block_until_ready(out_valid)
            if not np.any(ovf_h):
                break
            if bcap >= per or attempt == MAX_MESH_RETRIES - 1:
                raise CapacityError(
                    "mesh window bucket overflow after retries",
                    required=per * self.n_dev,
                )
            bcap *= 2
        return out_cols, out_nulls, out_valid

    def _window_program(self, batch, key_idxs, local_fn, n_out, bcap,
                        fn_key=None):
        key = (
            "window", str(batch.schema), batch.capacity, key_idxs,
            fn_key if fn_key is not None else id(local_fn), n_out, bcap,
            tuple(m is None for m in batch.nulls),
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile_window(
                batch, key_idxs, local_fn, n_out, bcap
            )
            self._programs[key] = prog
        return prog

    def _compile_window(self, batch, key_idxs, local_fn, n_out, bcap):
        axis, n_dev = self.axis, self.n_dev

        def f(cols, nulls, valid):
            ecols, enulls, evalid, ovf = exchange_by_key(
                cols, nulls, valid, key_idxs, axis, n_dev, bcap
            )
            out_cols, out_nulls = local_fn(
                list(ecols), list(enulls), evalid
            )
            out_nulls = tuple(
                jnp.zeros(c.shape[0], dtype=bool) if m is None else m
                for c, m in zip(out_cols, out_nulls)
            )
            return tuple(out_cols), out_nulls, evalid, ovf.reshape(1)

        in_specs = (
            self._leaf_specs(batch.columns),
            self._leaf_specs(batch.nulls),
            P(axis),
        )
        n = len(batch.columns) + n_out
        out_specs = (
            tuple(P(axis) for _ in range(n)),
            tuple(P(axis) for _ in range(n)),
            P(axis),
            P(axis),
        )
        sm = shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(sm)

    # -- partitioned join -----------------------------------------------------
    def join(
        self,
        left: DeviceBatch,
        right: DeviceBatch,
        left_keys: list[int],
        right_keys: list[int],
        join_type: JoinSide = JoinSide.INNER,
        bucket_cap: int | None = None,
        filter_fn=None,
        out_cap: int | None = None,
    ) -> DeviceBatch:
        """PARTITIONED-mode join (ref HashJoinExecNode PartitionMode
        PARTITIONED, ballista.proto:474-487): exchange BOTH sides by join
        key over ICI, then build+probe locally per device.

        Key packing follows the local tier (ops/join.py): exact single-int,
        exact2 two-int, or hashed with window-verified probes. Duplicate
        build keys run the m:n expansion path; the expansion output
        capacity and the exchange bucket capacity grow on overflow and the
        program re-dispatches (the inputs are already on device).

        ``filter_fn``: optional traceable residual filter
        ``f(joined_batch) -> bool[rows]`` applied inside the program
        (INNER joins only — the caller enforces that restriction).
        """
        # String keys join by dictionary code. The compiled program bakes no
        # dictionary knowledge, so the shared-dictionary contract must be
        # re-validated on EVERY call (a program-cache hit would otherwise
        # skip the trace-time check and join mismatched codes).
        for li, ri in zip(left_keys, right_keys):
            lf = left.schema.fields[li]
            rf = right.schema.fields[ri]
            if DataType.STRING in (lf.dtype, rf.dtype):
                ld = left.dictionaries.get(lf.name)
                rd = right.dictionaries.get(rf.name)
                if ld is None or rd is None or ld.values != rd.values:
                    raise ExecutionError(
                        f"mesh join key {lf.name!r}/{rf.name!r} requires a "
                        "shared dictionary; unify dictionaries before "
                        "sharding"
                    )
        # pack mode decided host-side on the build (right) batch — static
        # for the compiled program; probe packs with the same mode
        mode = _choose_pack_mode(right, list(right_keys))
        bcap = bucket_cap or max(
            left.capacity // self.n_dev, right.capacity // self.n_dev, 1
        )
        # post-exchange local probe length is n_dev * bucket_cap; a unique
        # build emits at most one row per probe row
        ocap = out_cap or self.n_dev * bcap

        for attempt in range(MAX_MESH_RETRIES):
            prog = self._join_program(
                left, right, tuple(left_keys), tuple(right_keys),
                join_type, bcap, mode, ocap, filter_fn,
            )
            with _COLLECTIVE_LOCK:
                cols, nulls, valid, bucket_ovf, run_ovf, exp_ovf, totals = (
                    prog(
                        left.columns, left.nulls, left.valid,
                        right.columns, right.nulls, right.valid,
                    )
                )
                from ballista_tpu.ops.fetch import fetch_arrays

                # fetch doubles as the completion barrier the lock needs
                bucket_ovf, run_ovf, exp_ovf, totals = fetch_arrays(
                    [bucket_ovf, run_ovf, exp_ovf, totals]
                )
                jax.block_until_ready(valid)
            if np.any(run_ovf):
                raise ExecutionError(
                    "mesh join build side has a packed-hash collision run "
                    "longer than the probe window; use integer join keys "
                    "or reduce build size"
                )
            if np.any(bucket_ovf):
                # grown capacities snap to the bucket ladder (like the
                # exec/base.py retry path) so mesh retries land on shared
                # compiled-program signatures under non-pow2 ladders too
                bcap = round_capacity(bcap * 2)
                ocap = max(ocap, round_capacity(self.n_dev * bcap))
                continue
            if np.any(exp_ovf):
                required = int(np.max(totals))
                ocap = round_capacity(max(required + 1, ocap * 2))
                continue
            break
        else:
            raise CapacityError(
                "mesh join exceeded static capacities after retries",
                required=int(np.max(totals)),
            )
        if join_type in (JoinSide.SEMI, JoinSide.ANTI):
            out_schema = left.schema
        elif join_type == JoinSide.LEFT:
            out_schema = left.schema.join(
                Schema([Field(f.name, f.dtype, True) for f in right.schema])
            )
        else:
            out_schema = left.schema.join(right.schema)
        dicts = dict(left.dictionaries)
        if join_type not in (JoinSide.SEMI, JoinSide.ANTI):
            dicts.update(right.dictionaries)
        return DeviceBatch(
            schema=out_schema,
            columns=tuple(cols),
            valid=valid,
            nulls=tuple(nulls),
            dictionaries=dicts,
        )

    def _join_program(
        self, left, right, left_keys, right_keys, join_type, bucket_cap,
        mode, out_cap, filter_fn,
    ):
        key = (
            "join",
            str(left.schema), left.capacity,
            str(right.schema), right.capacity,
            left_keys, right_keys, join_type, bucket_cap, mode, out_cap,
            id(filter_fn) if filter_fn is not None else None,
            tuple(m is None for m in left.nulls),
            tuple(m is None for m in right.nulls),
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile_join(
                left, right, left_keys, right_keys, join_type, bucket_cap,
                mode, out_cap, filter_fn,
            )
            self._programs[key] = prog
        return prog

    def _compile_join(
        self, left, right, left_keys, right_keys, join_type, bucket_cap,
        mode, out_cap, filter_fn,
    ):
        axis, n_dev = self.axis, self.n_dev
        l_schema, r_schema = left.schema, right.schema
        l_dicts = dict(left.dictionaries)
        r_dicts = dict(right.dictionaries)
        semi_anti = join_type in (JoinSide.SEMI, JoinSide.ANTI)

        def f(lcols, lnulls, lvalid, rcols, rnulls, rvalid):
            lc, ln, lv, l_ovf = exchange_by_key(
                lcols, lnulls, lvalid, left_keys, axis, n_dev, bucket_cap
            )
            rc, rn, rv, r_ovf = exchange_by_key(
                rcols, rnulls, rvalid, right_keys, axis, n_dev, bucket_cap
            )
            # build the right side locally under the static pack mode
            dead = ~rv
            for i in right_keys:
                if rn[i] is not None:
                    dead = dead | rn[i]
            packed = _pack_key([rc[i] for i in right_keys], mode)
            passes = [(dead, False), (packed, False)]
            if mode == "hash":
                # tie-break on actual keys: duplicate keys land adjacent
                passes.extend((rc[i], False) for i in right_keys)
            perm = multi_key_perm(passes)
            rbatch = DeviceBatch(
                schema=r_schema,
                columns=rc,
                valid=rv,
                nulls=rn,
                dictionaries=r_dicts,
            )
            bt = _build_finish(
                perm, dead, packed, rbatch, right_keys, mode
            )
            lbatch = DeviceBatch(
                schema=l_schema,
                columns=lc,
                valid=lv,
                nulls=ln,
                dictionaries=l_dicts,
            )
            first, count, live = probe_counts(bt, lbatch, list(left_keys))
            bucket_ovf = (l_ovf | r_ovf).reshape(1)
            run_ovf = bt.run_overflow.reshape(1)
            if semi_anti:
                m = count > 0
                keep = m if join_type == JoinSide.SEMI else ~m
                out = lbatch.with_valid(lbatch.valid & keep)
                zero = jnp.zeros(1, dtype=jnp.int32)
                out_nulls = tuple(
                    jnp.zeros(c.shape[0], dtype=bool) if nm is None else nm
                    for c, nm in zip(out.columns, out.nulls)
                )
                return (
                    out.columns, out_nulls, out.valid,
                    bucket_ovf, run_ovf,
                    jnp.zeros(1, dtype=bool), zero,
                )
            if join_type == JoinSide.LEFT:
                eff = jnp.where(lbatch.valid, jnp.maximum(count, 1), 0)
                ekind = JoinSide.LEFT
            else:
                eff = count
                ekind = JoinSide.INNER
            total = jnp.sum(eff).astype(jnp.int32).reshape(1)
            exp_ovf = (total > out_cap).reshape(1)
            batch, i, k, real = expand_join(
                bt, lbatch, first, count, eff, out_cap, ekind
            )
            if filter_fn is not None:
                passes_f = filter_fn(batch) & real
                batch = batch.with_valid(batch.valid & passes_f)
            out_nulls = tuple(
                jnp.zeros(c.shape[0], dtype=bool) if m is None else m
                for c, m in zip(batch.columns, batch.nulls)
            )
            return (
                batch.columns, out_nulls, batch.valid,
                bucket_ovf, run_ovf, exp_ovf, total,
            )

        in_specs = (
            self._leaf_specs(left.columns),
            self._leaf_specs(left.nulls),
            P(axis),
            self._leaf_specs(right.columns),
            self._leaf_specs(right.nulls),
            P(axis),
        )
        if semi_anti:
            n_out = len(l_schema)
        else:
            n_out = len(l_schema) + len(r_schema)
        out_specs = (
            tuple(P(axis) for _ in range(n_out)),
            tuple(P(axis) for _ in range(n_out)),
            P(axis),
            P(axis),
            P(axis),
            P(axis),
            P(axis),
        )
        sm = shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(sm)
