"""Mesh stage programs: whole distributed stages as ONE jitted shard_map.

The reference executes a repartitioned aggregate / partitioned join as
three processes' worth of machinery — upstream tasks hash-partition to IPC
files (shuffle_writer.rs:142-292), the scheduler promotes the next stage
(query_stage_scheduler.rs:181-309), downstream tasks fetch over Flight
(shuffle_reader.rs:102-130). On-pod, the whole pipeline compiles into one
XLA program per mesh: local partial -> ``all_to_all`` over ICI -> local
final, with no host round-trip between stages.

Capacity/overflow discipline: every shape is static; bucket and group
overflows come back as per-device flags, checked host-side after the step
(mirrors ops.aggregate / ops.join overflow style).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

from ballista_tpu.columnar.batch import DeviceBatch
from ballista_tpu.datatypes import DataType, Field, Schema
from ballista_tpu.errors import ExecutionError
from ballista_tpu.ops.aggregate import AggOp, group_aggregate
from ballista_tpu.ops.join import JoinSide, _build_finish, probe_side
from ballista_tpu.ops.perm import multi_key_perm
from ballista_tpu.parallel.collective import exchange_by_key
from ballista_tpu.parallel.mesh import SHARD_AXIS


def _sum_dtype_np(dtype: DataType) -> DataType:
    if dtype in (DataType.BOOL,) or dtype.is_integer:
        return DataType.INT64
    return DataType.FLOAT64


class MeshStageRunner:
    """Compiles and runs mesh-wide stage programs over a 1-D device mesh.

    Inputs are mesh-sharded batches (see ``parallel.mesh.shard_batch``);
    outputs stay sharded — each device holds the rows whose hash routes to
    it, exactly the invariant a downstream mesh stage needs.
    """

    def __init__(self, mesh, axis: str = SHARD_AXIS) -> None:
        self.mesh = mesh
        self.axis = axis
        self.n_dev = int(mesh.devices.size)
        self._programs: dict = {}

    # -- helpers -------------------------------------------------------------
    def _leaf_specs(self, tree):
        return jax.tree_util.tree_map(lambda _: P(self.axis), tree)

    @staticmethod
    def _check_flags(flags, what: str) -> None:
        import numpy as np

        if bool(np.any(np.asarray(flags))):
            raise ExecutionError(
                f"mesh {what} overflowed a static capacity; raise "
                "bucket/group capacity"
            )

    # -- repartitioned aggregate ---------------------------------------------
    def aggregate(
        self,
        batch: DeviceBatch,
        key_idxs: list[int],
        val_idxs: list[int],
        ops: list[AggOp],
        capacity: int,
        bucket_cap: int | None = None,
    ) -> DeviceBatch:
        """Partial agg per device -> all_to_all exchange of group states by
        key hash -> final merge agg per device. Output: sharded batch of
        (keys ++ aggregated values); each group lives on exactly one device.
        """
        bucket_cap = bucket_cap or capacity
        key = (
            "agg",
            str(batch.schema),
            batch.capacity,
            tuple(key_idxs),
            tuple(val_idxs),
            tuple(ops),
            capacity,
            bucket_cap,
            tuple(m is None for m in batch.nulls),
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile_aggregate(
                batch, tuple(key_idxs), tuple(val_idxs), tuple(ops),
                capacity, bucket_cap,
            )
            self._programs[key] = prog
        out_cols, out_nulls, out_valid, flags = prog(
            batch.columns, batch.nulls, batch.valid
        )
        self._check_flags(flags, "aggregate")
        in_schema = batch.schema
        fields = [in_schema.fields[i] for i in key_idxs]
        for i, op in zip(val_idxs, ops):
            f = in_schema.fields[i]
            if op == AggOp.COUNT:
                fields.append(Field(f"{f.name}#count", DataType.INT64, False))
            elif op == AggOp.SUM:
                fields.append(
                    Field(f"{f.name}#sum", _sum_dtype_np(f.dtype), True)
                )
            else:
                fields.append(Field(f"{f.name}#{op.value}", f.dtype, True))
        return DeviceBatch(
            schema=Schema(fields),
            columns=tuple(out_cols),
            valid=out_valid,
            nulls=tuple(out_nulls),
            dictionaries={
                k: v
                for k, v in batch.dictionaries.items()
                if any(f.name == k for f in fields)
            },
        )

    def _compile_aggregate(
        self, batch, key_idxs, val_idxs, ops, capacity, bucket_cap
    ):
        axis, n_dev = self.axis, self.n_dev
        merge_ops = tuple(op.merge_op for op in ops)
        n_keys = len(key_idxs)

        def f(cols, nulls, valid):
            key_cols = [cols[i] for i in key_idxs]
            key_nulls = [nulls[i] for i in key_idxs]
            val_cols = [cols[i] for i in val_idxs]
            val_nulls = [nulls[i] for i in val_idxs]
            part = group_aggregate(
                key_cols, key_nulls, valid, val_cols, val_nulls,
                list(ops), capacity,
            )
            st_cols = tuple(part.keys) + tuple(part.values)
            st_nulls = tuple(part.key_nulls) + tuple(part.value_nulls)
            ex_cols, ex_nulls, ex_valid, b_ovf = exchange_by_key(
                st_cols, st_nulls, part.valid,
                tuple(range(n_keys)), axis, n_dev, bucket_cap,
            )
            fin = group_aggregate(
                list(ex_cols[:n_keys]),
                list(ex_nulls[:n_keys]),
                ex_valid,
                list(ex_cols[n_keys:]),
                list(ex_nulls[n_keys:]),
                list(merge_ops),
                capacity,
            )
            flag = (part.overflow | b_ovf | fin.overflow).reshape(1)
            out_cols = tuple(fin.keys) + tuple(fin.values)
            # concrete (possibly all-false) masks so the output pytree has a
            # static structure for out_specs
            out_nulls = tuple(
                jnp.zeros(c.shape[0], dtype=bool) if m is None else m
                for c, m in zip(
                    out_cols, tuple(fin.key_nulls) + tuple(fin.value_nulls)
                )
            )
            return out_cols, out_nulls, fin.valid, flag

        in_specs = (
            self._leaf_specs(batch.columns),
            self._leaf_specs(batch.nulls),
            P(axis),
        )
        # outputs: all row-sharded (flags: one scalar per device)
        out_specs = (
            tuple(P(axis) for _ in range(n_keys + len(val_idxs))),
            tuple(P(axis) for _ in range(n_keys + len(val_idxs))),
            P(axis),
            P(axis),
        )
        sm = shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(sm)

    # -- partitioned join -----------------------------------------------------
    def join(
        self,
        left: DeviceBatch,
        right: DeviceBatch,
        left_keys: list[int],
        right_keys: list[int],
        join_type: JoinSide = JoinSide.INNER,
        bucket_cap: int | None = None,
    ) -> DeviceBatch:
        """PARTITIONED-mode join (ref HashJoinExecNode PartitionMode
        PARTITIONED, ballista.proto:474-487): exchange BOTH sides by join
        key over ICI, then build+probe locally per device. Join keys must
        be single integer columns (the exact-pack tier); the build side
        must be unique per key (flagged and raised otherwise)."""
        if len(left_keys) != 1 or len(right_keys) != 1:
            raise ExecutionError(
                "mesh partitioned join supports single-column integer keys"
            )
        lf = left.schema.fields[left_keys[0]]
        rf = right.schema.fields[right_keys[0]]
        for f_ in (lf, rf):
            if not (f_.dtype.is_integer or f_.dtype == DataType.STRING):
                raise ExecutionError(
                    f"mesh join key {f_.name!r} must be integer-backed"
                )
        # String keys join by dictionary code. The compiled program bakes no
        # dictionary knowledge, so the shared-dictionary contract must be
        # re-validated on EVERY call (a program-cache hit would otherwise
        # skip probe_side's trace-time check and join mismatched codes).
        if DataType.STRING in (lf.dtype, rf.dtype):
            ld = left.dictionaries.get(lf.name)
            rd = right.dictionaries.get(rf.name)
            if ld is None or rd is None or ld.values != rd.values:
                raise ExecutionError(
                    f"mesh join key {lf.name!r}/{rf.name!r} requires a "
                    "shared dictionary; unify dictionaries before sharding"
                )
        bucket_cap = bucket_cap or max(
            left.capacity // self.n_dev, right.capacity // self.n_dev, 1
        )
        key = (
            "join",
            str(left.schema), left.capacity,
            str(right.schema), right.capacity,
            tuple(left_keys), tuple(right_keys), join_type, bucket_cap,
            tuple(m is None for m in left.nulls),
            tuple(m is None for m in right.nulls),
        )
        prog = self._programs.get(key)
        if prog is None:
            prog = self._compile_join(
                left, right, tuple(left_keys), tuple(right_keys),
                join_type, bucket_cap,
            )
            self._programs[key] = prog
        cols, nulls, valid, flags = prog(
            left.columns, left.nulls, left.valid,
            right.columns, right.nulls, right.valid,
        )
        self._check_flags(flags, "join exchange/build")
        if join_type in (JoinSide.SEMI, JoinSide.ANTI):
            out_schema = left.schema
        elif join_type == JoinSide.LEFT:
            out_schema = left.schema.join(
                Schema([Field(f.name, f.dtype, True) for f in right.schema])
            )
        else:
            out_schema = left.schema.join(right.schema)
        dicts = dict(left.dictionaries)
        dicts.update(right.dictionaries)
        return DeviceBatch(
            schema=out_schema,
            columns=tuple(cols),
            valid=valid,
            nulls=tuple(nulls),
            dictionaries=dicts,
        )

    def _compile_join(
        self, left, right, left_keys, right_keys, join_type, bucket_cap
    ):
        axis, n_dev = self.axis, self.n_dev
        l_schema, r_schema = left.schema, right.schema
        l_dicts = dict(left.dictionaries)
        r_dicts = dict(right.dictionaries)

        def f(lcols, lnulls, lvalid, rcols, rnulls, rvalid):
            lc, ln, lv, l_ovf = exchange_by_key(
                lcols, lnulls, lvalid, left_keys, axis, n_dev, bucket_cap
            )
            rc, rn, rv, r_ovf = exchange_by_key(
                rcols, rnulls, rvalid, right_keys, axis, n_dev, bucket_cap
            )
            # build right locally (exact int packing; dups flagged)
            dead = ~rv
            for i in right_keys:
                if rn[i] is not None:
                    dead = dead | rn[i]
            packed = rc[right_keys[0]].astype(jnp.int64)
            perm = multi_key_perm([(dead, False), (packed, False)])
            rbatch = DeviceBatch(
                schema=r_schema,
                columns=rc,
                valid=rv,
                nulls=rn,
                dictionaries=r_dicts,
            )
            bt = _build_finish(
                perm, dead, packed, rbatch, tuple(right_keys), "exact"
            )
            lbatch = DeviceBatch(
                schema=l_schema,
                columns=lc,
                valid=lv,
                nulls=ln,
                dictionaries=l_dicts,
            )
            joined = probe_side(bt, lbatch, list(left_keys), join_type)
            flag = (l_ovf | r_ovf | bt.has_dups).reshape(1)
            out_nulls = tuple(
                jnp.zeros(c.shape[0], dtype=bool) if m is None else m
                for c, m in zip(joined.columns, joined.nulls)
            )
            return joined.columns, out_nulls, joined.valid, flag

        in_specs = (
            self._leaf_specs(left.columns),
            self._leaf_specs(left.nulls),
            P(axis),
            self._leaf_specs(right.columns),
            self._leaf_specs(right.nulls),
            P(axis),
        )
        if join_type in (JoinSide.SEMI, JoinSide.ANTI):
            n_out = len(l_schema)
        else:
            n_out = len(l_schema) + len(r_schema)
        out_specs = (
            tuple(P(axis) for _ in range(n_out)),
            tuple(P(axis) for _ in range(n_out)),
            P(axis),
            P(axis),
        )
        sm = shard_map(
            f, mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=False,
        )
        return jax.jit(sm)
