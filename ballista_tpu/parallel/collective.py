"""Traceable shuffle-exchange kernels (call INSIDE ``shard_map``).

The on-pod replacement for the reference's hash shuffle
(shuffle_writer.rs:201-285 -> IPC files -> shuffle_reader.rs:102-130 over
Flight): each device hash-bins its local rows into ``n_parts``
equal-capacity buckets (one fused stable sort + scatter, static shapes),
then one ``jax.lax.all_to_all`` over ICI delivers bucket *d* of every
device to device *d*. Bucket overflow is detected on device and surfaced
as a flag for the host to raise after the step (no data-dependent shapes).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ballista_tpu.ops.partition import partition_ids_for
from ballista_tpu.ops.perm import multi_key_perm
from ballista_tpu.ops.search import searchsorted as _ss


def bucket_rows(
    cols: tuple[jnp.ndarray, ...],
    nulls: tuple[jnp.ndarray | None, ...],
    valid: jnp.ndarray,
    key_positions: tuple[int, ...],
    n_parts: int,
    bucket_cap: int,
) -> tuple[tuple, tuple, jnp.ndarray, jnp.ndarray]:
    """Scatter local rows into ``n_parts`` contiguous buckets of
    ``bucket_cap`` slots each by KEY HASH. Returns (cols, nulls, valid,
    overflow) with row axis ``n_parts * bucket_cap``."""
    key_cols = [cols[i] for i in key_positions]
    key_nulls = [nulls[i] for i in key_positions]
    pid = partition_ids_for(key_cols, key_nulls, valid, n_parts)
    return bucket_rows_by_pid(cols, nulls, valid, pid, n_parts, bucket_cap)


def bucket_rows_by_pid(
    cols: tuple[jnp.ndarray, ...],
    nulls: tuple[jnp.ndarray | None, ...],
    valid: jnp.ndarray,
    pid: jnp.ndarray,
    n_parts: int,
    bucket_cap: int,
) -> tuple[tuple, tuple, jnp.ndarray, jnp.ndarray]:
    """bucket_rows with caller-computed partition ids (``pid >= n_parts``
    drops the row) — the range-exchange entry the mesh sample sort uses."""
    perm = multi_key_perm([(pid, False)])
    pid_s = pid[perm]
    starts = _ss(pid_s, jnp.arange(n_parts, dtype=pid_s.dtype))
    cap = valid.shape[0]
    iota = jnp.arange(cap, dtype=jnp.int32)
    pid_c = jnp.clip(pid_s, 0, n_parts - 1)
    rank = iota - starts[pid_c].astype(jnp.int32)
    live = pid_s < n_parts
    fits = live & (rank < bucket_cap)
    overflow = jnp.any(live & (rank >= bucket_cap))
    out_len = n_parts * bucket_cap
    # rows that don't fit scatter to the drop slot out_len
    slot = jnp.where(fits, pid_c * bucket_cap + rank, out_len)

    def scatter(col, fill):
        base = jnp.full((out_len,) + col.shape[1:], fill, dtype=col.dtype)
        return base.at[slot].set(col[perm], mode="drop")

    out_cols = tuple(scatter(c, 0) for c in cols)
    out_nulls = tuple(
        None if m is None else scatter(m, True) for m in nulls
    )
    out_valid = (
        jnp.zeros(out_len, dtype=bool).at[slot].set(fits, mode="drop")
    )
    return out_cols, out_nulls, out_valid, overflow


def all_to_all_rows(
    cols: tuple[jnp.ndarray, ...],
    nulls: tuple[jnp.ndarray | None, ...],
    valid: jnp.ndarray,
    axis_name: str,
    n_parts: int,
    bucket_cap: int,
) -> tuple[tuple, tuple, jnp.ndarray]:
    """Exchange bucketed rows over ICI: bucket d of every device lands on
    device d. Row axis stays ``n_parts * bucket_cap`` (bucket b of the
    result = rows received from peer b)."""

    def xc(col):
        x = col.reshape((n_parts, bucket_cap) + col.shape[1:])
        y = jax.lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0)
        return y.reshape((n_parts * bucket_cap,) + col.shape[1:])

    return (
        tuple(xc(c) for c in cols),
        tuple(None if m is None else xc(m) for m in nulls),
        xc(valid),
    )


def exchange_by_key(
    batch_cols: tuple[jnp.ndarray, ...],
    batch_nulls: tuple[jnp.ndarray | None, ...],
    valid: jnp.ndarray,
    key_positions: tuple[int, ...],
    axis_name: str,
    n_parts: int,
    bucket_cap: int,
) -> tuple[tuple, tuple, jnp.ndarray, jnp.ndarray]:
    """bucket_rows + all_to_all_rows: after this, every live row sits on
    the device owning hash(key) % n_parts. Returns (cols, nulls, valid,
    overflow)."""
    cols, nulls, v, overflow = bucket_rows(
        batch_cols, batch_nulls, valid, key_positions, n_parts, bucket_cap
    )
    cols, nulls, v = all_to_all_rows(
        cols, nulls, v, axis_name, n_parts, bucket_cap
    )
    return cols, nulls, v, overflow
