"""Device mesh construction and batch sharding.

The mesh is 1-D over the shuffle axis: stage partitions map to mesh slots
exactly like the reference maps stage partitions to executor task slots
(ballista/rust/scheduler/src/state/task_scheduler.rs:53-211) — except here
"executors" are chips on ICI and placement is XLA's job.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ballista_tpu.columnar.batch import DeviceBatch, round_capacity
from ballista_tpu.errors import ExecutionError

SHARD_AXIS = "shards"


def make_mesh(n_devices: int | None = None, axis: str = SHARD_AXIS) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices."""
    devs = jax.devices()
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ExecutionError(
                f"need {n_devices} devices, have {len(devs)} "
                "(set XLA_FLAGS=--xla_force_host_platform_device_count=N "
                "JAX_PLATFORMS=cpu for a virtual CPU mesh)"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def row_sharding(mesh: Mesh, axis: str = SHARD_AXIS) -> NamedSharding:
    return NamedSharding(mesh, P(axis))


def shard_batch(
    mesh: Mesh,
    batch: DeviceBatch,
    axis: str = SHARD_AXIS,
    local_capacity: int | None = None,
) -> DeviceBatch:
    """Distribute a host-visible batch across the mesh's row axis.

    Output arrays have global length ``n_dev * local_capacity`` with rows
    round-robin-packed into per-device blocks (block d = rows for device d);
    masked slots pad each block.
    """
    n_dev = mesh.devices.size
    n = int(np.sum(np.asarray(batch.valid)))
    per_dev = -(-n // n_dev)  # ceil
    cap = local_capacity or round_capacity(max(per_dev, 1))
    if per_dev > cap:
        raise ExecutionError(
            f"local capacity {cap} < {per_dev} rows per device"
        )
    live = np.flatnonzero(np.asarray(batch.valid))
    sh = row_sharding(mesh, axis)

    def place(col, fill=0):
        col = np.asarray(col)
        out = np.full((n_dev * cap,) + col.shape[1:], fill, dtype=col.dtype)
        for d in range(n_dev):
            rows = live[d::n_dev]
            out[d * cap : d * cap + len(rows)] = col[rows]
        return jax.device_put(out, sh)

    valid = np.zeros(n_dev * cap, dtype=bool)
    for d in range(n_dev):
        valid[d * cap : d * cap + len(live[d::n_dev])] = True
    return DeviceBatch(
        schema=batch.schema,
        columns=tuple(place(c) for c in batch.columns),
        valid=jax.device_put(valid, sh),
        nulls=tuple(
            None if m is None else place(m, fill=True) for m in batch.nulls
        ),
        dictionaries=dict(batch.dictionaries),
    )


def is_row_sharded(batch: DeviceBatch, mesh: Mesh, axis: str = SHARD_AXIS) -> bool:
    """True when the batch's arrays are already sharded over this mesh's
    row axis (the invariant mesh stage outputs maintain) — lets a chain of
    mesh operators compose without host round-trips."""
    want = NamedSharding(mesh, P(axis))
    try:
        return all(
            getattr(c, "sharding", None) is not None
            and c.sharding.is_equivalent_to(want, c.ndim)
            for c in batch.columns + (batch.valid,)
        )
    except Exception:
        return False


def unshard_batch(batch: DeviceBatch) -> DeviceBatch:
    """Gather a mesh-sharded batch back to one addressable batch (host
    gather — the client collect path, not a hot path)."""
    cols = tuple(jnp.asarray(np.asarray(c)) for c in batch.columns)
    return DeviceBatch(
        schema=batch.schema,
        columns=cols,
        valid=jnp.asarray(np.asarray(batch.valid)),
        nulls=tuple(
            None if m is None else jnp.asarray(np.asarray(m))
            for m in batch.nulls
        ),
        dictionaries=dict(batch.dictionaries),
    )


