"""Multi-chip dryrun body: the full distributed stage pipeline on an
n-device mesh, asserted against a numpy oracle.

Run as ``python -m ballista_tpu.parallel.dryrun N`` in an environment where
jax sees N devices (the driver entry ``__graft_entry__.dryrun_multichip``
launches this module in a subprocess with ``JAX_PLATFORMS=cpu`` and
``--xla_force_host_platform_device_count=N`` so a broken/mismatched TPU
runtime on the host can never take the dryrun down with it).

The pipeline mirrors the reference's PARTITIONED join + repartitioned
aggregate flow (planner.rs:133-157; shuffle_writer.rs:142-292 <->
shuffle_reader.rs:102-130), compiled as shard_map programs with
``jax.lax.all_to_all`` exchanges over the mesh axis.
"""

from __future__ import annotations

import sys

import numpy as np


def run(n_devices: int) -> None:
    import jax

    import pyarrow as pa

    from ballista_tpu.exec.context import TpuContext

    assert len(jax.devices()) >= n_devices, (
        f"need {n_devices} devices, jax sees {jax.devices()}"
    )

    rng = np.random.default_rng(7)
    n, n_dim = 20_000, 230
    fact = pa.table(
        {
            "k": pa.array(rng.integers(0, n_dim + 20, n)),  # some misses
            "v": pa.array(rng.uniform(0, 10, n)),
        }
    )
    dim = pa.table(
        {
            "id": pa.array(np.arange(n_dim, dtype=np.int64)),
            "grp": pa.array((np.arange(n_dim) % 13).astype(np.int64)),
        }
    )
    ctx = TpuContext()
    rt = ctx.mesh_runtime()
    assert rt is not None, "mesh runtime must be active for the dryrun"
    ctx.register_table("fact", fact)
    ctx.register_table("dim", dim)

    sql = (
        "SELECT grp, SUM(v) AS s, COUNT(*) AS c FROM fact "
        "JOIN dim ON k = id GROUP BY grp ORDER BY grp"
    )
    # the plan must route through the mesh operators (shard_map +
    # all_to_all), not the serial coalesce funnel
    disp = ctx.create_physical_plan(ctx.sql_to_logical(sql)).display()
    assert "MeshJoinExec" in disp and "MeshAggregateExec" in disp, disp

    out = ctx.sql(sql).collect().to_pandas()
    df = fact.to_pandas().merge(dim.to_pandas(), left_on="k", right_on="id")
    want = (
        df.groupby("grp")
        .v.agg(["sum", "count"])
        .reset_index()
        .sort_values("grp")
        .reset_index(drop=True)
    )
    np.testing.assert_array_equal(out.grp.to_numpy(), want.grp.to_numpy())
    np.testing.assert_allclose(
        out.s.to_numpy(), want["sum"].to_numpy(), rtol=1e-9
    )
    np.testing.assert_array_equal(out.c.to_numpy(), want["count"].to_numpy())

    # SCHEDULER PATH (SURVEY build-order #6): the same query through the
    # full distributed control plane — the executor registers n_devices,
    # the scheduler plans a fused mesh stage-chain, the stage plan crosses
    # the serde boundary, and the executor runs it via its own
    # MeshRuntime. Asserts mesh placement in the EXECUTOR-side stage plan
    # and the same oracle values end-to-end over gRPC/Flight.
    import time

    from ballista_tpu.client.context import BallistaContext

    dctx = BallistaContext.standalone()
    try:
        sched = dctx._standalone_cluster.scheduler
        deadline = time.time() + 30
        while time.time() < deadline:
            specs = [
                em.specification
                for em in sched.executor_manager.all_executors()
            ]
            if any((s.n_devices or 1) >= n_devices for s in specs):
                break
            time.sleep(0.1)
        else:
            raise AssertionError(
                f"executor never advertised {n_devices} devices: {specs}"
            )
        dctx.register_table("fact", fact)
        dctx.register_table("dim", dim)
        dout = dctx.sql(sql).collect().to_pandas()
        stage_disp = "\n".join(
            stage.plan.display()
            for job in sched.jobs.values()
            for stage in job.stages.values()
        )
        assert "MeshJoinExec" in stage_disp and (
            "MeshAggregateExec" in stage_disp
        ), f"mesh ops missing from distributed stage plans:\n{stage_disp}"
        np.testing.assert_array_equal(
            dout.grp.to_numpy(), want.grp.to_numpy()
        )
        np.testing.assert_allclose(
            dout.s.to_numpy(), want["sum"].to_numpy(), rtol=1e-9
        )
        np.testing.assert_array_equal(
            dout.c.to_numpy(), want["count"].to_numpy()
        )
    finally:
        dctx._standalone_cluster.stop()


def main() -> None:
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    run(n)
    # planlint static surface: the per-kernel signature report over
    # ops/ + exec/ — which jit parameters are static (recompile keys) vs
    # traced — printed beside the mesh-placement assertions so a hazard
    # introduced by a kernel change fails the same gate that proves the
    # distributed pipeline.
    from ballista_tpu.analysis.jaxlint import static_signature_report

    report = static_signature_report()
    hazards = [h for k in report.values() for h in k["hazards"]]
    print(f"planlint: {len(report)} jitted kernels, {len(hazards)} hazards")
    for name, info in sorted(report.items()):
        static = ", ".join(info["static"]) or "-"
        print(f"  {name}  static[{static}]")
    for h in hazards:
        print(f"  HAZARD {h}")
    if hazards:
        # not an assert: the gate must hold under `python -O` too
        raise SystemExit(
            f"{len(hazards)} JAX hazards (see planlint output above)"
        )
    # closed-vocabulary gate (docs/compile_cache.md): the same report is
    # the source of truth for compilecache.registry — a jit site that is
    # not registered there is an undeclared cold-start compile surface
    from ballista_tpu.compilecache import registry

    problems = registry.check_vocabulary(report)
    for p in problems:
        print(f"  VOCABULARY {p}")
    if problems:
        raise SystemExit(
            f"{len(problems)} compile-vocabulary findings (see above)"
        )
    print(
        f"compile-vocab: {len(registry.VOCABULARY)} kernels registered, "
        "report closed"
    )
    print(f"dryrun ok on {n} devices")


if __name__ == "__main__":
    main()
