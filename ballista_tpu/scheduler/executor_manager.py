"""Executor registry: heartbeats + slot accounting.

ref ballista/rust/scheduler/src/state/executor_manager.rs:28-145.
"""

from __future__ import annotations

import time

from ballista_tpu.analysis.witness import make_lock
from ballista_tpu.scheduler_types import ExecutorData, ExecutorMetadata

DEFAULT_EXECUTOR_TIMEOUT_SECONDS = 60.0  # ref :69-77


class ExecutorManager:
    def __init__(self) -> None:
        self._lock = make_lock("ExecutorManager._lock", reentrant=True)
        self._heartbeats: dict[str, float] = {}
        self._metadata: dict[str, ExecutorMetadata] = {}
        self._data: dict[str, ExecutorData] = {}
        # latest compile-latency counter snapshot per executor (ridden in
        # on HeartBeatParams/PollWorkParams.metrics; docs/compile_cache.md)
        self._metrics: dict[str, dict[str, float]] = {}

    def save_executor_metadata(self, meta: ExecutorMetadata) -> None:
        with self._lock:
            self._metadata[meta.id] = meta

    def get_executor_metadata(self, executor_id: str) -> ExecutorMetadata | None:
        with self._lock:
            return self._metadata.get(executor_id)

    def all_executors(self) -> list[ExecutorMetadata]:
        with self._lock:
            return list(self._metadata.values())

    def save_executor_heartbeat(self, executor_id: str) -> None:
        with self._lock:
            self._heartbeats[executor_id] = time.time()

    def save_executor_metrics(
        self, executor_id: str, metrics: dict[str, float]
    ) -> None:
        """Store the latest counter snapshot (replace, not merge: the
        executor sends cumulative process-wide counters)."""
        if not metrics:
            return
        with self._lock:
            self._metrics[executor_id] = dict(metrics)

    def get_executor_metrics(self, executor_id: str) -> dict[str, float]:
        with self._lock:
            return dict(self._metrics.get(executor_id, ()))

    def last_seen(self, executor_id: str) -> float | None:
        with self._lock:
            return self._heartbeats.get(executor_id)

    def get_alive_executors(
        self, timeout: float = DEFAULT_EXECUTOR_TIMEOUT_SECONDS
    ) -> set[str]:
        """ref :55-77 — alive = heartbeat within the window."""
        now = time.time()
        with self._lock:
            return {
                eid
                for eid, ts in self._heartbeats.items()
                if now - ts <= timeout
            }

    def save_executor_data(self, data: ExecutorData) -> None:
        with self._lock:
            self._data[data.executor_id] = data

    def update_executor_data(self, executor_id: str, delta: int) -> None:
        """Adjust available slots by +/- delta (ref :84-109)."""
        with self._lock:
            d = self._data.get(executor_id)
            if d is None:
                return
            d.available_task_slots = max(
                0, min(d.total_task_slots, d.available_task_slots + delta)
            )

    def get_executor_data(self, executor_id: str) -> ExecutorData | None:
        with self._lock:
            return self._data.get(executor_id)

    def tracked_executors(self) -> set[str]:
        """Executors with registered slot accounting (candidates for
        expiry checks)."""
        with self._lock:
            return set(self._data.keys())

    def remove_executor(self, executor_id: str) -> None:
        """Drop a dead executor from scheduling (metadata is kept — already-
        written shuffle locations still reference its host)."""
        with self._lock:
            self._data.pop(executor_id, None)
            self._heartbeats.pop(executor_id, None)
            self._metrics.pop(executor_id, None)

    def get_available_executors_data(
        self, timeout: float = DEFAULT_EXECUTOR_TIMEOUT_SECONDS
    ) -> list[ExecutorData]:
        """Alive executors with free slots, most-free first (ref :121-135)."""
        alive = self.get_alive_executors(timeout)
        with self._lock:
            out = [
                ExecutorData(
                    d.executor_id, d.total_task_slots, d.available_task_slots
                )
                for d in self._data.values()
                if d.executor_id in alive and d.available_task_slots > 0
            ]
        out.sort(key=lambda d: -d.available_task_slots)
        return out
