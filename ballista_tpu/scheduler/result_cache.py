"""Scheduler-side plan-fingerprint result cache (docs/serving.md).

The serving fast path's first layer: a bounded LRU mapping the canonical
fingerprint of an optimized logical plan — the SAME serde-bytes identity
``exec/context.create_physical_plan`` caches physical plans under —
composed with the session settings and the registered tables' data
versions, to the job's committed Arrow result (one IPC stream). A
repeated identical query over unchanged data is answered by the
scheduler alone: no stages, no task grants, no executor round-trip.

Invalidation is BY KEY, never by sweep: re-registering or appending to a
table changes its ``_data_version`` component (memory tables key on
object identity + row count, files on mtime — the seam
``exec/context.py`` already uses for its local plan caches), so the next
submission simply misses and the stale entry ages out of the LRU.
Plans scanning ``system.*`` tables are never keyed at all (they must
serve the rows as of THIS query). The cache is in-memory only — a
scheduler restart starts empty by construction, which is exactly the
"no stale serve after ``_recover_state``" contract.

Only COMMITTED results enter: population happens after JobFinished, by
re-reading the final stage's committed partitions through the same
``fetch_partition_table`` path the client uses. A mid-run executor kill
therefore can never seed the cache with partial data — either the job's
lineage recovery re-completes it (and the re-read sees the recomputed
commit), or the job fails and nothing is stored.
"""

from __future__ import annotations

import collections
import logging

from ballista_tpu.analysis.witness import make_lock

log = logging.getLogger(__name__)


def result_cache_key(optimized, cfg, provider) -> tuple | None:
    """Cache identity for one submission, or None for "uncacheable".

    ``(plan serde bytes, sorted session settings, provider data
    version)`` — identical queries over identical data under identical
    settings, nothing else. None when the provider cannot report data
    versions (no table registry attached — remote schedulers without an
    attached provider must not serve stale results), when the plan scans
    a system table, or when the plan has no serde encoding.
    """
    data_version = getattr(provider, "_data_version", None)
    if data_version is None:
        return None
    from ballista_tpu.exec.context import _scans_system_table

    if _scans_system_table(optimized):
        return None
    try:
        from ballista_tpu.serde import logical_to_proto

        fp = logical_to_proto(optimized).SerializeToString()
        version = data_version()
    except Exception:  # noqa: BLE001 — unserializable plan: run it fresh
        return None
    return (fp, tuple(sorted(cfg.settings().items())), version)


class ResultCache:
    """Bytes-bounded LRU of committed query results.

    Every mutable field is guarded by the witness lock (racelint
    guarded-field); payloads are immutable ``bytes`` so a returned hit
    is safe to hand to any thread. Eviction pops the least-recently-used
    entry first — ``OrderedDict`` recency order, fully deterministic for
    a given get/put sequence (detlint: no hash-seed iteration anywhere
    on the eviction path).
    """

    def __init__(self, capacity_bytes: int):
        self.capacity_bytes = max(0, int(capacity_bytes))
        # one entry may use at most a quarter of the budget: a single
        # huge result would otherwise evict the entire working set for
        # one hit
        self.entry_cap_bytes = self.capacity_bytes // 4 or 1
        self._lock = make_lock("ResultCache._lock")
        # key -> (ipc payload, meta dict). meta carries the originating
        # job's query_class so a hit keeps labeling the fleet latency
        # series correctly WITHOUT re-running physical planning.
        self._entries: collections.OrderedDict[tuple, tuple[bytes, dict]] = (
            collections.OrderedDict()
        )
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.rejected_oversize = 0

    @property
    def enabled(self) -> bool:
        return self.capacity_bytes > 0

    def get(self, key: tuple | None) -> tuple[bytes, dict] | None:
        """``(payload, meta)`` for ``key``, counting the hit/miss.
        ``None`` keys (uncacheable submissions) count as misses so the
        hit ratio the bench reports stays honest about them."""
        if not self.enabled:
            return None
        with self._lock:
            if key is None:
                self.misses += 1
                return None
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry

    def put(self, key: tuple, payload: bytes, meta: dict | None = None
            ) -> bool:
        """Store one committed result; False when it exceeds the
        per-entry cap (counted — no silent caps)."""
        if not self.enabled or key is None:
            return False
        size = len(payload)
        if size > self.entry_cap_bytes:
            with self._lock:
                self.rejected_oversize += 1
            return False
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= len(old[0])
            self._entries[key] = (payload, dict(meta or {}))
            self._bytes += size
            while self._bytes > self.capacity_bytes and self._entries:
                _k, evicted = self._entries.popitem(last=False)
                self._bytes -= len(evicted[0])
                self.evictions += 1
        return True

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    def stats(self) -> dict:
        """Snapshot for /api/metrics and the BENCH_SERVE artifact."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "rejected_oversize": self.rejected_oversize,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "capacity_bytes": self.capacity_bytes,
            }


def table_to_ipc(table) -> bytes:
    """One Arrow table -> one IPC stream (the CompletedJob.result_ipc
    wire shape). The stream format (not file) matches the shuffle data
    plane's framing so the client reassembles with the same reader."""
    import pyarrow as pa

    sink = pa.BufferOutputStream()
    with pa.ipc.new_stream(sink, table.schema) as w:
        w.write_table(table)
    return sink.getvalue().to_pybytes()


def ipc_to_table(payload: bytes):
    import pyarrow as pa

    with pa.ipc.open_stream(pa.py_buffer(payload)) as r:
        return r.read_all()
