"""Write-through persistent scheduler state.

Mirrors the reference's ``PersistentSchedulerState`` (ref
ballista/rust/scheduler/src/state/persistent_state.rs:39-399): a
write-through cache over a :class:`StateBackendClient` storing executor
metadata, job statuses, job->session config, and serialized stage plans
under ``/ballista/<namespace>/...`` keys (:326-352), with ``init()``
reloading everything on scheduler restart (:85-181) — the
restart-recovery contract pinned by the reference's test at
persistent_state.rs:401-525.

Running task state (the StageManager) is deliberately NOT persisted,
matching the reference: a restarted scheduler recovers completed jobs and
their result locations; jobs that were mid-flight are marked failed with
a restart error (the reference leaves them dangling — failing loudly is
the stricter contract).
"""

from __future__ import annotations

import dataclasses
import json
import logging

from ballista_tpu.scheduler.state_backend import StateBackendClient
from ballista_tpu.scheduler_types import (
    ExecutorMetadata,
    ExecutorSpecification,
    PartitionLocation,
)

log = logging.getLogger(__name__)


class PersistentSchedulerState:
    def __init__(
        self,
        backend: StateBackendClient,
        namespace: str = "default",
        codec=None,
    ) -> None:
        self.backend = backend
        self.namespace = namespace
        self.codec = codec

    # -- key scheme (ref persistent_state.rs:326-352) ------------------------
    def _k(self, *parts: str) -> str:
        return "/".join(("/ballista", self.namespace) + parts)

    # -- executors -----------------------------------------------------------
    def save_executor_metadata(self, meta: ExecutorMetadata) -> None:
        payload = json.dumps(
            {
                "id": meta.id,
                "host": meta.host,
                "port": meta.port,
                "grpc_port": meta.grpc_port,
                "task_slots": meta.specification.task_slots,
                "n_devices": meta.specification.n_devices,
            }
        ).encode()
        with self.backend.lock():  # ref persistent_state.rs:313-319
            self.backend.put(self._k("executor_metadata", meta.id), payload)

    def load_executors(self) -> list[ExecutorMetadata]:
        out = []
        for _, v in self.backend.get_from_prefix(
            self._k("executor_metadata")
        ):
            d = json.loads(v)
            out.append(
                ExecutorMetadata(
                    id=d["id"],
                    host=d["host"],
                    port=d["port"],
                    grpc_port=d.get("grpc_port", 0),
                    specification=ExecutorSpecification(
                        task_slots=d.get("task_slots", 4),
                        n_devices=d.get("n_devices", 1),
                    ),
                )
            )
        return out

    # -- sessions ------------------------------------------------------------
    def save_session(self, session_id: str, settings: dict[str, str]) -> None:
        with self.backend.lock():
            self.backend.put(
                self._k("sessions", session_id),
                json.dumps(settings).encode(),
            )

    def load_sessions(self) -> dict[str, dict[str, str]]:
        return {
            k.rsplit("/", 1)[1]: json.loads(v)
            for k, v in self.backend.get_from_prefix(self._k("sessions"))
        }

    # -- jobs ----------------------------------------------------------------
    def save_job(self, job) -> None:
        """``job`` is a scheduler JobInfo (duck-typed to avoid a cycle)."""
        payload = json.dumps(
            {
                "job_id": job.job_id,
                "session_id": job.session_id,
                "status": job.status,
                "error": job.error,
                "final_stage_id": job.final_stage_id,
                "dependencies": {
                    str(k): sorted(v) for k, v in job.dependencies.items()
                },
                "locations": [
                    {
                        k: v
                        for k, v in dataclasses.asdict(loc).items()
                        if k != "stats"  # per-file stats don't drive reads
                    }
                    for loc in job.completed_locations
                ],
            }
        ).encode()
        with self.backend.lock():
            self.backend.put(self._k("jobs", job.job_id), payload)

    def load_jobs(self) -> list[dict]:
        return [
            json.loads(v)
            for _, v in self.backend.get_from_prefix(self._k("jobs"))
        ]

    # -- stage plans ---------------------------------------------------------
    def save_stage_plan(self, job_id: str, stage_id: int, plan) -> None:
        if self.codec is None:
            return
        data = self.codec.physical_to_proto(plan).SerializeToString()
        with self.backend.lock():
            self.backend.put(self._k("stages", job_id, str(stage_id)), data)

    def load_stage_plans(self, job_id: str) -> dict[int, object]:
        """stage_id -> decoded physical plan."""
        if self.codec is None:
            return {}
        from ballista_tpu.proto import pb

        out: dict[int, object] = {}
        for k, v in self.backend.get_from_prefix(
            self._k("stages", job_id)
        ):
            stage_id = int(k.rsplit("/", 1)[1])
            node = pb.PhysicalPlanNode()
            node.ParseFromString(v)
            try:
                out[stage_id] = self.codec.physical_from_proto(node)
            except Exception as e:  # noqa: BLE001 — table may be gone
                log.warning(
                    "could not decode stage %s/%s on recovery: %s",
                    job_id, stage_id, e,
                )
        return out

    @staticmethod
    def locations_from_json(rows: list[dict]) -> list[PartitionLocation]:
        return [PartitionLocation(**r) for r in rows]
