"""Adaptive query execution: the POLICY layer over certified rewrites.

The ROADMAP's AQE item in one sentence: PR 10 ships per-operator runtime
stats, PR 11 ships the certified-rewrite safety substrate
(``ballista_tpu/rewrite.py`` + ``SchedulerServer.apply_certified_rewrite``),
PR 12's skew monitor flags hot partitions — this module is the brain that
READS those signals and DECIDES which certified rewrite to apply when.
It never mutates a plan itself: every adaptation goes through
``apply_certified_rewrite`` (the eqlint closure stays intact), so an
adaptation the certificate cannot prove safe is REJECTED with its failing
clause and the job proceeds on the pristine template — the policy may be
wrong, the plan may not (docs/aqe.md).

Two decision points, one rule set:

- **Reactive (StageFinished)** — ``on_stage_finished`` runs BEFORE a
  dependent stage is promoted: the completed producers' shuffle-write
  metas give exact per-bucket rows/bytes, and the consumer is still
  fully PENDING, so a rewrite that touches ONLY the consumer (the
  build-side flip) can apply mid-job. Rewrites that re-bucket a producer
  (broadcast/coalesce/split) cannot apply here — the producer just
  completed, and the runtime precondition (touched stages fully pending)
  correctly rejects them — so those decisions are LEARNED instead.
- **Proactive (submission)** — ``on_job_submitted`` applies the learned
  strategies for the job's query class (obs/qclass.py) right after stage
  generation, while every stage is still pending: split a skew-flagged
  consumer's buckets, coalesce tiny ones toward
  ``ballista.tpu.aqe_target_partition_mb``, broadcast a build side that
  measured under ``ballista.tpu.aqe_broadcast_threshold_mb``, flip a
  misestimated build. Strategies persist through the PR 7 hints seam
  (``compilecache/hints.py`` — the same ``plan_hints.json`` file, an
  ``("aqe", <class>)`` key family), so a FRESH process plans adaptively
  from the first submission of a known query class.

Every decision — applied, rejected (with the certificate clause), or
learned — is recorded on the job (``JobInfo.aqe_decisions``, served by
``GET /api/job/<id>``), as an ``aqe`` trace event with before/after
stats, in the ``ballista_aqe_rewrites_total{op,outcome}`` Prometheus
family, and in the job's terminal history record. A rejection of a
learned strategy whose certificate clause failed (not a transient
runtime-state race) UNLEARNS it, so a stale strategy self-heals into one
extra no-op submission rather than a permanent reject loop. All
adaptations stay inside the closed compile vocabulary by construction:
the certificate's compile-vocab clause is part of acceptance.
"""

from __future__ import annotations

import logging
import os

from ballista_tpu.analysis.witness import make_lock
from ballista_tpu.errors import RewriteRejected

log = logging.getLogger(__name__)

# decision thresholds (module constants, not knobs: they shape WHEN the
# knob-declared byte thresholds apply, and sweeping them is a bench
# exercise, not a deployment one)
FLIP_FACTOR = 2.0  # observed build > k x observed probe
FLIP_EST_FACTOR = 4.0  # observed build > k x ESTIMATED probe (hysteresis)
# noise floors: flipping a tiny build gains nothing and risks plan churn
# (every flip re-shapes a stage -> fresh compile signatures); only
# misestimates that actually cost something are worth acting on
FLIP_MIN_BUILD_BYTES = 1 << 20  # reactive path (exact meta bytes)
FLIP_MIN_BUILD_ROWS = 1 << 16  # metrics path (valid-row counts)
SPLIT_MAX_FACTOR = 8  # bucket-count growth per split decision
SPLIT_BUCKET_CAP = 64  # absolute bucket ceiling a split may reach
MB = 1024 * 1024

# rejection clauses that mean "this strategy is wrong for this plan"
# (unlearn) as opposed to "the job raced past the rewrite window"
# (keep — next submission applies while everything is pending)
_TRANSIENT_CLAUSES = ("runtime-state", "job-state", "injected")
# clauses that are STRUCTURAL per query class — determined by the plan
# shape alone, so a rejection today rejects forever: these also DENY
# the (family, stage) so the observe rules stop re-learning it.
# "op-applicability" is deliberately absent: its preconditions depend
# on session config (a coalesce learned at 16 buckets rejects at 2
# because 2 -> 2 cannot shrink), and a permanent denial would poison
# the class after a one-off config change — those just unlearn, and
# the observe rules re-derive a spec consistent with the current
# config on the next run.
_STRUCTURAL_CLAUSES = (
    "float-sensitivity",
    "schema-equivalence",
    "column-resolution",
    "compile-vocab",
    "partition-compat",
    "stage-dag",
)


def env_override() -> bool | None:
    """The ``BALLISTA_AQE`` process kill-switch/force: ``0``/``off``
    disables AQE regardless of session config, ``1``/``on`` enables it;
    unset defers to ``ballista.tpu.aqe``."""
    v = os.environ.get("BALLISTA_AQE", "").strip().lower()
    if v in ("0", "off", "false"):
        return False
    if v in ("1", "on", "true"):
        return True
    return None


def enabled(cfg) -> bool:
    ov = env_override()
    if ov is not None:
        return ov
    return cfg.aqe()


# ---------------------------------------------------------------------------
# learned strategies, persisted through the PR 7 hints seam
# ---------------------------------------------------------------------------


class StrategyStore:
    """Per-query-class learned rewrite strategies.

    In-memory map ``{query_class: (spec, ...)}`` where a spec is a plain
    literal tuple — ``("flip", stage_id, occurrence)``,
    ``("broadcast", stage_id, occurrence)``,
    ``("coalesce", stage_id, new_n)``, ``("split", stage_id, new_n)`` —
    persisted via :class:`compilecache.hints.HintStore` under
    ``("aqe", <class>)`` keys in the shared ``plan_hints.json`` (atomic
    merge-under writes; ``BALLISTA_TPU_HINT_CACHE=off`` keeps it
    process-local). Safety is NOT this store's job: stage ids are stable
    for a plan shape (the DistributedPlanner numbers deterministically
    and the class fingerprint is structural), and anything stale is
    caught by server-side re-certification at application time."""

    def __init__(self) -> None:
        self._lock = make_lock("AqeStrategyStore._lock")
        # hints.HintStore API shape: a scalar-hint dict (unused here)
        # plus the keyed entry cache the file round-trips
        self._hint: dict = {}
        self._cache: dict = {}
        from ballista_tpu.compilecache.hints import HintStore

        self._persist = HintStore()

    @staticmethod
    def _is_aqe_key(k) -> bool:
        return (
            isinstance(k, tuple)
            and len(k) == 2
            and k[0] in ("aqe", "aqe_deny")
        )

    def load_once(self) -> int:
        """Merge persisted strategies under in-memory ones (first call
        does the file read; later calls are free). The hint file is
        SHARED with the executor plan caches — every foreign key family
        (join flags, capacities) is pruned after the load: keeping a
        stale snapshot here would write it back on the next save with
        in-memory-wins semantics, rolling back whatever the real owner
        persisted since (merge-under preserves on-disk keys we simply
        don't carry)."""
        with self._lock:
            hint, cache = self._hint, self._cache
        n = self._persist.load_once(hint, cache)
        with self._lock:
            for k in [k for k in self._cache if not self._is_aqe_key(k)]:
                del self._cache[k]
            self._hint.clear()
        return n

    def get(self, query_class: str) -> tuple:
        """Learned specs for one class, deterministic order."""
        if query_class in ("", "unknown", "overflow"):
            return ()
        with self._lock:
            specs = self._cache.get(("aqe", query_class), ())
        return tuple(sorted(specs))

    @staticmethod
    def _family(kind: str) -> str:
        # split, coalesce, and the nosplit tombstone are ONE family:
        # learning one must drop the others for the same stage, or a
        # later coalesce would silently undo an earlier skew split (and
        # a tombstone must retire the split it reverts)
        return (
            "buckets" if kind in ("split", "coalesce", "nosplit") else kind
        )

    def learn(self, query_class: str, spec: tuple) -> bool:
        """Add one spec (replacing any same-family spec for the same
        stage — a re-observed skew overwrites the previous split target
        rather than stacking). Returns True when the set changed.
        Denied (certificate-rejected) families never re-learn: without
        the deny ledger every submission would re-observe the same
        signal, re-learn the same strategy, and re-reject it — an
        endless propose/reject churn instead of a settled class."""
        if query_class in ("", "unknown", "overflow"):
            return False
        if self.is_denied(query_class, spec[0], spec[1]):
            return False
        key = ("aqe", query_class)
        with self._lock:
            current = tuple(self._cache.get(key, ()))
            kept = tuple(
                s for s in current
                if (self._family(s[0]), s[1])
                != (self._family(spec[0]), spec[1])
            )
            new = tuple(sorted(kept + (spec,)))
            if new == current:
                return False
            self._cache[key] = new
        self._save()
        return True

    def unlearn(self, query_class: str, spec: tuple) -> bool:
        key = ("aqe", query_class)
        with self._lock:
            current = tuple(self._cache.get(key, ()))
            new = tuple(s for s in current if s != spec)
            if new == current:
                return False
            # keep the (possibly empty) entry rather than popping it:
            # HintStore's save merges UNDER the on-disk file (in-memory
            # entries win per key, absent keys are preserved), so a
            # deletion only persists as an overriding empty value
            self._cache[key] = new
        self._save()
        return True

    def deny(self, query_class: str, kind: str, stage_id: int) -> None:
        """Record a STRUCTURAL certificate rejection of a (family,
        stage) strategy for this class: the spec is unlearned by the
        caller and this ledger stops the observe-side rules from
        re-learning it. Callers only deny on clauses determined by the
        plan shape alone (``_STRUCTURAL_CLAUSES`` — those fail every
        time for the class), so denial is permanent and persisted
        beside the strategies; config-dependent rejections merely
        unlearn."""
        if query_class in ("", "unknown", "overflow"):
            return
        key = ("aqe_deny", query_class)
        entry = (self._family(kind), int(stage_id))
        with self._lock:
            current = tuple(self._cache.get(key, ()))
            if entry in current:
                return
            self._cache[key] = tuple(sorted(current + (entry,)))
        self._save()

    def is_denied(self, query_class: str, kind: str, stage_id: int) -> bool:
        with self._lock:
            denied = self._cache.get(("aqe_deny", query_class), ())
        return (self._family(kind), int(stage_id)) in denied

    def _save(self) -> None:
        # take the dict REFS under our lock, write outside it: HintStore
        # serializes + does file IO under its OWN lock (and snapshots
        # the dict against concurrent resize), and holding ours across
        # that would be blocking-under-lock. This runs on the scheduler
        # event-loop thread, but only when a strategy set actually
        # CHANGED (learn/unlearn/deny call it on change only, and
        # save_if_changed fingerprint-debounces besides) — a class
        # learns a handful of times and then settles, so steady state
        # does zero IO here.
        with self._lock:
            hint, cache = self._hint, self._cache
        self._persist.save_if_changed(hint, cache)

    def classes(self) -> list[str]:
        with self._lock:
            return sorted(
                k[1] for k, v in self._cache.items()
                if isinstance(k, tuple) and len(k) == 2
                and k[0] == "aqe" and v
            )


_STORE: StrategyStore | None = None
_STORE_LOCK = make_lock("aqe._STORE_LOCK")


def strategy_store() -> StrategyStore:
    """The process-wide store (schedulers in one process — standalone
    clusters, tests — share learned strategies, exactly like the
    compile caches they ride beside)."""
    global _STORE
    with _STORE_LOCK:
        if _STORE is None:
            _STORE = StrategyStore()
        return _STORE


def reset_store() -> None:
    """Drop the process store (tests; a fresh store re-reads the hint
    file on its next load_once)."""
    global _STORE
    with _STORE_LOCK:
        _STORE = None


def spec_describe(spec: tuple) -> str:
    kind = spec[0]
    if kind in ("flip", "broadcast"):
        return f"{kind}(stage={spec[1]}, occurrence={spec[2]})"
    if kind == "nosplit":
        return f"nosplit(stage={spec[1]})"
    return f"{kind}(stage={spec[1]}, n={spec[2]})"


def _op_from_spec(spec: tuple):
    from ballista_tpu import rewrite as rw

    kind = spec[0]
    if kind == "flip":
        return rw.FlipJoinBuildSide(int(spec[1]), int(spec[2]))
    if kind == "broadcast":
        return rw.SwitchToBroadcast(int(spec[1]), int(spec[2]))
    if kind == "coalesce":
        return rw.CoalesceShufflePartitions(int(spec[1]), int(spec[2]))
    if kind == "split":
        return rw.SplitShufflePartitions(int(spec[1]), int(spec[2]))
    raise RewriteRejected(
        f"unknown learned strategy kind {kind!r}", clause="op-applicability"
    )


# ---------------------------------------------------------------------------
# runtime-stats gathering
# ---------------------------------------------------------------------------


def producer_stats(server, job_id: str, consumer_plan) -> dict:
    """Observed output of every completed producer a consumer stage
    reads: ``{producer_stage_id: {"rows", "bytes",
    "buckets": {bucket: (rows, bytes)}}}`` summed from the committed
    shuffle-write metas (exact counts — the executors measured them)."""
    from ballista_tpu.distributed_plan import find_unresolved_shuffles

    out: dict[int, dict] = {}
    for u in sorted(
        find_unresolved_shuffles(consumer_plan), key=lambda u: u.stage_id
    ):
        if u.stage_id in out:
            continue
        buckets: dict[int, tuple[int, int]] = {}
        rows = nbytes = 0
        for _task_idx, _eid, metas in server.stage_manager.completed_partitions(
            job_id, u.stage_id
        ):
            for m in metas:
                r, b = buckets.get(m.partition_id, (0, 0))
                buckets[m.partition_id] = (r + m.num_rows, b + m.num_bytes)
                rows += m.num_rows
                nbytes += m.num_bytes
        out[u.stage_id] = {"rows": rows, "bytes": nbytes, "buckets": buckets}
    return out


def estimate_subtree_bytes(node, observed: dict[int, dict]) -> int | None:
    """Rough byte estimate of a plan subtree from what is knowable
    before it runs: stage reads use their producer's OBSERVED output
    bytes, in-memory scans their Arrow table size, file scans their
    on-disk size; operators pass through the sum of their inputs (an
    upper-ish bound — filters/aggregates only shrink). ``None`` when any
    leaf is unknowable: a wrong estimate must disable the decision, not
    mis-steer it."""
    from ballista_tpu.distributed_plan import UnresolvedShuffleExec

    if isinstance(node, UnresolvedShuffleExec):
        stats = observed.get(node.stage_id)
        return None if stats is None else int(stats["bytes"])
    table = getattr(node, "table", None)
    if table is not None and hasattr(table, "nbytes") and not node.children():
        return int(table.nbytes)
    paths = getattr(node, "paths", None) or (
        [node.path] if getattr(node, "path", None) else None
    )
    if paths and not node.children():
        try:
            return sum(os.path.getsize(p) for p in paths)
        except OSError:
            return None
    if not node.children():
        return None
    total = 0
    for c in node.children():
        est = estimate_subtree_bytes(c, observed)
        if est is None:
            return None
        total += est
    return total


def keyed_bucket_totals(
    job, stats: dict
) -> tuple[dict[int, tuple[int, int]], int]:
    """Per-bucket ``(rows, bytes)`` summed across the KEYED producers in
    ``stats`` (the hash buckets a consumer's tasks each read), plus the
    keyed-producer count. Unkeyed (collect/coalesce) producers are
    excluded — their single output is not a hash bucket."""
    buckets: dict[int, tuple[int, int]] = {}
    keyed = 0
    for sid in sorted(stats):
        stage = job.stages.get(sid)
        if stage is None or not getattr(stage.plan, "partition_keys", None):
            continue
        keyed += 1
        for b in sorted(stats[sid]["buckets"]):
            r0, b0 = buckets.get(b, (0, 0))
            r, nb = stats[sid]["buckets"][b]
            buckets[b] = (r0 + r, b0 + nb)
    return buckets, keyed


# ---------------------------------------------------------------------------
# decision rules (pure — unit-testable without a scheduler)
# ---------------------------------------------------------------------------


def decide_bucket_strategy(
    buckets: dict[int, tuple[int, int]],
    current_n: int,
    skew_ratio: float,
    skew_min_rows: int,
    target_partition_mb: int,
) -> tuple | None:
    """Split-vs-coalesce over one consumer's observed input buckets.

    Skew first: a bucket whose rows exceed ``skew_ratio`` x the bucket
    median (above the noise floor) wants MORE buckets — grow by the
    observed imbalance (bounded). Otherwise, when the whole input would
    fit in fewer ``target_partition_mb`` buckets, shrink to that ideal —
    fuller buckets amortize per-task costs. Balanced, right-sized input
    decides nothing."""
    import statistics

    if current_n < 1 or len(buckets) < 2:
        return None
    rows = [buckets.get(i, (0, 0))[0] for i in range(current_n)]
    nbytes = sum(buckets.get(i, (0, 0))[1] for i in range(current_n))
    med = statistics.median(rows)
    peak = max(rows)
    if skew_ratio > 0 and med > 0 and peak >= skew_min_rows and (
        peak > skew_ratio * med
    ):
        factor = min(SPLIT_MAX_FACTOR, max(2, int(peak // max(1, med))))
        new_n = min(SPLIT_BUCKET_CAP, current_n * factor)
        if new_n > current_n:
            return ("split", new_n)
        return None
    if target_partition_mb > 0:
        ideal = max(1, -(-nbytes // (target_partition_mb * MB)))
        if ideal < current_n:
            return ("coalesce", ideal)
    return None


def find_collect_joins(plan) -> list[tuple[int, object]]:
    """``(occurrence, node)`` for collect-mode INNER hash joins, with
    occurrence counted over ALL hash joins in preorder — the exact
    addressing :class:`rewrite.FlipJoinBuildSide` resolves."""
    from ballista_tpu.exec.joins import HashJoinExec
    from ballista_tpu.plan.logical import JoinType
    from ballista_tpu.rewrite import find_nodes

    out = []
    for i, j in enumerate(
        find_nodes(plan, lambda p: isinstance(p, HashJoinExec))
    ):
        if j.join_type == JoinType.INNER and j.partition_mode == "collect":
            out.append((i, j))
    return out


def find_partitioned_joins(plan) -> list[tuple[int, object]]:
    """``(occurrence, node)`` with occurrence counted over PARTITIONED
    hash joins only — :class:`rewrite.SwitchToBroadcast` addressing."""
    from ballista_tpu.exec.joins import HashJoinExec
    from ballista_tpu.rewrite import find_nodes

    return list(
        enumerate(
            find_nodes(
                plan,
                lambda p: isinstance(p, HashJoinExec)
                and p.partition_mode == "partitioned",
            )
        )
    )


# ---------------------------------------------------------------------------
# the policy engine
# ---------------------------------------------------------------------------


class AqePolicy:
    """Decision engine bound to one :class:`SchedulerServer`.

    Hooks (all exception-guarded by the caller — adaptation must never
    outrank the scheduling it advises):

    - ``on_job_submitted(job)`` — right after stage generation: apply
      this class's learned strategies while every stage is pending.
    - ``on_stage_finished(job, stage_id, ready)`` — before promotion of
      the ``ready`` consumers: reactive flip + learn bucket/broadcast
      strategies from the completed producers' exact output stats.
    - ``on_job_finished(job)`` — learn build-side flips from the shipped
      per-operator metrics (the only place an INLINE probe side's true
      size is measured)."""

    def __init__(self, server) -> None:
        self.server = server
        self.store = strategy_store()

    # -- shared plumbing -----------------------------------------------------
    def _cfg(self, job):
        return self.server._session_config(job.session_id)

    def _record(
        self,
        job,
        kind: str,
        outcome: str,
        stage_ids: tuple,
        *,
        clause: str = "",
        source: str = "",
        before: dict | None = None,
        after: dict | None = None,
        detail: str = "",
    ) -> None:
        self.server.record_aqe_decision(
            job,
            {
                "op": kind,
                "outcome": outcome,  # applied | rejected | learned
                "stage_ids": sorted(int(s) for s in stage_ids),
                "clause": clause,
                "source": source,  # reactive | learned
                "before": dict(before or {}),
                "after": dict(after or {}),
                "detail": detail,
            },
        )

    def _apply(
        self,
        job,
        kind: str,
        op,
        spec: tuple | None,
        source: str,
        before: dict,
        after: dict,
    ) -> bool:
        """One adaptation through the sanctioned gate. Returns True when
        the rewrite was ACCEPTED; a rejection records the failing clause
        and (for a learned strategy whose certificate genuinely failed)
        unlearns the spec so it cannot reject forever."""
        try:
            cert = self.server.apply_certified_rewrite(job.job_id, op)
        except RewriteRejected as e:
            self._record(
                job, kind, "rejected", e.stage_ids or (),
                clause=e.clause, source=source, before=before, after=after,
                detail=str(e),
            )
            if spec is not None and e.clause not in _TRANSIENT_CLAUSES:
                self.store.unlearn(job.query_class, spec)
                if e.clause in _STRUCTURAL_CLAUSES:
                    self.store.deny(job.query_class, spec[0], spec[1])
                log.warning(
                    "aqe: unlearned%s %s for class %s (%s)",
                    "+denied" if e.clause in _STRUCTURAL_CLAUSES else "",
                    spec_describe(spec), job.query_class, e.clause,
                )
            return False
        except Exception:  # noqa: BLE001 — policy failure must never
            # fail the job it advises
            log.exception("aqe: rewrite application failed for %s", kind)
            return False
        self._record(
            job, kind, "applied",
            cert.rewritten_stages + cert.added_stages,
            source=source, before=before, after=after,
            detail=cert.summary(),
        )
        return True

    # -- submission: learned strategies --------------------------------------
    def wants_to_adapt(self, job) -> bool:
        """True when this class has applicable learned strategies — the
        scheduler then submits leaf stages PENDING-first so a polling
        executor cannot claim a task in the submission/rewrite gap and
        spuriously close the rewrite window (runtime-state)."""
        if not enabled(self._cfg(job)):
            return False
        self.store.load_once()
        return any(
            sp[0] != "nosplit" for sp in self.store.get(job.query_class)
        )

    def on_job_submitted(self, job) -> None:
        cfg = self._cfg(job)
        if not enabled(cfg):
            return
        self.store.load_once()
        for spec in self.store.get(job.query_class):
            if spec[0] == "nosplit":
                # a tombstone, not an op: "splitting stage N did not
                # shrink its hot bucket — stop re-proposing it"
                continue
            try:
                op = _op_from_spec(spec)
            except RewriteRejected as e:
                self._record(
                    job, spec[0], "rejected", (spec[1],),
                    clause=e.clause, source="learned", detail=str(e),
                )
                self.store.unlearn(job.query_class, spec)
                continue
            self._apply(
                job, spec[0], op, spec, "learned",
                {"strategy": spec_describe(spec)}, {},
            )

    # -- StageFinished: reactive + learning ----------------------------------
    def on_stage_finished(
        self, job, stage_id: int, ready_stats: dict[int, dict]
    ) -> None:
        """``ready_stats``: pending consumer stage id -> that consumer's
        :func:`producer_stats`, for the consumers whose producers are
        all complete — the stages the caller is about to promote (the
        caller computed the stats once and shares them with the skew
        pass)."""
        cfg = self._cfg(job)
        if not enabled(cfg):
            return
        for consumer_id in sorted(ready_stats):
            with self.server._lock:
                stage = job.stages.get(consumer_id)
                plan = stage.plan if stage is not None else None
            if plan is None:
                continue
            stats = ready_stats[consumer_id]
            self._maybe_flip(job, consumer_id, plan, stats, cfg)
            self._learn_buckets(job, consumer_id, plan, stats, cfg)
            self._learn_broadcast(job, consumer_id, plan, stats, cfg)

    def _maybe_flip(self, job, consumer_id, plan, stats, cfg) -> None:
        """Reactive build-side flip: the ONLY rewrite whose touched set
        is exactly the still-pending consumer, so it can apply mid-job.
        Compares the OBSERVED build-producer output against the probe
        side (observed when it is a stage read, estimated from
        scan/table sizes otherwise — estimation uses a wider hysteresis
        factor)."""
        from ballista_tpu.distributed_plan import UnresolvedShuffleExec

        applied_any = False
        for occurrence, join in find_collect_joins(plan):
            if applied_any:
                # one flip re-shapes the plan; re-decide on the next
                # signal rather than stacking occurrences on a stale tree
                break
            build = join.right
            if not isinstance(build, UnresolvedShuffleExec):
                continue
            bstats = stats.get(build.stage_id)
            if bstats is None or bstats["bytes"] < FLIP_MIN_BUILD_BYTES:
                continue
            build_bytes = bstats["bytes"]
            if isinstance(join.left, UnresolvedShuffleExec):
                pstats = stats.get(join.left.stage_id)
                probe_bytes = None if pstats is None else pstats["bytes"]
                factor = FLIP_FACTOR
            else:
                probe_bytes = estimate_subtree_bytes(join.left, stats)
                factor = FLIP_EST_FACTOR
            if probe_bytes is None or build_bytes <= factor * probe_bytes:
                continue
            from ballista_tpu import rewrite as rw

            before = {
                "build_bytes": int(build_bytes),
                "probe_bytes": int(probe_bytes),
            }
            after = {
                "build_bytes": int(probe_bytes),
                "probe_bytes": int(build_bytes),
            }
            # remember the misestimate either way: the next submission
            # of this class flips at planning time
            spec = ("flip", consumer_id, occurrence)
            learned_now = self.store.learn(job.query_class, spec)
            if not self.server.stage_manager.all_tasks_pending(
                job.job_id, consumer_id
            ):
                # eager-shuffle handout already started this pending
                # stage's tasks — the mid-job rewrite window is closed
                # (rebind would reject on runtime-state), so defer to
                # the learned strategy instead of burning a certify
                if learned_now:
                    self._record(
                        job, "flip", "learned", (consumer_id,),
                        source="reactive", before=before, after=after,
                        detail="rewrite window closed by eager tasks; "
                        f"learned for class={job.query_class}",
                    )
                continue
            op = rw.FlipJoinBuildSide(consumer_id, occurrence)
            applied_any = self._apply(
                job, "flip", op, spec, "reactive", before, after,
            )

    def _learn_buckets(self, job, consumer_id, plan, stats, cfg) -> None:
        """Split/coalesce decisions over the consumer's observed input
        buckets. These re-bucket producers that JUST completed, so they
        cannot apply mid-job (the pending-stages precondition would —
        correctly — reject them); they are learned for the next
        submission of this query class."""
        with self.server._lock:
            buckets, keyed = keyed_bucket_totals(job, stats)
        if not keyed:
            return
        with self.server._lock:
            stage = job.stages.get(consumer_id)
            current_n = (
                stage.input_partition_count if stage is not None else 0
            )
        prior = next(
            (
                s for s in self.store.get(job.query_class)
                if StrategyStore._family(s[0]) == "buckets"
                and s[1] == consumer_id
            ),
            None,
        )
        if prior is not None and prior[0] == "nosplit":
            return
        decision = decide_bucket_strategy(
            buckets,
            current_n,
            cfg.skew_ratio(),
            cfg.skew_min_rows(),
            cfg.aqe_target_partition_mb(),
        )
        peak = max(
            buckets.get(i, (0, 0))[0] for i in range(max(1, current_n))
        )
        if prior is not None and prior[0] == "split" and (
            current_n >= prior[2]
        ):
            # the plan ran AT our learned split count: judge it, never
            # escalate. Escalation chases an asymptote — a hot bucket
            # that is ONE irreducible key keeps tripping the ratio at
            # any count (same hash -> same bucket), and even a genuine
            # rebalance keeps the top key's mass in one bucket — so the
            # split either HELPED (hot bucket shrank: freeze it exactly
            # as learned) or it didn't (revert and tombstone so the
            # class settles instead of oscillating relearn/revert).
            prev_peak = prior[3] if len(prior) > 3 else 0
            if decision is not None and decision[0] == "split" and (
                not prev_peak or peak >= 0.8 * prev_peak
            ):
                self.store.learn(
                    job.query_class, ("nosplit", consumer_id, 0)
                )
                self._record(
                    job, "split", "reverted", (consumer_id,),
                    source="reactive",
                    before={"buckets": current_n, "max_rows": int(peak)},
                    after={"max_rows_at_fewer_buckets": int(prev_peak)},
                    detail="split did not shrink the hot bucket "
                    "(irreducible hot key); tombstoned for this class",
                )
            return
        if decision is None:
            return
        kind, new_n = decision
        spec = (
            (kind, consumer_id, new_n, int(peak))
            if kind == "split"
            else (kind, consumer_id, new_n)
        )
        if self.store.learn(job.query_class, spec):
            rows = [buckets.get(i, (0, 0))[0] for i in range(current_n)]
            self._record(
                job, kind, "learned", (consumer_id,), source="reactive",
                before={
                    "buckets": current_n,
                    "max_rows": max(rows) if rows else 0,
                    "total_bytes": sum(
                        buckets.get(i, (0, 0))[1] for i in range(current_n)
                    ),
                },
                after={"buckets": new_n},
                detail=f"class={job.query_class}",
            )

    def _learn_broadcast(self, job, consumer_id, plan, stats, cfg) -> None:
        """A partitioned join whose build side measured under the
        broadcast threshold re-plans collect-mode next run — the build
        producer writes ONE partition every probe task collects whole,
        instead of hash-scattering both sides."""
        from ballista_tpu.distributed_plan import UnresolvedShuffleExec

        threshold = cfg.aqe_broadcast_threshold_mb() * MB
        if threshold <= 0:
            return
        for occurrence, join in find_partitioned_joins(plan):
            build = join.right
            if not isinstance(build, UnresolvedShuffleExec):
                continue
            bstats = stats.get(build.stage_id)
            if bstats is None or not (0 < bstats["bytes"] < threshold):
                continue
            spec = ("broadcast", consumer_id, occurrence)
            if self.store.learn(job.query_class, spec):
                self._record(
                    job, "broadcast", "learned", (consumer_id,),
                    source="reactive",
                    before={"build_bytes": int(bstats["bytes"])},
                    after={"threshold_bytes": int(threshold)},
                    detail=f"class={job.query_class}",
                )

    # -- job completion: learn flips needing executed-operator metrics -------
    def on_job_finished(self, job) -> None:
        """Collect-join flips whose probe side ran INLINE (a scan
        subtree) can only be sized from the shipped per-operator metrics
        — compare each collect join's measured child outputs and learn
        the flip when the build side was the larger one. The plans in
        ``job.stages`` are the templates that actually RAN (any accepted
        rewrite already swapped them), so a flipped join measures
        build < probe and learns nothing — no flip-flopping."""
        cfg = self._cfg(job)
        if not enabled(cfg):
            return
        from ballista_tpu.obs.profile import walk_paths

        with self.server._lock:
            stages = {
                sid: s.plan for sid, s in sorted(job.stages.items())
            }
            op_metrics = dict(job.op_metrics)
        # measured ROWS per (stage, operator path), summed across the
        # stage's partitions. Rows, not the shipped output_bytes: those
        # meter capacity-PADDED device residency (a 100-row dimension
        # batch padded to a 2M-row capacity reads as gigabytes), which
        # at small scale flagged flips backwards on every TPC-H join
        by_path: dict[tuple[int, str], float] = {}
        parts_of: dict[int, set] = {}
        for (sid, part), records in sorted(op_metrics.items()):
            parts_of.setdefault(sid, set()).add(part)
            for r in records:
                v = r.get("counters", {}).get("output_rows")
                if isinstance(v, (int, float)):
                    key = (sid, r["path"])
                    by_path[key] = by_path.get(key, 0) + float(v)
        if not by_path:
            return
        # per-TASK means, not cross-task sums: a collect join's build
        # reader re-reads the whole collected side in EVERY task, so a
        # 4-task stage reports 4x the build rows — comparing sums would
        # inflate build-vs-probe by the task count
        for (sid, path) in list(by_path):
            by_path[(sid, path)] /= max(1, len(parts_of.get(sid, ())))
        for sid in sorted(stages):
            plan = stages[sid]
            join_paths = {
                id(node): path for path, node in walk_paths(plan)
            }
            for occurrence, join in find_collect_joins(plan):
                jp = join_paths.get(id(join))
                if jp is None:
                    continue
                probe = by_path.get((sid, jp + ".0"))
                build = by_path.get((sid, jp + ".1"))
                if not probe or not build:
                    continue
                if build < FLIP_MIN_BUILD_ROWS or (
                    build <= FLIP_FACTOR * probe
                ):
                    continue
                spec = ("flip", sid, occurrence)
                if self.store.learn(job.query_class, spec):
                    self._record(
                        job, "flip", "learned", (sid,), source="reactive",
                        before={
                            "build_rows": int(build),
                            "probe_rows": int(probe),
                        },
                        after={},
                        detail=f"class={job.query_class}",
                    )


# ---------------------------------------------------------------------------
# EXPLAIN ANALYZE narration
# ---------------------------------------------------------------------------


def narrate(ctx, optimized) -> str:
    """One EXPLAIN ANALYZE line: the query's distributed class token,
    whether AQE would act on it, and the learned strategies a submission
    would apply (docs/aqe.md). Never raises — narration is advisory."""
    try:
        state = "on" if enabled(ctx.config) else "off"
        store = strategy_store()
        store.load_once()
        if state == "off" and not store.classes():
            # the class token needs a full distributed planning pass;
            # don't pay it on a profiling verb when AQE is off and this
            # process has learned nothing to narrate
            return (
                "aqe=off: no learned strategies in this process (enable "
                "ballista.tpu.aqe to adapt; the distributed query class "
                "is computed when AQE is on or strategies exist)"
            )
        from ballista_tpu.exec.planner import PhysicalPlanner
        from ballista_tpu.obs.qclass import plan_class

        phys = PhysicalPlanner(
            ctx,
            ctx.config.default_shuffle_partitions(),
            config=ctx.config,
            distributed=True,
        ).plan(optimized)
        qclass = plan_class(phys)
        specs = store.get(qclass)
        if not specs:
            return (
                f"aqe={state} class={qclass}: no learned strategies "
                "(first run observes; later runs adapt from submission)"
            )
        return (
            f"aqe={state} class={qclass}: would apply "
            + "; ".join(spec_describe(s) for s in specs)
        )
    except Exception as e:  # noqa: BLE001 — a profiling verb must not
        # die on its narration
        log.debug("aqe narration failed", exc_info=True)
        return f"aqe: narration unavailable ({type(e).__name__}: {e})"
