"""Pluggable KV state backends for scheduler persistence.

Mirrors the reference's ``StateBackendClient`` trait (ref
ballista/rust/scheduler/src/state/backend/mod.rs:53-94: get,
get_from_prefix, put, lock, watch) with two implementations standing in
for the reference's sled (backend/standalone.rs:31-180) and etcd
(backend/etcd.rs:32-196):

- :class:`MemoryBackend` — in-process dict (tests / ephemeral schedulers);
- :class:`SqliteBackend` — a file-backed store, the embedded-DB analogue
  of sled in this Python runtime (sqlite ships in the stdlib and gives
  the same durability contract: survive a scheduler restart on one node).

Keys follow the reference's scheme: ``/ballista/<namespace>/...``
(persistent_state.rs:326-352).
"""

from __future__ import annotations

import dataclasses
import queue
import sqlite3
from typing import Iterator

from ballista_tpu.analysis.witness import make_lock


@dataclasses.dataclass(frozen=True)
class WatchEvent:
    """One observed mutation (ref backend/mod.rs:96-104 WatchEvent::Put /
    Delete)."""

    kind: str  # "put" | "delete"
    key: str
    value: bytes | None  # None for deletes


class Watch:
    """A live subscription to key mutations under a prefix (ref
    backend/mod.rs:84-94 ``watch`` returning a Stream of WatchEvents).
    Iterate for events; ``stop()`` ends the stream. Trigger-based: events
    fire from this process's put/delete calls — the same visibility the
    reference's sled-backed standalone watch has (cross-process watch is
    etcd's job; see docs/deployment.md HA notes)."""

    _STOP = object()

    def __init__(self, prefix: str, unsubscribe) -> None:
        self.prefix = prefix
        self._q: queue.Queue = queue.Queue()
        self._unsubscribe = unsubscribe
        self._stopped = False

    def _offer(self, event: WatchEvent) -> None:
        self._q.put(event)

    def stop(self) -> None:
        if not self._stopped:
            self._stopped = True
            self._unsubscribe(self)
            self._q.put(self._STOP)

    def __iter__(self) -> "Watch":
        return self

    def __next__(self) -> WatchEvent:
        item = self._q.get()
        if item is self._STOP:
            raise StopIteration
        return item

    def get(self, timeout: float | None = None) -> WatchEvent | None:
        """Non-raising fetch: the next event, or None on timeout/stop."""
        try:
            item = self._q.get(timeout=timeout)
        except queue.Empty:
            return None
        if item is self._STOP:
            self._q.put(self._STOP)  # keep the sentinel for iterators
            return None
        return item


class StateBackendClient:
    """KV-store interface (ref backend/mod.rs:53-94: get, get_from_prefix,
    put, lock, watch)."""

    def __init__(self) -> None:
        self._watchers: list[Watch] = []
        self._watch_lock = make_lock("StateBackendClient._watch_lock")

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def get_from_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        raise NotImplementedError

    def put(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def lock(self):
        """Global scheduler lock (ref etcd.rs:85 `/ballista_global_lock`,
        persistent_state.rs:313-319 global lock around each save)."""
        raise NotImplementedError

    def watch(self, prefix: str) -> Watch:
        """Subscribe to mutations under ``prefix``."""
        w = Watch(prefix, self._unwatch)
        with self._watch_lock:
            self._watchers.append(w)
        return w

    def _unwatch(self, w: Watch) -> None:
        with self._watch_lock:
            if w in self._watchers:
                self._watchers.remove(w)

    def _notify(self, kind: str, key: str, value: bytes | None) -> None:
        with self._watch_lock:
            watchers = list(self._watchers)
        for w in watchers:
            if key.startswith(w.prefix):
                w._offer(WatchEvent(kind, key, value))

    def close(self) -> None:
        with self._watch_lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.stop()


class MemoryBackend(StateBackendClient):
    def __init__(self) -> None:
        super().__init__()
        self._data: dict[str, bytes] = {}
        self._lock = make_lock("MemoryBackend._lock", reentrant=True)

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def get_from_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )

    def put(self, key: str, value: bytes) -> None:
        v = bytes(value)
        with self._lock:
            self._data[key] = v
            # notify under the data lock: watchers must observe events in
            # the order the writes were applied
            self._notify("put", key, v)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)
            self._notify("delete", key, None)

    def lock(self):
        return self._lock


class SqliteBackend(StateBackendClient):
    """File-backed KV store (the sled analogue, ref
    backend/standalone.rs:31-180). One table, BLOB values, WAL mode so a
    crashed scheduler's last committed writes survive."""

    def __init__(self, path: str) -> None:
        super().__init__()
        self.path = path
        self._lock = make_lock("SqliteBackend._lock", reentrant=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv ("
                "key TEXT PRIMARY KEY, value BLOB NOT NULL)"
            )
            self._conn.commit()

    def get(self, key: str) -> bytes | None:
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE key = ?", (key,)
            ).fetchone()
        return None if row is None else bytes(row[0])

    def get_from_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        with self._lock:
            rows = self._conn.execute(
                "SELECT key, value FROM kv WHERE key >= ? AND key < ? "
                "ORDER BY key",
                (prefix, prefix + "￿"),
            ).fetchall()
        return [(k, bytes(v)) for k, v in rows]

    def put(self, key: str, value: bytes) -> None:
        v = bytes(value)
        with self._lock:
            self._conn.execute(
                "INSERT INTO kv (key, value) VALUES (?, ?) "
                "ON CONFLICT(key) DO UPDATE SET value = excluded.value",
                (key, sqlite3.Binary(v)),
            )
            self._conn.commit()
            self._notify("put", key, v)

    def delete(self, key: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE key = ?", (key,))
            self._conn.commit()
            self._notify("delete", key, None)

    def lock(self):
        return self._lock

    def close(self) -> None:
        super().close()
        with self._lock:
            self._conn.close()
