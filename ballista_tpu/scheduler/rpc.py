"""Hand-written gRPC plumbing for the two services.

The build image has protoc but not the grpc_tools python plugin, so the
method registry that generated *_pb2_grpc.py files would contain is written
out here explicitly (same wire format, same service/method names as
proto/ballista_tpu.proto services — ref ballista.proto:917-940).
"""

from __future__ import annotations

import os

import grpc

from ballista_tpu.proto import pb

SCHEDULER_SERVICE = "ballista_tpu.SchedulerGrpc"
EXECUTOR_SERVICE = "ballista_tpu.ExecutorGrpc"


def rpc_timeout_s() -> float:
    """Default per-call deadline for client stubs built here (and the
    etcd unary calls — scheduler/etcd_backend.py). Unbounded RPCs are
    how a hung peer wedges the control plane: every unary call gets
    this deadline unless the caller passes an explicit ``timeout=``.
    0 (or negative) disables the default, restoring unbounded calls."""
    raw = os.environ.get("BALLISTA_RPC_TIMEOUT_S", "") or "30"
    try:
        return float(raw)
    except ValueError:
        return 30.0

SCHEDULER_METHODS = {
    "PollWork": (pb.PollWorkParams, pb.PollWorkResult),
    "RegisterExecutor": (pb.RegisterExecutorParams, pb.RegisterExecutorResult),
    "HeartBeatFromExecutor": (pb.HeartBeatParams, pb.HeartBeatResult),
    "UpdateTaskStatus": (pb.UpdateTaskStatusParams, pb.UpdateTaskStatusResult),
    "GetFileMetadata": (pb.GetFileMetadataParams, pb.GetFileMetadataResult),
    "ExecuteQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "GetJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
    # eager shuffle (docs/shuffle.md): executors poll published map-output
    # locations of a still-running producer stage
    "GetShuffleLocations": (pb.FetchPartition, pb.ShuffleLocationsResult),
    # queryable history (docs/observability.md): clients fetch the
    # persistent query log / cost records / executor roster backing the
    # system.* SQL tables
    "GetHistory": (pb.GetHistoryParams, pb.GetHistoryResult),
}

EXECUTOR_METHODS = {
    "LaunchTask": (pb.LaunchTaskParams, pb.LaunchTaskResult),
    "StopExecutor": (pb.StopExecutorParams, pb.StopExecutorResult),
}


def add_service(server: grpc.Server, service: str, methods: dict, impl) -> None:
    handlers = {}
    for name, (req, resp) in methods.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(impl, name),
            request_deserializer=req.FromString,
            response_serializer=lambda r: r.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service, handlers),)
    )


def _with_deadline(call):
    """Apply the default deadline to a unary callable unless the caller
    chose one (timeout=None explicitly requests an unbounded call)."""

    def invoke(request, *args, **kwargs):
        if args or "timeout" in kwargs:
            return call(request, *args, **kwargs)
        default = rpc_timeout_s()
        if default > 0:
            kwargs["timeout"] = default
        return call(request, **kwargs)

    return invoke


class _Stub:
    def __init__(self, channel: grpc.Channel, service: str, methods: dict):
        for name, (req, resp) in methods.items():
            setattr(
                self,
                name,
                _with_deadline(channel.unary_unary(
                    f"/{service}/{name}",
                    request_serializer=lambda r: r.SerializeToString(),
                    response_deserializer=resp.FromString,
                )),
            )


def scheduler_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, SCHEDULER_SERVICE, SCHEDULER_METHODS)


def executor_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, EXECUTOR_SERVICE, EXECUTOR_METHODS)
