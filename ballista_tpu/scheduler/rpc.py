"""Hand-written gRPC plumbing for the two services.

The build image has protoc but not the grpc_tools python plugin, so the
method registry that generated *_pb2_grpc.py files would contain is written
out here explicitly (same wire format, same service/method names as
proto/ballista_tpu.proto services — ref ballista.proto:917-940).
"""

from __future__ import annotations

import grpc

from ballista_tpu.proto import pb

SCHEDULER_SERVICE = "ballista_tpu.SchedulerGrpc"
EXECUTOR_SERVICE = "ballista_tpu.ExecutorGrpc"

SCHEDULER_METHODS = {
    "PollWork": (pb.PollWorkParams, pb.PollWorkResult),
    "RegisterExecutor": (pb.RegisterExecutorParams, pb.RegisterExecutorResult),
    "HeartBeatFromExecutor": (pb.HeartBeatParams, pb.HeartBeatResult),
    "UpdateTaskStatus": (pb.UpdateTaskStatusParams, pb.UpdateTaskStatusResult),
    "GetFileMetadata": (pb.GetFileMetadataParams, pb.GetFileMetadataResult),
    "ExecuteQuery": (pb.ExecuteQueryParams, pb.ExecuteQueryResult),
    "GetJobStatus": (pb.GetJobStatusParams, pb.GetJobStatusResult),
    # eager shuffle (docs/shuffle.md): executors poll published map-output
    # locations of a still-running producer stage
    "GetShuffleLocations": (pb.FetchPartition, pb.ShuffleLocationsResult),
    # queryable history (docs/observability.md): clients fetch the
    # persistent query log / cost records / executor roster backing the
    # system.* SQL tables
    "GetHistory": (pb.GetHistoryParams, pb.GetHistoryResult),
}

EXECUTOR_METHODS = {
    "LaunchTask": (pb.LaunchTaskParams, pb.LaunchTaskResult),
    "StopExecutor": (pb.StopExecutorParams, pb.StopExecutorResult),
}


def add_service(server: grpc.Server, service: str, methods: dict, impl) -> None:
    handlers = {}
    for name, (req, resp) in methods.items():
        handlers[name] = grpc.unary_unary_rpc_method_handler(
            getattr(impl, name),
            request_deserializer=req.FromString,
            response_serializer=lambda r: r.SerializeToString(),
        )
    server.add_generic_rpc_handlers(
        (grpc.method_handlers_generic_handler(service, handlers),)
    )


class _Stub:
    def __init__(self, channel: grpc.Channel, service: str, methods: dict):
        for name, (req, resp) in methods.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{service}/{name}",
                    request_serializer=lambda r: r.SerializeToString(),
                    response_deserializer=resp.FromString,
                ),
            )


def scheduler_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, SCHEDULER_SERVICE, SCHEDULER_METHODS)


def executor_stub(channel: grpc.Channel) -> _Stub:
    return _Stub(channel, EXECUTOR_SERVICE, EXECUTOR_METHODS)
