"""KEDA external-scaler gRPC service.

ref ballista/rust/scheduler/src/scheduler_server/external_scaler.rs:31-66:
KEDA polls ``IsActive`` (scale 0<->1 on whether any task is running) and
``GetMetrics`` (saturate the HPA while work exists). Served under KEDA's
fixed service path ``externalscaler.ExternalScaler`` (keda.proto) so a
stock KEDA `ScaledObject` pointing at the scheduler works unchanged.
"""

from __future__ import annotations

from ballista_tpu.proto import pb
from ballista_tpu.scheduler.rpc import add_service

EXTERNAL_SCALER_SERVICE = "externalscaler.ExternalScaler"

EXTERNAL_SCALER_METHODS = {
    "IsActive": (pb.ScaledObjectRef, pb.IsActiveResponse),
    "GetMetricSpec": (pb.ScaledObjectRef, pb.GetMetricSpecResponse),
    "GetMetrics": (pb.GetMetricsRequest, pb.GetMetricsResponse),
}

# PR 12 (docs/observability.md): the scale signal is no longer the raw
# inflight count — GetMetrics reports SchedulerServer.desired_executors(),
# the composite pressure (inflight tasks over per-executor slots, scaled
# up when queue-wait p90 exceeds the declared target) also exposed as the
# ballista_desired_executors gauge. With targetSize=1 KEDA's replica math
# (metricValue / targetSize) then IS the desired executor count.
COMPOSITE_PRESSURE_METRIC_NAME = "desired_executors"
# Pre-PR-12 metric name: a GetMetrics request that explicitly asks for
# it (a ScaledObject pinning `metricName: inflight_tasks`) still gets
# the raw inflight count under that name — real back-compat, not an
# advertised default (GetMetricSpec only announces the composite).
INFLIGHT_TASKS_METRIC_NAME = "inflight_tasks"


class ExternalScalerServicer:
    """Implements KEDA's three-RPC contract over the scheduler state."""

    def __init__(self, server):
        self.s = server

    def IsActive(self, request: pb.ScaledObjectRef, context):
        # ref :34-41 checks has_running_tasks(); counting PENDING too is a
        # deliberate fix — scaled to zero, no task can ever be RUNNING, so
        # the reference's signal can never trigger the 0->1 scale-up
        return pb.IsActiveResponse(
            result=self.s.stage_manager.inflight_tasks() > 0
        )

    def GetMetricSpec(self, request: pb.ScaledObjectRef, context):
        # ref :43-53 — one metric; target 1 means metricValue is read
        # directly as the replica count
        return pb.GetMetricSpecResponse(
            metricSpecs=[
                pb.MetricSpec(
                    metricName=COMPOSITE_PRESSURE_METRIC_NAME, targetSize=1
                )
            ]
        )

    def GetMetrics(self, request: pb.GetMetricsRequest, context):
        # ref :55-66 reports a huge constant to saturate the HPA while
        # work exists; the composite pressure signal gives KEDA the
        # actual executor count the queue state asks for — including the
        # queue-wait term that raw inflight counting cannot see (jobs
        # stacking up behind few big tasks)
        if request.metricName == INFLIGHT_TASKS_METRIC_NAME:
            # back-compat: a ScaledObject still pinning the pre-PR-12
            # name keeps its raw-inflight / 1-task-per-replica semantics
            return pb.GetMetricsResponse(
                metricValues=[
                    pb.MetricValue(
                        metricName=INFLIGHT_TASKS_METRIC_NAME,
                        metricValue=self.s.stage_manager.inflight_tasks(),
                    )
                ]
            )
        return pb.GetMetricsResponse(
            metricValues=[
                pb.MetricValue(
                    metricName=COMPOSITE_PRESSURE_METRIC_NAME,
                    metricValue=self.s.desired_executors(),
                )
            ]
        )


def add_external_scaler(grpc_server, scheduler_server) -> None:
    """Attach the KEDA service to an already-running gRPC server (the
    reference multiplexes it on the scheduler's main port, main.rs:136-166)."""
    add_service(
        grpc_server,
        EXTERNAL_SCALER_SERVICE,
        EXTERNAL_SCALER_METHODS,
        ExternalScalerServicer(scheduler_server),
    )
