"""KEDA external-scaler gRPC service.

ref ballista/rust/scheduler/src/scheduler_server/external_scaler.rs:31-66:
KEDA polls ``IsActive`` (scale 0<->1 on whether any task is running) and
``GetMetrics`` (saturate the HPA while work exists). Served under KEDA's
fixed service path ``externalscaler.ExternalScaler`` (keda.proto) so a
stock KEDA `ScaledObject` pointing at the scheduler works unchanged.
"""

from __future__ import annotations

from ballista_tpu.proto import pb
from ballista_tpu.scheduler.rpc import add_service

EXTERNAL_SCALER_SERVICE = "externalscaler.ExternalScaler"

EXTERNAL_SCALER_METHODS = {
    "IsActive": (pb.ScaledObjectRef, pb.IsActiveResponse),
    "GetMetricSpec": (pb.ScaledObjectRef, pb.GetMetricSpecResponse),
    "GetMetrics": (pb.GetMetricsRequest, pb.GetMetricsResponse),
}

INFLIGHT_TASKS_METRIC_NAME = "inflight_tasks"


class ExternalScalerServicer:
    """Implements KEDA's three-RPC contract over the scheduler state."""

    def __init__(self, server):
        self.s = server

    def IsActive(self, request: pb.ScaledObjectRef, context):
        # ref :34-41 checks has_running_tasks(); counting PENDING too is a
        # deliberate fix — scaled to zero, no task can ever be RUNNING, so
        # the reference's signal can never trigger the 0->1 scale-up
        return pb.IsActiveResponse(
            result=self.s.stage_manager.inflight_tasks() > 0
        )

    def GetMetricSpec(self, request: pb.ScaledObjectRef, context):
        # ref :43-53 — one metric, target 1 task per replica
        return pb.GetMetricSpecResponse(
            metricSpecs=[
                pb.MetricSpec(
                    metricName=INFLIGHT_TASKS_METRIC_NAME, targetSize=1
                )
            ]
        )

    def GetMetrics(self, request: pb.GetMetricsRequest, context):
        # ref :55-66 reports a huge constant to saturate the HPA while work
        # exists; reporting the actual inflight count gives KEDA a real
        # signal and the same saturating behavior for large jobs
        return pb.GetMetricsResponse(
            metricValues=[
                pb.MetricValue(
                    metricName=INFLIGHT_TASKS_METRIC_NAME,
                    metricValue=self.s.stage_manager.inflight_tasks(),
                )
            ]
        )


def add_external_scaler(grpc_server, scheduler_server) -> None:
    """Attach the KEDA service to an already-running gRPC server (the
    reference multiplexes it on the scheduler's main port, main.rs:136-166)."""
    add_service(
        grpc_server,
        EXTERNAL_SCALER_SERVICE,
        EXTERNAL_SCALER_METHODS,
        ExternalScalerServicer(scheduler_server),
    )
