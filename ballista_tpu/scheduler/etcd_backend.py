"""etcd v3 state backend: the multi-scheduler / HA store.

Mirrors the reference's ``EtcdClient`` (ref
ballista/rust/scheduler/src/state/backend/etcd.rs:32-196): get/
get_from_prefix map to Range over ``[key, prefix_end)``, put to Put,
lock to the v3 Lock service under ``/ballista_global_lock`` (etcd.rs:85)
backed by a leased session, and watch to the Watch bidi stream — which,
unlike the Memory/Sqlite backends' in-process trigger, observes writes
from OTHER schedulers: that is the property that makes multi-scheduler
deployments work (docs/deployment.md "HA" runbook).

The wire protocol is the public etcd API subset in ``proto/etcd.proto``
(hand-registered method paths, same pattern as scheduler/rpc.py — the
image has protoc but no grpc_tools plugin). There is no etcd server in
this build image, so the integration test (tests/test_etcd_backend.py)
runs this client against an in-process fake speaking the same wire
protocol; against a real etcd only the endpoint changes.
"""

from __future__ import annotations

import logging
import threading

import grpc

log = logging.getLogger(__name__)

from ballista_tpu.proto import etcd_pb2 as epb
from ballista_tpu.scheduler.rpc import _with_deadline, rpc_timeout_s
from ballista_tpu.scheduler.state_backend import (
    StateBackendClient,
    Watch,
    WatchEvent,
)

GLOBAL_LOCK_NAME = b"/ballista_global_lock"  # ref etcd.rs:85
LOCK_LEASE_TTL_S = 30


def _is_ipv4_hostport(ep: str) -> bool:
    host, _, port = ep.rpartition(":")
    if not port.isdigit():
        return False
    parts = host.split(".")
    return len(parts) == 4 and all(
        p.isdigit() and int(p) < 256 for p in parts
    )


def prefix_end(prefix: bytes) -> bytes:
    """etcd range_end for "all keys with this prefix": the prefix with its
    last byte incremented (trailing 0xff bytes dropped, as etcd clients
    do); b"\\0" means "to the end of keyspace"."""
    b = bytearray(prefix)
    while b:
        if b[-1] < 0xFF:
            b[-1] += 1
            return bytes(b)
        b.pop()
    return b"\x00"


class _EtcdStub:
    """Hand-registered method paths for the etcd services used here. The
    v3lock service lives in package ``v3lockpb`` on a real etcd — the
    path is what crosses the wire, not our local message package."""

    def __init__(self, channel: grpc.Channel) -> None:
        def u(path, resp):
            # Every unary etcd call carries the default per-call deadline
            # (BALLISTA_RPC_TIMEOUT_S): an unreachable etcd member must
            # fail the call, not wedge the scheduler under its state
            # lock. The watch / lease_keep_alive STREAMS below stay
            # unbounded — their lifetime is the subscription's.
            return _with_deadline(channel.unary_unary(
                path,
                request_serializer=lambda r: r.SerializeToString(),
                response_deserializer=resp.FromString,
            ))

        self.range = u("/etcdserverpb.KV/Range", epb.RangeResponse)
        self.put = u("/etcdserverpb.KV/Put", epb.PutResponse)
        self.delete_range = u("/etcdserverpb.KV/DeleteRange",
                              epb.DeleteRangeResponse)
        self.lease_grant = u("/etcdserverpb.Lease/LeaseGrant",
                             epb.LeaseGrantResponse)
        self.lease_revoke = u("/etcdserverpb.Lease/LeaseRevoke",
                              epb.LeaseRevokeResponse)
        self.lock = u("/v3lockpb.Lock/Lock", epb.LockResponse)
        self.unlock = u("/v3lockpb.Lock/Unlock", epb.UnlockResponse)
        self.watch = channel.stream_stream(
            "/etcdserverpb.Watch/Watch",
            request_serializer=lambda r: r.SerializeToString(),
            response_deserializer=epb.WatchResponse.FromString,
        )
        self.lease_keep_alive = channel.stream_stream(
            "/etcdserverpb.Lease/LeaseKeepAlive",
            request_serializer=lambda r: r.SerializeToString(),
            response_deserializer=epb.LeaseKeepAliveResponse.FromString,
        )


class _EtcdLock:
    """Context manager over the v3 Lock service: a leased session + Lock
    on entry, Unlock + lease revoke on exit (a crashed holder's lock
    self-releases when the lease TTL expires — the distributed analogue
    of the reference dropping its etcd lock guard, etcd.rs:85-101)."""

    def __init__(self, stub: _EtcdStub) -> None:
        self._stub = stub
        self._key: bytes | None = None
        self._lease = 0
        self._ka_stop: threading.Event | None = None
        self._ka_call = None

    def _start_keepalive(self) -> None:
        """Refresh the lease while the lock is held — a critical section
        longer than the TTL must NOT let the lock self-release under us
        (the TTL exists only so a CRASHED holder frees it)."""
        stop = self._ka_stop = threading.Event()
        lease = self._lease

        def requests():
            while not stop.wait(LOCK_LEASE_TTL_S / 3):
                yield epb.LeaseKeepAliveRequest(ID=lease)

        try:
            call = self._ka_call = self._stub.lease_keep_alive(requests())

            def drain():
                try:
                    for _ in call:
                        pass
                except grpc.RpcError:
                    pass  # holder exit cancels the stream

            # exits when __exit__ cancels the keepalive stream: the
            # thread's lifetime IS the stream's
            threading.Thread(  # lifelint: transfer=stream-bounded
                target=drain, daemon=True,
                name="etcd-lock-keepalive").start()
        except grpc.RpcError:
            log.warning("etcd lease keepalive unavailable; lock relies on "
                        "TTL=%ss outliving the critical section",
                        LOCK_LEASE_TTL_S)

    def __enter__(self) -> "_EtcdLock":
        self._lease = self._stub.lease_grant(
            epb.LeaseGrantRequest(TTL=LOCK_LEASE_TTL_S)
        ).ID
        # Lock acquisition may legitimately wait out a CRASHED holder's
        # lease (TTL expiry frees it), so its deadline is wider than the
        # default unary deadline; timeout=None (deadline disabled) keeps
        # the historical unbounded wait.
        default = rpc_timeout_s()
        lock_timeout = (
            max(default, 2.0 * LOCK_LEASE_TTL_S) if default > 0 else None
        )
        self._key = self._stub.lock(
            epb.LockRequest(name=GLOBAL_LOCK_NAME, lease=self._lease),
            timeout=lock_timeout,
        ).key
        self._start_keepalive()
        return self

    def __exit__(self, *exc) -> None:
        if self._ka_stop is not None:
            self._ka_stop.set()
            if self._ka_call is not None:
                self._ka_call.cancel()
            self._ka_stop = self._ka_call = None
        try:
            if self._key is not None:
                self._stub.unlock(epb.UnlockRequest(key=self._key))
        finally:
            self._key = None
            if self._lease:
                lease, self._lease = self._lease, 0
                try:
                    self._stub.lease_revoke(epb.LeaseRevokeRequest(ID=lease))
                except grpc.RpcError:
                    pass  # TTL expiry will collect it
        return None


class _StreamWatch(Watch):
    """A Watch fed by the server's event stream instead of local
    _notify — events include other processes' writes."""

    def __init__(self, prefix: str, unsubscribe, cancel_stream) -> None:
        super().__init__(prefix, unsubscribe)
        self._cancel_stream = cancel_stream

    def stop(self) -> None:
        if not self._stopped:
            self._cancel_stream()
        super().stop()


class EtcdBackend(StateBackendClient):
    def __init__(self, urls: str) -> None:
        """``urls``: etcd endpoints, ``host:port[,host:port...]`` (same
        flag format as the reference's --etcd-urls). Multiple endpoints
        become a single multi-address gRPC target with round-robin pick —
        member failover is the channel's reconnect, not a client-side
        retry loop."""
        super().__init__()
        self.urls = urls
        endpoints = [u.strip() for u in urls.split(",") if u.strip()]
        if not endpoints:
            raise ValueError("empty etcd endpoint list")
        opts = []
        if len(endpoints) == 1:
            target = endpoints[0]
        elif all(_is_ipv4_hostport(e) for e in endpoints):
            # gRPC's name-syntax multi-address target; round_robin gets
            # every member address and the channel handles failover
            target = "ipv4:" + ",".join(endpoints)
            opts = [("grpc.lb_policy_name", "round_robin")]
        else:
            # hostname endpoints can't share one channel target; use the
            # first and say so rather than failing obscurely at first RPC
            target = endpoints[0]
            log.warning(
                "multiple hostname etcd endpoints %s: using %s only "
                "(front the cluster with one DNS name for failover)",
                endpoints, target,
            )
        self._channel = grpc.insecure_channel(target, options=opts)
        self._stub = _EtcdStub(self._channel)

    # -- KV ------------------------------------------------------------------
    def get(self, key: str) -> bytes | None:
        resp = self._stub.range(epb.RangeRequest(key=key.encode()))
        return resp.kvs[0].value if resp.kvs else None

    def get_from_prefix(self, prefix: str) -> list[tuple[str, bytes]]:
        p = prefix.encode()
        resp = self._stub.range(
            epb.RangeRequest(key=p, range_end=prefix_end(p), sort_order=1)
        )
        return [(kv.key.decode(), kv.value) for kv in resp.kvs]

    def put(self, key: str, value: bytes) -> None:
        self._stub.put(epb.PutRequest(key=key.encode(), value=bytes(value)))

    def delete(self, key: str) -> None:
        self._stub.delete_range(epb.DeleteRangeRequest(key=key.encode()))

    def lock(self):
        return _EtcdLock(self._stub)

    # -- watch ---------------------------------------------------------------
    def watch(self, prefix: str) -> Watch:
        p = prefix.encode()
        create = epb.WatchRequest(
            create_request=epb.WatchCreateRequest(
                key=p, range_end=prefix_end(p)
            )
        )
        done = threading.Event()

        def requests():
            yield create
            done.wait()  # hold the send side open until stop()

        call = self._stub.watch(requests())
        w = _StreamWatch(prefix, self._unwatch, lambda: (done.set(),
                                                         call.cancel()))
        created = threading.Event()

        def pump():
            try:
                for resp in call:
                    if resp.created:
                        created.set()
                    for ev in resp.events:
                        if ev.type == epb.Event.DELETE:
                            w._offer(WatchEvent(
                                "delete", ev.kv.key.decode(), None))
                        else:
                            w._offer(WatchEvent(
                                "put", ev.kv.key.decode(), ev.kv.value))
            except grpc.RpcError as e:
                if not done.is_set():
                    # NOT a local stop(): the server stream died. Surface
                    # it loudly — a scheduler silently blind to peer
                    # writes defeats the backend's purpose.
                    log.error("etcd watch on %r lost: %s; subscription "
                              "ends (restart the watch to resume)",
                              prefix, e)
            created.set()  # unblock the creator on early failure too
            w.stop()

        # exits when w.stop()/close() cancels the watch stream: the
        # thread's lifetime IS the stream's
        threading.Thread(  # lifelint: transfer=stream-bounded
            target=pump, daemon=True,
            name=f"etcd-watch-{prefix}").start()
        # Hand the watch out only after the server acknowledged it
        # (created=true): a put() racing watch() must not fall into the
        # gap before registration.
        created.wait(timeout=10)
        with self._watch_lock:
            self._watchers.append(w)
        return w

    def _notify(self, kind: str, key: str, value: bytes | None) -> None:
        # events arrive from the server stream; local echo would deliver
        # this process's writes twice
        pass

    def close(self) -> None:
        super().close()
        self._channel.close()
