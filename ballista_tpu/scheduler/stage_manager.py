"""Stage/task bookkeeping state machine.

ref ballista/rust/scheduler/src/state/stage_manager.rs:35-605. Tracks per
stage a vector of task statuses with legal-transition validation
(:536-586 — the reference's defensive mechanism against racy status
updates), the child->parents stage dependency map (:140-155), pending /
running / completed stage sets, and emits Stage/Job events on completion.
"""

from __future__ import annotations

import dataclasses
import enum
import random

from ballista_tpu.analysis.statemachine import TASK_TRANSITIONS
from ballista_tpu.analysis.witness import make_lock
from ballista_tpu.errors import InternalError
from ballista_tpu.scheduler_types import (
    PartitionId,
    ShuffleWritePartitionMeta,
)


class TaskState(enum.Enum):
    PENDING = "pending"
    RUNNING = "running"
    FAILED = "failed"
    COMPLETED = "completed"


# Legal transitions (ref stage_manager.rs:536-586: e.g. Pending->Failed is
# ignored; Completed->Pending re-opens a stage on status reset). DERIVED
# from the canonical declared table (analysis/statemachine.py) so the
# validator and the spec racelint/property tests check against cannot
# drift apart.
_LEGAL = {
    (TaskState(src), TaskState(dst)) for src, dst in TASK_TRANSITIONS
}


@dataclasses.dataclass
class TaskInfo:
    state: TaskState = TaskState.PENDING
    executor_id: str = ""
    error: str = ""
    partitions: list[ShuffleWritePartitionMeta] = dataclasses.field(
        default_factory=list
    )
    # bounded-retry bookkeeping: attempts = FAILED transitions consumed so
    # far (the next run is attempt number `attempts`); blamed = executors
    # this task failed on or was lost from (handout prefers others)
    attempts: int = 0
    blamed: set[str] = dataclasses.field(default_factory=set)
    # fleet observability (docs/observability.md): wall-clock bounds of
    # the CURRENT attempt (stamped on the RUNNING / terminal transitions;
    # a requeue resets them) — the timeline endpoint's Gantt source and
    # the straggler monitor's duration input
    started_s: float = 0.0
    ended_s: float = 0.0
    # flagged by the straggler monitor (duration > k x stage median)
    straggler: bool = False
    # this attempt window was already fed to the duration histogram —
    # replayed COMPLETED statuses (a lost PollWork response makes the
    # executor resend; the transition replay is rejected as illegal)
    # must not observe the same window twice
    duration_metered: bool = False


@dataclasses.dataclass
class Stage:
    job_id: str
    stage_id: int
    n_tasks: int  # = input partition count of the stage's ShuffleWriter
    tasks: list[TaskInfo] = dataclasses.field(default_factory=list)
    # retry policy (session config ballista.tpu.task_max_attempts): a task
    # may consume this many attempts before its failure fails the job; the
    # same bound caps lost-shuffle recompute rounds of this stage
    max_attempts: int = 3
    # times this stage's completed output was invalidated and re-run
    # (lost-shuffle recovery); bounded by max_attempts
    recomputes: int = 0

    def __post_init__(self):
        if not self.tasks:
            self.tasks = [TaskInfo() for _ in range(self.n_tasks)]

    def counts(self) -> dict[TaskState, int]:
        out = {s: 0 for s in TaskState}
        for t in self.tasks:
            out[t.state] += 1
        return out

    @property
    def is_completed(self) -> bool:
        return all(t.state == TaskState.COMPLETED for t in self.tasks)

    @property
    def has_failed(self) -> bool:
        return any(t.state == TaskState.FAILED for t in self.tasks)


class StageEvent:
    pass


@dataclasses.dataclass(frozen=True)
class StageFinished(StageEvent):
    job_id: str
    stage_id: int


@dataclasses.dataclass(frozen=True)
class JobFinished(StageEvent):
    job_id: str


@dataclasses.dataclass(frozen=True)
class JobFailed(StageEvent):
    job_id: str
    stage_id: int
    error: str


@dataclasses.dataclass(frozen=True)
class TaskRescheduled(StageEvent):
    """A failed task was requeued (FAILED -> PENDING) for another bounded
    attempt; `attempt` is the attempt number the NEXT run will carry."""

    job_id: str
    stage_id: int
    partition_id: int
    attempt: int
    error: str


def straggler_stats(
    durations: list[float], factor: float, min_s: float
) -> tuple[float, float] | None:
    """``(threshold, median)`` for the straggler monitor over a stage's
    completed task durations, or None when no meaningful threshold
    exists (monitor disabled, fewer than 3 completions to form a
    median, or a zero median). ONE definition shared by the committing
    check (SchedulerServer._observe_task_completion) and the timeline's
    live projection (rest.job_timeline) — two hand-synced copies once
    disagreed on the median convention, making the Gantt view and the
    Prometheus counter contradict each other about the same task. The
    median rides along so flag sites don't sort the list twice."""
    import statistics

    if factor <= 0 or len(durations) < 3:
        return None
    med = statistics.median(durations)
    if med <= 0:
        return None
    return max(min_s, factor * med), med


class StageManager:
    """In-memory running/pending/completed stage maps (ref :326-356)."""

    def __init__(self) -> None:
        self._lock = make_lock("StageManager._lock", reentrant=True)
        self._stages: dict[tuple[str, int], Stage] = {}
        self._running: set[tuple[str, int]] = set()
        self._pending: set[tuple[str, int]] = set()
        self._completed: set[tuple[str, int]] = set()
        # child stage -> parent stages waiting on it (ref :140-155)
        self._dependencies: dict[tuple[str, int], set[int]] = {}
        self._final_stage: dict[str, int] = {}

    # -- registration --------------------------------------------------------
    def add_final_stage(self, job_id: str, stage_id: int) -> None:
        with self._lock:
            self._final_stage[job_id] = stage_id

    def final_stage(self, job_id: str) -> int:
        with self._lock:
            return self._final_stage[job_id]

    def add_stages_dependency(
        self, job_id: str, deps: dict[int, set[int]]
    ) -> None:
        """deps: child_stage_id -> set of parent stage ids."""
        with self._lock:
            for child, parents in deps.items():
                self._dependencies[(job_id, child)] = set(parents)

    def parents_of(self, job_id: str, stage_id: int) -> set[int]:
        with self._lock:
            return set(self._dependencies.get((job_id, stage_id), set()))

    def add_running_stage(
        self, job_id: str, stage_id: int, n_tasks: int, max_attempts: int = 3
    ) -> None:
        with self._lock:
            key = (job_id, stage_id)
            self._stages[key] = Stage(
                job_id, stage_id, n_tasks, max_attempts=max(1, max_attempts)
            )
            self._running.add(key)
            self._pending.discard(key)

    def add_pending_stage(
        self, job_id: str, stage_id: int, n_tasks: int, max_attempts: int = 3
    ) -> None:
        with self._lock:
            key = (job_id, stage_id)
            self._stages[key] = Stage(
                job_id, stage_id, n_tasks, max_attempts=max(1, max_attempts)
            )
            self._pending.add(key)

    def is_running_stage(self, job_id: str, stage_id: int) -> bool:
        with self._lock:
            return (job_id, stage_id) in self._running

    def is_pending_stage(self, job_id: str, stage_id: int) -> bool:
        with self._lock:
            return (job_id, stage_id) in self._pending

    def is_completed_stage(self, job_id: str, stage_id: int) -> bool:
        with self._lock:
            return (job_id, stage_id) in self._completed

    def get_stage(self, job_id: str, stage_id: int) -> Stage | None:
        with self._lock:
            return self._stages.get((job_id, stage_id))

    # -- scheduling ----------------------------------------------------------
    def fetch_pending_tasks(
        self, job_id: str, stage_id: int, max_n: int, executor_id: str = ""
    ) -> list[int]:
        """Pending task (partition) ids of one stage, marking nothing.

        When ``executor_id`` is given, tasks that have NOT blamed it (never
        failed on / were lost from it) sort first — the soft "prefer a
        different executor" retry placement. Soft, not hard: a blamed
        executor is still offered the task when nothing else is pending,
        so a single-executor cluster can never deadlock on its own blame
        list."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None:
                return []
            out = [
                i
                for i, t in enumerate(stage.tasks)
                if t.state == TaskState.PENDING
            ]
            if executor_id:
                out.sort(
                    key=lambda i: executor_id in stage.tasks[i].blamed
                )
            return out[:max_n]

    def task_attempt(self, job_id: str, stage_id: int, partition: int) -> int:
        """Attempt number the next/current run of this task carries (= the
        count of FAILED transitions consumed so far)."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None or not (0 <= partition < stage.n_tasks):
                return 0
            return stage.tasks[partition].attempts

    def assign_next_task(
        self, executor_id: str = ""
    ) -> tuple[str, int, int, int, list["StageEvent"]] | None:
        """Atomically pick a schedulable stage, choose a pending task
        (blame-aware soft preference), and mark it RUNNING. Returns
        ``(job_id, stage_id, partition, attempt, events)`` or None.

        One critical section closes the pick/mark race: two concurrent
        PollWork threads could both observe the same partition PENDING,
        and the loser's PENDING->RUNNING mark was silently ignored as an
        illegal RUNNING->RUNNING hop — both executors then ran the same
        task (wasted slot at best, double-reported completions at
        worst)."""
        with self._lock:
            pick = self.fetch_schedulable_stage()
            if pick is None:
                return None
            job_id, stage_id = pick
            pending = self.fetch_pending_tasks(
                job_id, stage_id, 1, executor_id=executor_id
            )
            if not pending:
                return None
            partition = pending[0]
            events = self.update_task_status(
                PartitionId(job_id, stage_id, partition),
                TaskState.RUNNING,
                executor_id=executor_id,
            )
            attempt = self.task_attempt(job_id, stage_id, partition)
            return job_id, stage_id, partition, attempt, events

    def assign_next_tasks(
        self, executor_id: str = "", max_n: int = 1
    ) -> list[tuple[str, int, int, int, list["StageEvent"]]]:
        """Batched :meth:`assign_next_task` (docs/serving.md): up to
        ``max_n`` picks inside ONE critical section, so a single PollWork
        round-trip can carry a full grant batch without re-racing the
        pick/mark window per task. Picks may span stages/jobs — each
        iteration re-fetches the schedulable stage, so a stage drained
        mid-batch simply hands the remaining slots to the next one."""
        out: list[tuple[str, int, int, int, list["StageEvent"]]] = []
        with self._lock:
            for _ in range(max(1, max_n)):
                got = self.assign_next_task(executor_id)
                if got is None:
                    break
                out.append(got)
        return out

    def assign_next_eager_task(
        self, executor_id: str, eager_jobs: set[str]
    ) -> tuple[str, int, int, int, list["StageEvent"]] | None:
        """Eager-shuffle handout (docs/shuffle.md): atomically pick a task
        from a PENDING consumer stage whose producers are all in flight
        with at least one committed map output, and mark it RUNNING.
        Called only when :meth:`assign_next_task` found no runnable work,
        so eager consumers never compete with normal tasks for slots —
        they soak otherwise-idle capacity with early fetch work.

        ``eager_jobs``: jobs whose session enabled ballista.tpu.
        eager_shuffle (the server snapshots the flag at submission).
        Promotion stays the commit point: the stage remains PENDING and is
        promoted exactly as in barriered mode once every producer
        completes."""
        with self._lock:
            candidates = []
            for key in self._pending:  # detlint: nondet=placement
                job_id, stage_id = key
                if job_id not in eager_jobs:
                    continue
                stage = self._stages.get(key)
                if stage is None or not any(
                    t.state == TaskState.PENDING for t in stage.tasks
                ):
                    continue
                producers = [
                    child
                    for (jid, child), parents in self._dependencies.items()
                    if jid == job_id and stage_id in parents
                ]
                if not producers:
                    continue
                ready = True
                for p in producers:
                    ps = self._stages.get((job_id, p))
                    if ps is None or not any(
                        t.state == TaskState.COMPLETED for t in ps.tasks
                    ):
                        ready = False
                        break
                if ready:
                    candidates.append(key)
            if not candidates:
                return None
            job_id, stage_id = random.choice(  # detlint: nondet=placement
                candidates
            )
            pending = self.fetch_pending_tasks(
                job_id, stage_id, 1, executor_id=executor_id
            )
            if not pending:
                return None
            partition = pending[0]
            events = self.update_task_status(
                PartitionId(job_id, stage_id, partition),
                TaskState.RUNNING,
                executor_id=executor_id,
            )
            attempt = self.task_attempt(job_id, stage_id, partition)
            return job_id, stage_id, partition, attempt, events

    def shuffle_locations(
        self, job_id: str, stage_id: int, partition: int
    ) -> tuple[list[tuple[int, str, ShuffleWritePartitionMeta]], int, bool] | None:
        """Eager-poll snapshot for GetShuffleLocations: the published
        (COMPLETED) map outputs of one stage feeding ``partition``, as
        ``(entries, tasks_done_prefix, complete)`` where entries are
        ``(map task index, executor_id, meta)`` in task order and the
        prefix counts leading COMPLETED tasks (lineage recovery may
        shrink it; readers never consume beyond it pre-commit). None when
        the stage bookkeeping is gone (job finished or torn down)."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None:
                return None
            entries = []
            prefix = 0
            counting = True
            complete = True
            for i, t in enumerate(stage.tasks):
                if t.state == TaskState.COMPLETED:
                    if counting:
                        prefix = i + 1
                    for m in t.partitions:
                        if m.partition_id == partition:
                            entries.append((i, t.executor_id, m))
                else:
                    counting = False
                    complete = False
            return entries, prefix, complete

    def fetch_schedulable_stage(self) -> tuple[str, int] | None:
        """A random running stage with pending tasks (ref :300-324 — random
        pick avoids head-of-line blocking across jobs)."""
        with self._lock:
            candidates = [
                key
                for key in self._running  # detlint: nondet=placement
                if any(
                    t.state == TaskState.PENDING
                    for t in self._stages[key].tasks
                )
            ]
            if not candidates:
                return None
            return random.choice(candidates)  # detlint: nondet=placement

    # -- status updates ------------------------------------------------------
    def update_task_status(
        self,
        task_id: PartitionId,
        new_state: TaskState,
        executor_id: str = "",
        error: str = "",
        partitions: list[ShuffleWritePartitionMeta] | None = None,
        retryable: bool = True,
        count_attempt: bool = True,
    ) -> list[StageEvent]:
        """Apply one task status; illegal transitions are ignored (the
        reference rejects them rather than corrupting counts, :536-586).
        Returns stage/job events triggered by this update.

        A FAILED update consumes one bounded attempt: while attempts remain
        and the error is ``retryable``, the task is immediately requeued
        through the legal FAILED -> PENDING transition (blaming the
        executor so the next handout prefers a different one) and a
        :class:`TaskRescheduled` event fires instead of :class:`JobFailed`.
        ``retryable=False`` (deterministic errors — PlanVerificationError
        and friends, see errors.NON_RETRYABLE_ERROR_TYPES) short-circuits
        straight to JobFailed: re-running cannot change the outcome.
        ``count_attempt=False`` requeues without consuming an attempt —
        used for shuffle-fetch failures, which blame the *producing*
        executor, not this task; their boundedness comes from the
        producing stage's recompute cap instead."""
        with self._lock:
            key = (task_id.job_id, task_id.stage_id)
            stage = self._stages.get(key)
            if stage is None:
                # late status for a removed (failed/finished) job — drop it
                # rather than corrupting counts (ref :536-586 is equally
                # defensive about out-of-band updates)
                return []
            if not (0 <= task_id.partition_id < stage.n_tasks):
                raise InternalError(
                    f"task partition {task_id.partition_id} out of range "
                    f"for stage with {stage.n_tasks} tasks"
                )
            info = stage.tasks[task_id.partition_id]
            if (info.state, new_state) not in _LEGAL:
                return []
            blamed_executor = executor_id or info.executor_id
            import time as _time

            # attempt wall-clock bounds (timeline + straggler monitor):
            # RUNNING opens a fresh window, terminal states close it, and
            # any PENDING re-open (requeue, invalidation) clears it
            if new_state == TaskState.RUNNING:
                info.started_s = _time.time()
                info.ended_s = 0.0
            elif new_state in (TaskState.COMPLETED, TaskState.FAILED):
                info.ended_s = _time.time()
            elif new_state == TaskState.PENDING:
                info.started_s = 0.0
                info.ended_s = 0.0
                info.duration_metered = False
            info.state = new_state
            info.executor_id = executor_id or info.executor_id
            info.error = error
            if partitions is not None:
                info.partitions = list(partitions)

            events: list[StageEvent] = []
            if new_state == TaskState.FAILED:
                if blamed_executor:
                    info.blamed.add(blamed_executor)
                if count_attempt:
                    info.attempts += 1
                if not retryable:
                    events.append(
                        JobFailed(task_id.job_id, task_id.stage_id, error)
                    )
                elif info.attempts >= stage.max_attempts:
                    events.append(
                        JobFailed(
                            task_id.job_id,
                            task_id.stage_id,
                            f"task {task_id} failed after "
                            f"{info.attempts} attempts: {error}",
                        )
                    )
                else:
                    # bounded requeue (FAILED -> PENDING, the legal
                    # transition the reference declares but never takes)
                    info.state = TaskState.PENDING
                    info.executor_id = ""
                    info.started_s = 0.0
                    info.ended_s = 0.0
                    info.duration_metered = False
                    events.append(
                        TaskRescheduled(
                            task_id.job_id,
                            task_id.stage_id,
                            task_id.partition_id,
                            info.attempts,
                            error,
                        )
                    )
            elif stage.is_completed and key in self._running:
                self._running.discard(key)
                self._completed.add(key)
                if self._final_stage.get(task_id.job_id) == task_id.stage_id:
                    events.append(JobFinished(task_id.job_id))
                else:
                    events.append(
                        StageFinished(task_id.job_id, task_id.stage_id)
                    )
            return events

    def promote_pending_stage(self, job_id: str, stage_id: int) -> list[StageEvent]:
        """Pending -> running. Returns completion events in the (rare) case
        every task already COMPLETED while the stage sat pending — possible
        after lost-shuffle recovery demotes a running stage whose in-flight
        tasks then all report success; without this check the stage would
        re-enter running fully complete and no status update would ever
        fire its StageFinished/JobFinished."""
        with self._lock:
            key = (job_id, stage_id)
            if key not in self._pending:
                return []
            self._pending.discard(key)
            self._running.add(key)
            stage = self._stages[key]
            if not stage.is_completed:
                return []
            self._running.discard(key)
            self._completed.add(key)
            if self._final_stage.get(job_id) == stage_id:
                return [JobFinished(job_id)]
            return [StageFinished(job_id, stage_id)]

    def demote_running_stage(self, job_id: str, stage_id: int) -> None:
        """Running -> pending: a dependency's output was invalidated
        (lost shuffle), so no further task of this stage may be handed out
        until the dependency re-completes and locations are re-resolved.
        In-flight RUNNING tasks keep running (they either fetched the data
        before the loss — their output is valid — or will fail with a
        ShuffleFetchError and requeue)."""
        with self._lock:
            key = (job_id, stage_id)
            if key in self._running:
                self._running.discard(key)
                self._pending.add(key)

    def invalidate_executor_outputs(
        self, job_id: str, stage_id: int, executor_ids: set[str]
    ) -> list[PartitionId]:
        """Lost-shuffle recovery, producer side: COMPLETED tasks of this
        stage whose shuffle files live on one of ``executor_ids`` are
        re-opened (the legal COMPLETED -> PENDING transition) with their
        partition metadata dropped, and a completed stage rolls back to
        running so exactly the lost map partitions re-run. Blames the dead
        executor on each re-opened task and counts one recompute round
        against the stage. Returns the re-opened task ids (empty when the
        executor produced nothing here — e.g. a concurrent failure already
        invalidated it)."""
        out: list[PartitionId] = []
        with self._lock:
            key = (job_id, stage_id)
            stage = self._stages.get(key)
            if stage is None:
                return []
            for i, t in enumerate(stage.tasks):
                if (
                    t.state == TaskState.COMPLETED
                    and t.executor_id in executor_ids
                ):
                    t.state = TaskState.PENDING
                    t.blamed.add(t.executor_id)
                    t.executor_id = ""
                    t.partitions = []
                    t.started_s = 0.0
                    t.ended_s = 0.0
                    t.duration_metered = False
                    out.append(PartitionId(job_id, stage_id, i))
            if out:
                stage.recomputes += 1
                if key in self._completed:
                    self._completed.discard(key)
                    self._running.add(key)
        return out

    def rebind_stages_for_rewrite(
        self,
        job_id: str,
        affected: dict[int, int],
        removed: tuple[int, ...],
        added: dict[int, int],
        deps: dict[int, set[int]],
        max_attempts: int = 3,
    ) -> str | None:
        """Atomically re-register bookkeeping for a certified rewrite
        (SchedulerServer.apply_certified_rewrite): ``affected`` maps every
        rewritten stage id to its (possibly changed) task count,
        ``removed``/``added`` are the exchange-elimination/-injection
        deltas, ``deps`` is the job's full recomputed dependency map.

        Runtime precondition, checked under the lock before anything
        changes: every touched stage must be fully PENDING — no task
        running or completed, no completed stage. A stage with progress
        holds results computed against the OLD template (a producer's
        files already bucketed the old way, a consumer task mid-fetch),
        and swapping under it is exactly the uncertified mutation this
        API exists to prevent. Returns an error string on violation
        (nothing mutated — the caller rejects and keeps the pristine
        templates); None on success. Rewritten stages land PENDING (the
        caller re-resolves and promotes the ones whose deps are already
        complete); ``recomputes`` carries over so lineage-recovery
        boundedness survives a rewrite."""
        with self._lock:
            for sid in list(affected) + list(removed):
                key = (job_id, sid)
                stage = self._stages.get(key)
                if stage is None:
                    return f"stage {sid} has no bookkeeping to rebind"
                if key in self._completed:
                    return f"stage {sid} already completed"
                busy = [
                    t.state.value
                    for t in stage.tasks
                    if t.state != TaskState.PENDING
                ]
                if busy:
                    return (
                        f"stage {sid} has {len(busy)} non-pending tasks "
                        f"({sorted(set(busy))}); rewrites require a fully "
                        "pending stage"
                    )
            for sid, n_tasks in affected.items():
                key = (job_id, sid)
                old = self._stages[key]
                fresh = Stage(
                    job_id, sid, n_tasks, max_attempts=old.max_attempts
                )
                fresh.recomputes = old.recomputes
                self._stages[key] = fresh
                self._running.discard(key)
                self._pending.add(key)
            for sid in removed:
                key = (job_id, sid)
                self._stages.pop(key, None)
                self._running.discard(key)
                self._pending.discard(key)
            for sid, n_tasks in added.items():
                key = (job_id, sid)
                self._stages[key] = Stage(
                    job_id, sid, n_tasks, max_attempts=max(1, max_attempts)
                )
                self._pending.add(key)
            # dependency map: wholesale replacement for this job — stale
            # entries (including removed stages') all drop here
            for key in [k for k in self._dependencies if k[0] == job_id]:
                self._dependencies.pop(key)
            for child, parents in deps.items():
                self._dependencies[(job_id, child)] = set(parents)
            return None

    def stages_with_outputs_of(
        self, executor_ids: set[str]
    ) -> list[tuple[str, int]]:
        """Stages holding COMPLETED shuffle output produced by one of
        ``executor_ids`` — the candidates for lost-shuffle invalidation
        when those executors expire."""
        with self._lock:
            return [
                key
                for key, stage in self._stages.items()
                if any(
                    t.state == TaskState.COMPLETED
                    and t.executor_id in executor_ids
                    for t in stage.tasks
                )
            ]

    def take_unmetered_runtime(
        self, job_id: str, stage_id: int, partition: int
    ) -> float | None:
        """Duration (seconds) of a task's CURRENT closed attempt window,
        consumed EXACTLY ONCE (atomic under the lock): a replayed
        COMPLETED status — the executor resends after a lost RPC
        response, and the transition replay is rejected — gets None, so
        the stage-task histogram never double-counts one window. A
        PENDING re-open clears the flag with the window (a genuine new
        attempt meters again)."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None or not (0 <= partition < stage.n_tasks):
                return None
            t = stage.tasks[partition]
            if t.duration_metered or not (t.started_s and t.ended_s):
                return None
            t.duration_metered = True
            return max(0.0, t.ended_s - t.started_s)

    def completed_durations(
        self, job_id: str, stage_id: int
    ) -> list[float]:
        """Closed-attempt durations of this stage's COMPLETED tasks (the
        straggler monitor's median base)."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None:
                return []
            return [
                t.ended_s - t.started_s
                for t in stage.tasks
                if t.state == TaskState.COMPLETED
                and t.started_s
                and t.ended_s
            ]

    def mark_straggler(
        self, job_id: str, stage_id: int, partition: int
    ) -> bool:
        """Flag one task as a straggler (idempotent; returns whether the
        flag was newly set — the counter increments only once)."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None or not (0 <= partition < stage.n_tasks):
                return False
            t = stage.tasks[partition]
            if t.straggler:
                return False
            t.straggler = True
            return True

    def all_tasks_pending(self, job_id: str, stage_id: int) -> bool:
        """True when every task of the stage is PENDING — the rewrite
        window (rebind_stages_for_rewrite's precondition). Eager-shuffle
        handout can start a PENDING stage's tasks early, which closes
        the window without promoting the stage; the AQE policy checks
        here before proposing a mid-job rewrite (docs/aqe.md)."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None:
                return False
            return all(t.state == TaskState.PENDING for t in stage.tasks)

    def stage_recomputes(self, job_id: str, stage_id: int) -> int:
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            return stage.recomputes if stage is not None else 0

    def stage_max_attempts(self, job_id: str, stage_id: int) -> int:
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            return stage.max_attempts if stage is not None else 3

    def completed_partitions(
        self, job_id: str, stage_id: int
    ) -> list[tuple[int, str, list[ShuffleWritePartitionMeta]]]:
        """[(task/partition index, executor_id, written files)] of a
        completed stage (feeds PartitionLocation resolution)."""
        with self._lock:
            stage = self._stages.get((job_id, stage_id))
            if stage is None:
                return []
            return [
                (i, t.executor_id, list(t.partitions))
                for i, t in enumerate(stage.tasks)
                if t.state == TaskState.COMPLETED
            ]

    def remove_job_stages(self, job_id: str) -> None:
        """Drop every stage of a finished/failed job so dead tasks can't be
        scheduled again and inflight counts (the KEDA signal) go to zero."""
        with self._lock:
            keys = [k for k in self._stages if k[0] == job_id]
            for k in keys:
                self._stages.pop(k, None)
                self._running.discard(k)
                self._pending.discard(k)
                self._completed.discard(k)
                self._dependencies.pop(k, None)
            self._final_stage.pop(job_id, None)

    def reset_tasks_of_executors(
        self, executor_ids: set[str]
    ) -> list[PartitionId]:
        """Executor-lost recovery: every RUNNING task assigned to one of
        ``executor_ids`` goes back to PENDING (the RUNNING->PENDING legal
        transition, ref stage_manager.rs:553-558) so the next offer/poll can
        hand it to a live executor. Returns the reset task ids."""
        out: list[PartitionId] = []
        with self._lock:
            for (job_id, stage_id), stage in self._stages.items():
                for i, t in enumerate(stage.tasks):
                    if (
                        t.state == TaskState.RUNNING
                        and t.executor_id in executor_ids
                    ):
                        t.state = TaskState.PENDING
                        # blame (prefer another executor next time) but do
                        # NOT consume an attempt: the executor died, the
                        # task did nothing wrong
                        t.blamed.add(t.executor_id)
                        t.executor_id = ""
                        t.started_s = 0.0
                        t.ended_s = 0.0
                        t.duration_metered = False
                        out.append(PartitionId(job_id, stage_id, i))
        return out

    def job_stage_summary(self, job_id: str) -> list[dict]:
        """Read-only per-stage snapshot for the REST /api/state payload:
        stage id, DAG state, and task-state counts (ref ui job detail)."""
        with self._lock:
            out = []
            keys = sorted(k for k in self._stages if k[0] == job_id)
            for key in keys:
                _, sid = key
                stage = self._stages[key]
                state = (
                    "completed" if key in self._completed
                    else "running" if key in self._running
                    else "pending"
                )
                counts = stage.counts()
                out.append(
                    {
                        "stage_id": sid,
                        "state": state,
                        "n_tasks": stage.n_tasks,
                        "tasks": {
                            s.value: n for s, n in counts.items()
                        },
                        # retry visibility: total failed attempts consumed
                        # across this stage's tasks + lost-shuffle
                        # recompute rounds (both 0 on a clean run)
                        "attempts": sum(t.attempts for t in stage.tasks),
                        "recomputes": stage.recomputes,
                    }
                )
            return out

    def job_stage_detail(self, job_id: str) -> list[dict]:
        """Per-stage, per-task stats snapshot (docs/observability.md):
        everything /api/job/<id> and EXPLAIN ANALYZE aggregation need —
        task state, attempts, executor, and the written shuffle output's
        rows/bytes/batches summed over the task's output partitions. The
        scheduler overlays per-operator metrics (JobInfo.op_metrics) on
        top; this stays a pure StageManager view so it can be snapshotted
        before job teardown."""
        with self._lock:
            out = []
            keys = sorted(k for k in self._stages if k[0] == job_id)
            for key in keys:
                _, sid = key
                stage = self._stages[key]
                state = (
                    "completed" if key in self._completed
                    else "running" if key in self._running
                    else "pending"
                )
                tasks = []
                for i, t in enumerate(stage.tasks):
                    tasks.append(
                        {
                            "partition": i,
                            "state": t.state.value,
                            "attempts": t.attempts,
                            "executor_id": t.executor_id,
                            "output_rows": sum(
                                m.num_rows for m in t.partitions
                            ),
                            "output_bytes": sum(
                                m.num_bytes for m in t.partitions
                            ),
                            "output_batches": sum(
                                m.num_batches for m in t.partitions
                            ),
                            # push-shuffle visibility (docs/shuffle.md):
                            # how many of this task's output partitions
                            # committed in memory vs on disk
                            "output_pushed": sum(
                                1 for m in t.partitions if m.push
                            ),
                            # timeline (docs/observability.md): the
                            # current attempt's wall-clock window + the
                            # straggler-monitor flag
                            "started_s": round(t.started_s, 6),
                            "ended_s": round(t.ended_s, 6),
                            "straggler": t.straggler,
                        }
                    )
                out.append(
                    {
                        "stage_id": sid,
                        "state": state,
                        "n_tasks": stage.n_tasks,
                        "recomputes": stage.recomputes,
                        "tasks": tasks,
                    }
                )
            return out

    def has_running_tasks(self) -> bool:
        with self._lock:
            return any(
                t.state == TaskState.RUNNING
                for s in self._stages.values()
                for t in s.tasks
            )

    def inflight_tasks(self) -> int:
        with self._lock:
            return sum(
                1
                for s in self._stages.values()
                for t in s.tasks
                if t.state in (TaskState.PENDING, TaskState.RUNNING)
            )
