"""Scheduler process entrypoint: ``python -m ballista_tpu.scheduler``.

ref ballista/rust/scheduler/src/main.rs:65-198 — parse the flag/env config
tier, pick the state backend (in-memory or sqlite, standing in for the
reference's sled/etcd pair), start the SchedulerGrpc service and the REST
``/state`` API, and wait for a signal.

Flags mirror the reference's scheduler config spec; every flag also reads a
``BALLISTA_SCHEDULER_<NAME>`` environment default (configure_me behavior).
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import threading

from ballista_tpu.config import BallistaConfig, TaskSchedulingPolicy

log = logging.getLogger("ballista_tpu.scheduler")


def _env(name: str, default):
    return os.environ.get(f"BALLISTA_SCHEDULER_{name.upper()}", default)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m ballista_tpu.scheduler",
        description="ballista-tpu scheduler process",
    )
    p.add_argument("--bind-host", default=_env("bind_host", "0.0.0.0"))
    p.add_argument(
        "--bind-port", type=int, default=int(_env("bind_port", 50050))
    )
    p.add_argument(
        "--rest-port",
        type=int,
        default=int(_env("rest_port", 0)),
        help="REST /state + UI port; 0 disables "
        "(the reference multiplexes gRPC+REST on one port, main.rs:136-166)",
    )
    p.add_argument(
        "--scheduler-policy",
        default=_env("scheduler_policy", "pull-staged"),
        choices=["pull-staged", "push-staged"],
    )
    p.add_argument(
        "--namespace", default=_env("namespace", "ballista"),
        help="state-backend key prefix (ref main.rs:74-78)",
    )
    p.add_argument(
        "--state-backend",
        default=_env("state_backend", "memory"),
        choices=["memory", "sqlite", "etcd"],
        help="memory (ephemeral), sqlite (embedded/sled analogue), or "
        "etcd (HA/multi-scheduler, ref state/backend/etcd.rs:32-196)",
    )
    p.add_argument(
        "--state-path",
        default=_env("state_path", "ballista-scheduler-state.db"),
        help="sqlite file path when --state-backend=sqlite",
    )
    p.add_argument(
        "--etcd-urls",
        default=_env("etcd_urls", "localhost:2379"),
        help="etcd endpoints (host:port[,host:port...]) when "
        "--state-backend=etcd (ref scheduler main.rs --etcd-urls)",
    )
    p.add_argument(
        "--executor-timeout-seconds",
        type=float,
        default=float(_env("executor_timeout_seconds", 60)),
    )
    p.add_argument("--log-level", default=_env("log_level", "INFO"))
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    logging.basicConfig(
        level=getattr(logging, args.log_level.upper(), logging.INFO),
        format="%(asctime)s %(levelname)s %(name)s %(message)s",
    )
    from ballista_tpu.config import warn_unknown_env

    warn_unknown_env()  # typo'd BALLISTA_* knobs must be loud (config.md)
    # re-log the import-time cache decision now that a handler exists
    import ballista_tpu

    log.info(
        "jax persistent compilation cache: %s",
        ballista_tpu.jax_cache_dir or "disabled",
    )
    from ballista_tpu.scheduler.server import (
        SchedulerServer,
        start_scheduler_grpc,
    )
    from ballista_tpu.scheduler.state_backend import (
        MemoryBackend,
        SqliteBackend,
    )

    if args.state_backend == "etcd":
        from ballista_tpu.scheduler.etcd_backend import EtcdBackend

        backend = EtcdBackend(args.etcd_urls)
    elif args.state_backend == "sqlite":
        backend = SqliteBackend(args.state_path)
    else:
        backend = MemoryBackend()
    server = SchedulerServer(
        provider=None,
        config=BallistaConfig(),
        state_backend=backend,
        namespace=args.namespace,
        policy=TaskSchedulingPolicy.parse(args.scheduler_policy),
        executor_timeout_s=args.executor_timeout_seconds,
    )
    grpc_server, port = start_scheduler_grpc(
        server, args.bind_host, args.bind_port
    )
    log.info(
        "scheduler: gRPC on %s:%d, policy=%s, backend=%s",
        args.bind_host, port, args.scheduler_policy, args.state_backend,
    )
    rest = None
    if args.rest_port:
        from ballista_tpu.scheduler.rest import start_rest_server

        rest, rest_port = start_rest_server(
            server, args.bind_host, args.rest_port
        )
        log.info("REST /state on %s:%d", args.bind_host, rest_port)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    log.info("shutting down")
    if rest is not None:
        from ballista_tpu.scheduler.rest import stop_rest_server

        stop_rest_server(rest)
    grpc_server.stop(grace=1)
    server.shutdown()
    backend.close()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
