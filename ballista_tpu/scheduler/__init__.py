"""Scheduler: control plane, stage DAG state machine, task dispatch.

The reference's scheduler crate (ballista/rust/scheduler/src): gRPC
service, DistributedPlanner-driven stage generation, StageManager state
machine, executor registry, pull/push task dispatch, persistent state.
"""
