"""SchedulerServer: query/stage orchestration + gRPC service.

Combines the reference's SchedulerServer (scheduler_server/mod.rs:54-232),
gRPC handlers (scheduler_server/grpc.rs:57-553), and QueryStageScheduler
event loop (scheduler_server/query_stage_scheduler.rs:40-473):

  ExecuteQuery -> plan (SQL -> logical -> optimized -> physical)
              -> JobSubmitted event -> DistributedPlanner stage split
              -> stage DAG submit (running if deps resolved, else pending)
  PollWork    -> heartbeat + apply statuses + hand out <=1 task (pull mode)
  StageFinished -> resolve dependent stages (patch shuffle locations)
  JobFinished -> assemble CompletedJob partition locations
"""

from __future__ import annotations

import dataclasses
import logging
import random
import string
import threading

from ballista_tpu.config import BallistaConfig, TaskSchedulingPolicy
from ballista_tpu.distributed_plan import (
    DistributedPlanner,
    QueryStage,
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
)
from ballista_tpu.errors import PlanError
from ballista_tpu.event_loop import EventAction, EventLoop
from ballista_tpu.exec.base import ExecutionPlan
from ballista_tpu.exec.planner import PhysicalPlanner, TableProvider
from ballista_tpu.plan.optimizer import optimize
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.executor_manager import ExecutorManager
from ballista_tpu.scheduler.stage_manager import (
    JobFailed,
    JobFinished,
    StageFinished,
    StageManager,
    TaskState,
)
from ballista_tpu.scheduler_types import (
    ExecutorData,
    ExecutorMetadata,
    ExecutorSpecification,
    PartitionId,
    PartitionLocation,
    ShuffleWritePartitionMeta,
)
from ballista_tpu.serde import BallistaCodec, loc_to_proto
from ballista_tpu.sql import ast
from ballista_tpu.sql.parser import parse_sql
from ballista_tpu.sql.planner import SqlPlanner

log = logging.getLogger(__name__)


def generate_job_id() -> str:
    """7-char alnum ids (ref grpc.rs:546-553)."""
    return "".join(random.choices(string.ascii_lowercase + string.digits, k=7))


@dataclasses.dataclass
class JobInfo:
    job_id: str
    session_id: str
    status: str = "queued"  # queued | running | failed | completed
    error: str = ""
    stages: dict[int, QueryStage] = dataclasses.field(default_factory=dict)
    # child stage id -> parent stage ids (parents consume the child)
    dependencies: dict[int, set[int]] = dataclasses.field(default_factory=dict)
    final_stage_id: int = 0
    completed_locations: list[PartitionLocation] = dataclasses.field(
        default_factory=list
    )
    # resolved (shuffle-patched) serialized plans, per stage
    resolved_plan_bytes: dict[int, bytes] = dataclasses.field(
        default_factory=dict
    )


@dataclasses.dataclass(frozen=True)
class JobSubmitted:
    job_id: str
    plan: ExecutionPlan


@dataclasses.dataclass(frozen=True)
class ReviveOffers:
    """Push-mode dispatch tick (ref scheduler_server/event_loop.rs:35-169:
    SchedulerServerEvent::ReviveOffers)."""

    n: int = 1


class QueryStageScheduler(EventAction):
    """The stage DAG state machine (ref query_stage_scheduler.rs:40-473)."""

    def __init__(self, server: "SchedulerServer"):
        self.server = server

    def on_receive(self, event):
        s = self.server
        if isinstance(event, ReviveOffers):
            s._offer_resources()
            return None
        if isinstance(event, JobSubmitted):
            s._generate_stages(event.job_id, event.plan)
        elif isinstance(event, StageFinished):
            s._on_stage_finished(event.job_id, event.stage_id)
        elif isinstance(event, JobFinished):
            s._on_job_finished(event.job_id)
        elif isinstance(event, JobFailed):
            s._on_job_failed(event.job_id, event.error)
        else:
            log.warning("unknown scheduler event %r", event)
            return None
        # push mode: every stage/job event can unlock work — re-offer (ref
        # query_stage_scheduler.rs:403-408)
        if s.policy == TaskSchedulingPolicy.PUSH_STAGED:
            return ReviveOffers()
        return None


class SchedulerServer:
    """State + event loop. The gRPC servicer (:class:`SchedulerGrpcServicer`)
    and the REST API both drive this object."""

    def __init__(
        self,
        provider: TableProvider,
        config: BallistaConfig | None = None,
        state_backend=None,
        namespace: str = "default",
        policy: TaskSchedulingPolicy = TaskSchedulingPolicy.PULL_STAGED,
    ):
        """``state_backend``: a
        :class:`ballista_tpu.scheduler.state_backend.StateBackendClient`;
        when given, executors/sessions/jobs/stage-plans write through to it
        and a new SchedulerServer over the same backend recovers them (ref
        persistent_state.rs:85-181 + the restart test :401-525)."""
        self.provider = provider
        self.config = config or BallistaConfig()
        self.codec = BallistaCodec(provider=provider)
        self.stage_manager = StageManager()
        self.executor_manager = ExecutorManager()
        self.jobs: dict[str, JobInfo] = {}
        self.sessions: dict[str, BallistaConfig] = {}
        self.policy = policy
        # push mode: the scheduler dials each executor's gRPC back at
        # registration (ref grpc.rs:180-192) and launches tasks through it
        self.executor_clients: dict[str, object] = {}
        self._executor_channels: dict[str, object] = {}
        self._lock = threading.RLock()
        self.state = None
        if state_backend is not None:
            from ballista_tpu.scheduler.persistent_state import (
                PersistentSchedulerState,
            )

            self.state = PersistentSchedulerState(
                state_backend, namespace, self.codec
            )
            self._recover_state()
        self.event_loop = EventLoop("query-stage", QueryStageScheduler(self))
        self.event_loop.start()
        import time as _time

        self.start_time = _time.time()

    def _recover_state(self) -> None:
        """Rebuild in-memory state from the backend on restart (ref
        persistent_state.rs init :85-181)."""
        for em in self.state.load_executors():
            self.executor_manager.save_executor_metadata(em)
        for sid, settings in self.state.load_sessions().items():
            try:
                self.sessions[sid] = (
                    BallistaConfig(settings) if settings else self.config
                )
            except Exception:  # noqa: BLE001 — stale/unknown keys
                self.sessions[sid] = self.config
        for rec in self.state.load_jobs():
            job = JobInfo(
                job_id=rec["job_id"],
                session_id=rec["session_id"],
                status=rec["status"],
                error=rec.get("error", ""),
                final_stage_id=rec.get("final_stage_id", 0),
            )
            job.dependencies = {
                int(k): set(v)
                for k, v in rec.get("dependencies", {}).items()
            }
            job.completed_locations = self.state.locations_from_json(
                rec.get("locations", [])
            )
            plans = self.state.load_stage_plans(job.job_id)
            for stage_id, plan in plans.items():
                job.stages[stage_id] = QueryStage(
                    job.job_id, stage_id, plan
                )
            if job.status in ("queued", "running"):
                # tasks in flight died with the old scheduler; fail loudly
                # rather than dangle (running StageManager state is not
                # persisted, matching the reference)
                job.status = "failed"
                job.error = "scheduler restarted while job was in flight"
                self.state.save_job(job)
            self.jobs[job.job_id] = job
        if self.jobs:
            log.info(
                "recovered %d jobs, %d sessions from state backend",
                len(self.jobs), len(self.sessions),
            )

    # -- session management (ref grpc.rs:350-374) ----------------------------
    def get_or_create_session(
        self, session_id: str, settings: dict[str, str]
    ) -> str:
        with self._lock:
            if session_id and session_id in self.sessions:
                if settings:
                    self.sessions[session_id] = BallistaConfig(settings)
                return session_id
            new_id = "".join(
                random.choices(string.ascii_lowercase + string.digits, k=16)
            )
            self.sessions[new_id] = (
                BallistaConfig(settings) if settings else self.config
            )
            if self.state is not None:
                self.state.save_session(new_id, settings or {})
            return new_id

    def persist_executor(self, em: ExecutorMetadata) -> None:
        if self.state is not None:
            self.state.save_executor_metadata(em)

    # -- query submission ----------------------------------------------------
    def submit_sql(self, sql: str, session_id: str) -> str:
        stmt = parse_sql(sql)
        if not isinstance(stmt, (ast.Select, ast.SetOp)):
            raise PlanError("ExecuteQuery requires a SELECT statement")
        logical = SqlPlanner(self.provider).plan(stmt)
        return self.submit_logical(logical, session_id)

    def submit_logical(self, logical, session_id: str) -> str:
        cfg = self.sessions.get(session_id, self.config)
        optimized = optimize(logical)
        # distributed=True inserts HashRepartitionExec exchange boundaries
        # (honoring ballista.repartition.*) so the stage splitter can cut
        # multi-partition hash shuffles (ref planner.rs:133-157)
        physical = PhysicalPlanner(
            self.provider,
            cfg.default_shuffle_partitions(),
            config=cfg,
            distributed=True,
        ).plan(optimized)
        return self.submit_physical(physical, session_id)

    def submit_physical(self, physical: ExecutionPlan, session_id: str) -> str:
        job_id = generate_job_id()
        with self._lock:
            job = JobInfo(job_id=job_id, session_id=session_id)
            self.jobs[job_id] = job
            if self.state is not None:
                self.state.save_job(job)
        self.event_loop.post(JobSubmitted(job_id, physical))
        return job_id

    # -- stage generation (ref query_stage_scheduler.rs:59-105) --------------
    def _generate_stages(self, job_id: str, plan: ExecutionPlan) -> None:
        try:
            planner = DistributedPlanner()
            stages = planner.plan_query_stages(job_id, plan)
        except Exception as e:  # noqa: BLE001
            self._on_job_failed(job_id, f"planning failed: {e}")
            return
        job = self.jobs[job_id]
        deps: dict[int, set[int]] = {}
        for stage in stages:
            job.stages[stage.stage_id] = stage
            for u in find_unresolved_shuffles(stage.plan):
                deps.setdefault(u.stage_id, set()).add(stage.stage_id)
        job.final_stage_id = stages[-1].stage_id
        job.dependencies = deps
        self.stage_manager.add_final_stage(job_id, job.final_stage_id)
        self.stage_manager.add_stages_dependency(job_id, deps)
        job.status = "running"
        if self.state is not None:
            # write-through: stage plans + job record (ref
            # persistent_state.rs save_stage_plan :183-324)
            for stage in stages:
                self.state.save_stage_plan(
                    job_id, stage.stage_id, stage.plan
                )
            self.state.save_job(job)
        self._submit_stage(job_id, job.final_stage_id, set())

    def _submit_stage(
        self, job_id: str, stage_id: int, seen: set[int]
    ) -> None:
        """Recursive dependency walk (ref :124-177)."""
        if stage_id in seen:
            return
        seen.add(stage_id)
        if self.stage_manager.is_running_stage(
            job_id, stage_id
        ) or self.stage_manager.is_pending_stage(job_id, stage_id):
            return
        job = self.jobs[job_id]
        stage = job.stages[stage_id]
        unresolved = find_unresolved_shuffles(stage.plan)
        unfinished = [
            u
            for u in unresolved
            if not self.stage_manager.is_completed_stage(job_id, u.stage_id)
        ]
        n_tasks = stage.input_partition_count
        if unfinished:
            self.stage_manager.add_pending_stage(job_id, stage_id, n_tasks)
            for u in unfinished:
                self._submit_stage(job_id, u.stage_id, seen)
        else:
            self._resolve_stage(job_id, stage_id)
            self.stage_manager.add_running_stage(job_id, stage_id, n_tasks)

    def _resolve_stage(self, job_id: str, stage_id: int) -> None:
        """Patch completed shuffle locations into the stage plan and
        serialize it once (ref try_resolve_stage :181-309 +
        task_scheduler.rs:146-156)."""
        job = self.jobs[job_id]
        stage = job.stages[stage_id]
        unresolved = find_unresolved_shuffles(stage.plan)
        if unresolved:
            locations: dict[int, list[list[PartitionLocation]]] = {}
            for u in unresolved:
                locations[u.stage_id] = self._stage_output_locations(
                    job_id, u.stage_id, u.output_partition_count
                )
            resolved = remove_unresolved_shuffles(stage.plan, locations)
            stage.plan = resolved
        job.resolved_plan_bytes[stage_id] = self.codec.physical_to_proto(
            stage.plan
        ).SerializeToString()

    def _stage_output_locations(
        self, job_id: str, stage_id: int, n_out: int
    ) -> list[list[PartitionLocation]]:
        locs: list[list[PartitionLocation]] = [[] for _ in range(n_out)]
        for (task_idx, executor_id, metas) in (
            self.stage_manager.completed_partitions(job_id, stage_id)
        ):
            meta_exec = self.executor_manager.get_executor_metadata(executor_id)
            host = meta_exec.host if meta_exec else "localhost"
            port = meta_exec.port if meta_exec else 0
            for m in metas:
                locs[m.partition_id].append(
                    PartitionLocation(
                        job_id=job_id,
                        stage_id=stage_id,
                        partition=m.partition_id,
                        executor_id=executor_id,
                        host=host,
                        port=port,
                        path=m.path,
                    )
                )
        return locs

    # -- event handlers ------------------------------------------------------
    def _on_stage_finished(self, job_id: str, stage_id: int) -> None:
        """Promote pending parents whose deps are all complete (ref
        :107-122)."""
        job = self.jobs.get(job_id)
        if job is None:
            return
        for parent in self.stage_manager.parents_of(job_id, stage_id):
            if not self.stage_manager.is_pending_stage(job_id, parent):
                continue
            unresolved = find_unresolved_shuffles(job.stages[parent].plan)
            if all(
                self.stage_manager.is_completed_stage(job_id, u.stage_id)
                for u in unresolved
            ):
                self._resolve_stage(job_id, parent)
                self.stage_manager.promote_pending_stage(job_id, parent)

    def _on_job_finished(self, job_id: str) -> None:
        """Assemble CompletedJob locations (ref :370-388, :416-473)."""
        job = self.jobs.get(job_id)
        if job is None:
            return
        final = job.stages[job.final_stage_id]
        locs = self._stage_output_locations(
            job_id, job.final_stage_id, final.output_partition_count
        )
        flat: list[PartitionLocation] = []
        for part in locs:
            flat.extend(part)
        job.completed_locations = flat
        job.status = "completed"
        if self.state is not None:
            self.state.save_job(job)
        log.info("job %s completed (%d partitions)", job_id, len(flat))

    def _on_job_failed(self, job_id: str, error: str) -> None:
        job = self.jobs.get(job_id)
        if job is None:
            return
        job.status = "failed"
        job.error = error
        if self.state is not None:
            self.state.save_job(job)
        log.error("job %s failed: %s", job_id, error)

    # -- task handout (pull mode; ref grpc.rs:121-147) -----------------------
    def next_task(self, executor_id: str) -> pb.TaskDefinition | None:
        pick = self.stage_manager.fetch_schedulable_stage()
        if pick is None:
            return None
        job_id, stage_id = pick
        pending = self.stage_manager.fetch_pending_tasks(job_id, stage_id, 1)
        if not pending:
            return None
        partition = pending[0]
        task_id = PartitionId(job_id, stage_id, partition)
        events = self.stage_manager.update_task_status(
            task_id, TaskState.RUNNING, executor_id=executor_id
        )
        for e in events:
            self.event_loop.post(e)
        job = self.jobs[job_id]
        plan_bytes = job.resolved_plan_bytes.get(stage_id)
        if plan_bytes is None:
            self._resolve_stage(job_id, stage_id)
            plan_bytes = job.resolved_plan_bytes[stage_id]
        cfg = self.sessions.get(job.session_id, self.config)
        return pb.TaskDefinition(
            task_id=pb.PartitionId(
                job_id=job_id, stage_id=stage_id, partition_id=partition
            ),
            plan=plan_bytes,
            props=[
                pb.KeyValuePair(key=k, value=v)
                for k, v in cfg.settings().items()
            ],
            session_id=job.session_id,
        )

    def apply_task_statuses(self, statuses: list[pb.TaskStatus]) -> None:
        """ref scheduler_server/mod.rs update_task_status :171-191."""
        for st in statuses:
            tid = PartitionId(
                st.task_id.job_id, st.task_id.stage_id, st.task_id.partition_id
            )
            kind = st.WhichOneof("status")
            if kind == "completed":
                metas = [
                    ShuffleWritePartitionMeta(
                        partition_id=int(p.partition_id),
                        path=p.path,
                        num_batches=int(p.num_batches),
                        num_rows=int(p.num_rows),
                        num_bytes=int(p.num_bytes),
                    )
                    for p in st.completed.partitions
                ]
                events = self.stage_manager.update_task_status(
                    tid,
                    TaskState.COMPLETED,
                    executor_id=st.completed.executor_id,
                    partitions=metas,
                )
            elif kind == "failed":
                events = self.stage_manager.update_task_status(
                    tid, TaskState.FAILED, error=st.failed.error
                )
            elif kind == "running":
                events = self.stage_manager.update_task_status(
                    tid, TaskState.RUNNING, executor_id=st.running.executor_id
                )
            else:
                events = []
            for e in events:
                self.event_loop.post(e)

    def job_status_proto(self, job_id: str) -> pb.JobStatus:
        job = self.jobs.get(job_id)
        if job is None:
            return pb.JobStatus(failed=pb.FailedJob(error="unknown job"))
        if job.status == "queued":
            return pb.JobStatus(queued=pb.QueuedJob())
        if job.status == "running":
            return pb.JobStatus(running=pb.RunningJob())
        if job.status == "failed":
            return pb.JobStatus(failed=pb.FailedJob(error=job.error))
        return pb.JobStatus(
            completed=pb.CompletedJob(
                partition_location=[
                    loc_to_proto(l) for l in job.completed_locations
                ]
            )
        )

    def shutdown(self) -> None:
        self.event_loop.stop()


class SchedulerGrpcServicer:
    """The gRPC surface (ref grpc.rs:57-553)."""

    def __init__(self, server: SchedulerServer):
        self.s = server

    def PollWork(self, request: pb.PollWorkParams, context):
        meta = request.metadata
        em = ExecutorMetadata(
            id=meta.id,
            host=meta.host,
            port=meta.port,
            grpc_port=meta.grpc_port,
            specification=ExecutorSpecification(
                task_slots=meta.specification.task_slots or 4
            ),
        )
        self.s.executor_manager.save_executor_metadata(em)
        self.s.executor_manager.save_executor_heartbeat(meta.id)
        self.s.persist_executor(em)
        if self.s.executor_manager.get_executor_data(meta.id) is None:
            self.s.executor_manager.save_executor_data(
                ExecutorData(
                    meta.id,
                    em.specification.task_slots,
                    em.specification.task_slots,
                )
            )
        self.s.apply_task_statuses(list(request.task_status))
        result = pb.PollWorkResult()
        if request.can_accept_task:
            task = self.s.next_task(meta.id)
            if task is not None:
                result.task.CopyFrom(task)
        return result

    def RegisterExecutor(self, request, context):
        meta = request.metadata
        em = ExecutorMetadata(
            id=meta.id,
            host=meta.host,
            port=meta.port,
            grpc_port=meta.grpc_port,
            specification=ExecutorSpecification(
                task_slots=meta.specification.task_slots or 4
            ),
        )
        self.s.executor_manager.save_executor_metadata(em)
        self.s.executor_manager.save_executor_heartbeat(meta.id)
        self.s.persist_executor(em)
        self.s.executor_manager.save_executor_data(
            ExecutorData(
                meta.id, em.specification.task_slots, em.specification.task_slots
            )
        )
        return pb.RegisterExecutorResult(success=True)

    def HeartBeatFromExecutor(self, request, context):
        self.s.executor_manager.save_executor_heartbeat(request.executor_id)
        return pb.HeartBeatResult(reregister=False)

    def UpdateTaskStatus(self, request, context):
        self.s.apply_task_statuses(list(request.task_status))
        n_done = sum(
            1
            for st in request.task_status
            if st.WhichOneof("status") in ("completed", "failed")
        )
        if n_done:
            self.s.executor_manager.update_executor_data(
                request.executor_id, n_done
            )
        return pb.UpdateTaskStatusResult(success=True)

    def GetFileMetadata(self, request, context):
        """Parquet-only schema inference (ref grpc.rs:279-326)."""
        import pyarrow.parquet as papq

        from ballista_tpu.columnar.arrow_interop import schema_from_arrow
        from ballista_tpu.serde import schema_to_proto

        if request.file_type not in ("parquet", ""):
            context.abort(
                __import__("grpc").StatusCode.INVALID_ARGUMENT,
                f"unsupported file type {request.file_type!r}",
            )
        schema = schema_from_arrow(papq.read_schema(request.path))
        return pb.GetFileMetadataResult(schema=schema_to_proto(schema))

    def ExecuteQuery(self, request, context):
        settings = {kv.key: kv.value for kv in request.settings}
        session_id = self.s.get_or_create_session(request.session_id, settings)
        kind = request.WhichOneof("query")
        if kind is None:
            # session-create-only call (ref context.rs remote() :83-135)
            return pb.ExecuteQueryResult(job_id="", session_id=session_id)
        try:
            if kind == "sql":
                job_id = self.s.submit_sql(request.sql, session_id)
            else:
                from ballista_tpu.serde import logical_from_proto

                node = pb.LogicalPlanNode()
                node.ParseFromString(request.logical_plan)
                job_id = self.s.submit_logical(
                    logical_from_proto(node), session_id
                )
        except Exception as e:  # noqa: BLE001
            log.exception("ExecuteQuery failed")
            job_id = generate_job_id()
            self.s.jobs[job_id] = JobInfo(
                job_id=job_id, session_id=session_id, status="failed",
                error=str(e),
            )
        return pb.ExecuteQueryResult(job_id=job_id, session_id=session_id)

    def GetJobStatus(self, request, context):
        return pb.GetJobStatusResult(
            status=self.s.job_status_proto(request.job_id)
        )


def start_scheduler_grpc(
    server: SchedulerServer, host: str = "0.0.0.0", port: int = 0
):
    """Start the gRPC server; returns (grpc_server, bound_port)."""
    import grpc as _grpc

    from ballista_tpu.scheduler.rpc import (
        SCHEDULER_METHODS,
        SCHEDULER_SERVICE,
        add_service,
    )

    gs = _grpc.server(
        __import__("concurrent.futures", fromlist=["ThreadPoolExecutor"])
        .ThreadPoolExecutor(max_workers=16)
    )
    add_service(gs, SCHEDULER_SERVICE, SCHEDULER_METHODS, SchedulerGrpcServicer(server))
    bound = gs.add_insecure_port(f"{host}:{port}")
    gs.start()
    return gs, bound
