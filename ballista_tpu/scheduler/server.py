"""SchedulerServer: query/stage orchestration + gRPC service.

Combines the reference's SchedulerServer (scheduler_server/mod.rs:54-232),
gRPC handlers (scheduler_server/grpc.rs:57-553), and QueryStageScheduler
event loop (scheduler_server/query_stage_scheduler.rs:40-473):

  ExecuteQuery -> plan (SQL -> logical -> optimized -> physical)
              -> JobSubmitted event -> DistributedPlanner stage split
              -> stage DAG submit (running if deps resolved, else pending)
  PollWork    -> heartbeat + apply statuses + hand out <=1 task (pull mode)
  StageFinished -> resolve dependent stages (patch shuffle locations)
  JobFinished -> assemble CompletedJob partition locations
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import random
import string
import threading

from ballista_tpu.analysis.witness import make_lock
from ballista_tpu.config import BallistaConfig, TaskSchedulingPolicy
from ballista_tpu.distributed_plan import (
    DistributedPlanner,
    QueryStage,
    find_unresolved_shuffles,
    remove_unresolved_shuffles,
)
from ballista_tpu.errors import (
    PlanError,
    RewriteRejected,
    error_is_retryable,
    parse_shuffle_fetch_error,
)
from ballista_tpu.event_loop import EventAction, EventLoop
from ballista_tpu.exec.base import ExecutionPlan
from ballista_tpu.exec.planner import PhysicalPlanner, TableProvider
from ballista_tpu.plan.optimizer import optimize
from ballista_tpu.proto import pb
from ballista_tpu.scheduler.executor_manager import ExecutorManager
from ballista_tpu.scheduler.stage_manager import (
    JobFailed,
    JobFinished,
    StageFinished,
    StageManager,
    TaskRescheduled,
    TaskState,
)
from ballista_tpu.scheduler_types import (
    ExecutorData,
    ExecutorMetadata,
    ExecutorSpecification,
    PartitionId,
    PartitionLocation,
    ShuffleWritePartitionMeta,
)
from ballista_tpu.serde import BallistaCodec, loc_to_proto
from ballista_tpu.sql import ast
from ballista_tpu.sql.parser import parse_sql
from ballista_tpu.sql.planner import SqlPlanner

log = logging.getLogger(__name__)


def generate_job_id() -> str:
    """7-char alnum ids (ref grpc.rs:546-553)."""
    return "".join(  # detlint: nondet=id-minting
        random.choices(string.ascii_lowercase + string.digits, k=7)
    )


def _stage_dependencies(stages) -> dict[int, set[int]]:
    """child stage id -> parent stage ids (parents consume the child),
    recomputed from placeholders — shared by initial stage generation and
    the certified-rewrite swap (exchange injection/removal changes the
    edge set)."""
    deps: dict[int, set[int]] = {}
    for stage in stages:
        for u in find_unresolved_shuffles(stage.plan):
            deps.setdefault(u.stage_id, set()).add(stage.stage_id)
    return deps


class _MeshPlanningHandle:
    """Stand-in MeshRuntime used ONLY during scheduler-side planning: the
    Mesh*Exec constructors store it without touching devices, the serde
    encoder never serializes it, and the decoding executor replaces it
    with a real MeshRuntime over its own mesh. Executing a plan holding
    this handle is a bug — fail loudly."""

    mesh = None
    runner = None

    def place(self, *_a, **_k):  # pragma: no cover
        raise PlanError(
            "planning-only mesh handle executed on the scheduler; mesh "
            "stages must run on a mesh-capable executor"
        )


@dataclasses.dataclass
class JobInfo:
    job_id: str
    session_id: str
    status: str = "queued"  # queued | running | failed | completed
    error: str = ""
    stages: dict[int, QueryStage] = dataclasses.field(default_factory=dict)
    # child stage id -> parent stage ids (parents consume the child)
    dependencies: dict[int, set[int]] = dataclasses.field(default_factory=dict)
    final_stage_id: int = 0
    completed_locations: list[PartitionLocation] = dataclasses.field(
        default_factory=list
    )
    # resolved (shuffle-patched) serialized plans, per stage. Invalidated
    # for a consumer stage whenever a dependency's shuffle output is lost
    # (the stage's pristine plan in `stages` is then re-resolved against
    # refreshed locations once the producer re-completes).
    resolved_plan_bytes: dict[int, bytes] = dataclasses.field(
        default_factory=dict
    )
    # eager-shuffle (docs/shuffle.md): session flag snapshot + serialized
    # EAGER resolutions per stage. Eager plans carry no locations (readers
    # poll), so unlike resolved_plan_bytes they are never invalidated by
    # lost-shuffle recovery.
    eager: bool = False
    eager_plan_bytes: dict[int, bytes] = dataclasses.field(
        default_factory=dict
    )
    # retry policy snapshot (session config at submission) + visibility
    # counters that outlive the per-stage bookkeeping (torn down at job
    # completion): bounded task retries + lost-shuffle recompute rounds
    max_attempts: int = 3
    total_retries: int = 0
    total_recomputes: int = 0
    # certified plan rewrites (ballista_tpu/rewrite.py): accepted swaps of
    # stage templates + certificate-validation rejections (visibility for
    # REST and the chaos suites; both 0 on a non-adaptive run)
    total_rewrites: int = 0
    total_rewrite_rejects: int = 0
    # per-rewrite decision log (docs/aqe.md): one dict per
    # apply_certified_rewrite call — op, stage ids, outcome, and the
    # failing certificate clause on a reject — served by /api/job/<id>
    # so the UI can explain WHY a stage's shape changed mid-job
    rewrite_log: list = dataclasses.field(default_factory=list)
    # stage ids touched by ACCEPTED rewrites (the /timeline "rewritten"
    # marker: a Gantt row whose partition count changed mid-job says so)
    rewritten_stages: set = dataclasses.field(default_factory=set)
    # AQE policy decisions (scheduler/aqe.py): applied/rejected/learned,
    # with before/after stats — the policy-level view layered over
    # rewrite_log
    aqe_decisions: list = dataclasses.field(default_factory=list)
    # observability (docs/observability.md). trace_id is minted at
    # submission when the session's ballista.tpu.trace is not "off";
    # empty trace_id IS the zero-overhead off path (no span is ever
    # created for this job anywhere in the system).
    trace_id: str = ""
    root_span_id: str = ""
    # open stage spans (obs.trace.Span), by stage id — their span_id is
    # the parent stamped onto task-attempt props
    stage_spans: dict = dataclasses.field(default_factory=dict)
    # the job's reassembled span store, keyed by span_id (dict = dedup:
    # in-proc standalone clusters can see a scheduler-recorded span come
    # back through the executor shipping path)
    spans: dict = dataclasses.field(default_factory=dict)
    # per-(stage_id, partition) operator-metric records shipped home in
    # CompletedTask (obs.profile.operator_metrics shape)
    op_metrics: dict = dataclasses.field(default_factory=dict)
    # per-stage/per-task stats snapshot taken at job completion/failure —
    # the stage bookkeeping is torn down then, and /api/job must keep
    # serving the run's stats afterwards
    stage_stats: list | None = None
    # the OPEN root span (finished at job completion/failure)
    root_span: object = None
    # fleet observability (docs/observability.md): the query-class label
    # (obs.qclass.plan_class — repeated query shapes share one series),
    # submission + first-task-assignment timestamps (queue wait = the
    # gap), and the skew monitor's flagged (stage, partition) pairs
    query_class: str = "unknown"
    submitted_s: float = 0.0
    first_assign_s: float = 0.0
    skew_flags: list = dataclasses.field(default_factory=list)
    # cost accounting (docs/observability.md): the job's aggregated
    # resource cost vector (obs.history.CostVector), summed from every
    # attempt's shipped cost — failed/retried/recomputed attempts
    # included, because the tenant paid for them too. None until the
    # first costed attempt reports (accounting off = stays None).
    cost: object = None
    # serving fast path (docs/serving.md): the result-cache key this
    # job's committed result will be stored under (None = uncacheable or
    # cache off); the cached Arrow IPC payload when the job was SERVED
    # from the cache (GetJobStatus ships it in CompletedJob.result_ipc);
    # and the single-stage-bypass flag (task granted/completed outside
    # the stage state machine).
    cache_key: object = None
    result_ipc: bytes = b""
    bypass: bool = False


@dataclasses.dataclass(frozen=True)
class JobSubmitted:
    job_id: str
    plan: ExecutionPlan


@dataclasses.dataclass(frozen=True)
class ReviveOffers:
    """Push-mode dispatch tick (ref scheduler_server/event_loop.rs:35-169:
    SchedulerServerEvent::ReviveOffers)."""

    n: int = 1


class QueryStageScheduler(EventAction):
    """The stage DAG state machine (ref query_stage_scheduler.rs:40-473)."""

    def __init__(self, server: "SchedulerServer"):
        self.server = server

    def on_receive(self, event):
        s = self.server
        if isinstance(event, ReviveOffers):
            s._offer_resources()
            return None
        if isinstance(event, JobSubmitted):
            try:
                s._generate_stages(event.job_id, event.plan)
            except Exception as e:  # noqa: BLE001
                # stage persistence/serialization failures after planning
                # must FAIL the job — an escaped exception here previously
                # left it "running" forever (clients poll indefinitely)
                log.exception("stage submission failed for %s", event.job_id)
                s._on_job_failed(
                    event.job_id, f"stage submission failed: {e}"
                )
        elif isinstance(event, TaskRescheduled):
            s._on_task_rescheduled(event)
        elif isinstance(event, StageFinished):
            s._on_stage_finished(event.job_id, event.stage_id)
        elif isinstance(event, JobFinished):
            s._on_job_finished(event.job_id)
        elif isinstance(event, JobFailed):
            s._on_job_failed(event.job_id, event.error)
        else:
            log.warning("unknown scheduler event %r", event)
            return None
        # push mode: every stage/job event can unlock work — re-offer (ref
        # query_stage_scheduler.rs:403-408)
        if s.policy == TaskSchedulingPolicy.PUSH_STAGED:
            return ReviveOffers()
        return None


class SchedulerServer:
    """State + event loop. The gRPC servicer (:class:`SchedulerGrpcServicer`)
    and the REST API both drive this object."""

    def __init__(
        self,
        provider: TableProvider,
        config: BallistaConfig | None = None,
        state_backend=None,
        namespace: str = "default",
        policy: TaskSchedulingPolicy = TaskSchedulingPolicy.PULL_STAGED,
        executor_timeout_s: float = 60.0,
        expiry_check_interval_s: float = 15.0,
    ):
        """``state_backend``: a
        :class:`ballista_tpu.scheduler.state_backend.StateBackendClient`;
        when given, executors/sessions/jobs/stage-plans write through to it
        and a new SchedulerServer over the same backend recovers them (ref
        persistent_state.rs:85-181 + the restart test :401-525)."""
        self.provider = provider
        self.config = config or BallistaConfig()
        # the scheduler plans queries, so it must resolve UDF names too
        # (plugin.py contract: client, scheduler, and executors all load
        # the same plugin dir; $BALLISTA_PLUGIN_DIR is always consulted)
        from ballista_tpu.plugin import load_plugins

        load_plugins(self.config.plugin_dir() or None)
        self.codec = BallistaCodec(provider=provider)
        self.stage_manager = StageManager()
        self.executor_manager = ExecutorManager()
        self.jobs: dict[str, JobInfo] = {}
        self.sessions: dict[str, BallistaConfig] = {}
        self.policy = policy
        # push mode: the scheduler dials each executor's gRPC back at
        # registration (ref grpc.rs:180-192) and launches tasks through it
        self.executor_clients: dict[str, object] = {}
        self._executor_channels: dict[str, object] = {}
        # consecutive LaunchTask failures per executor; an executor that
        # heartbeats but can't be dialed (NAT, bad --external-host) would
        # otherwise soak offers forever
        self._launch_failures: dict[str, int] = {}
        self.max_launch_failures = 3
        self._lock = make_lock("SchedulerServer._lock", reentrant=True)
        # observability (docs/observability.md): trace_id -> job_id for
        # span ingestion from executor RPCs, and the cross-job counter
        # aggregation the /api/metrics plane serves — both guarded by
        # _lock like the job map they shadow. _obs_retained bounds the
        # HEAVY per-job payloads (spans, op_metrics, stage_stats) across
        # terminal jobs: the jobs dict itself has always kept light
        # JobInfo records forever, but with the shipping collector
        # default-on every completed task now adds per-operator records —
        # unbounded retention would leak a long-lived scheduler dry.
        self._traces: dict[str, str] = {}
        self.obs_task_counters: dict[str, float] = {}
        self._obs_retained: collections.deque = collections.deque()
        self.obs_retained_jobs = 50
        # fleet-level distributional plane (docs/observability.md): an
        # INSTANCE registry (never the executor-process module registry —
        # an in-proc standalone cluster would double-count shipped
        # deltas) holding the scheduler's own latency observations plus
        # everything executors ship home on poll/heartbeat
        from ballista_tpu.obs import hist as obs_hist

        self.hists = obs_hist.Registry("scheduler")
        self._h_job_latency = self.hists.histogram(
            "ballista_job_latency_seconds",
            "End-to-end job latency (submit -> completed) by query class",
            ("class",),
        )
        self._h_queue_wait = self.hists.histogram(
            "ballista_queue_wait_seconds",
            "Queue wait (submit -> first task assignment) by query class",
            ("class",),
        )
        self._h_stage_task = self.hists.histogram(
            "ballista_stage_task_seconds",
            "Per-task durations by query class and stage",
            ("class", "stage"),
        )
        self._h_dispatch_lag = self.hists.histogram(
            "ballista_event_dispatch_lag_seconds",
            "Scheduler event-loop dispatch lag (post -> handler entry)",
            (),
        )
        # straggler/skew counters by query class + the recent queue-wait
        # window the composite autoscale signal reads (p90 of the last N
        # waits — a cumulative histogram cannot answer "right now").
        # Entries are (recorded_at, wait_s): the p90 is computed over a
        # RECENCY window, not just the last N samples — with no arrivals
        # nothing new is appended, and a count-only window would keep a
        # burst's waits applying the 4x scale-up long after the queue
        # drained.
        self.obs_straggler_total: dict[str, int] = {}
        self.obs_skew_total: dict[str, int] = {}
        self._recent_queue_waits: collections.deque = collections.deque(
            maxlen=64
        )
        self.queue_wait_window_s = 120.0
        # bounded label cardinality (no-silent-caps): the class
        # fingerprint keeps literal differences distinct, so a
        # parameterized workload (WHERE id = <user>) could mint one
        # class per literal — every class creates never-evicted
        # histogram children here AND on every executor. Beyond the cap,
        # new shapes aggregate under "overflow" and the overflow is
        # COUNTED (ballista_query_class_overflow_total).
        self._known_classes: set[str] = set()
        self.max_query_classes = 256
        self.obs_class_overflow = 0
        # per-query-class resource-cost rollup (docs/observability.md):
        # the ballista_job_cost_total counter family /api/metrics serves.
        # Guarded by _lock like the other obs aggregations.
        self.obs_class_cost: dict[str, dict[str, float]] = {}
        # queryable history (docs/observability.md): the append-only
        # job-lifecycle log. Written through the SAME state backend the
        # scheduler persists to — on sqlite/etcd it survives restarts;
        # without a configured backend an in-process MemoryBackend keeps
        # the surface (REST /api/history, system.queries) alive for the
        # process lifetime. Constructed BEFORE _recover_state so recovery
        # can terminal-record jobs that died with the old scheduler.
        from ballista_tpu.obs.history import HistoryStore

        if state_backend is None:
            from ballista_tpu.scheduler.state_backend import MemoryBackend

            history_backend = MemoryBackend()
        else:
            history_backend = state_backend
        self.history = HistoryStore(
            history_backend,
            namespace,
            retention_jobs=self.config.history_retention_jobs(),
        )
        # adaptive query execution (docs/aqe.md): the policy engine that
        # reads runtime stats and applies certified rewrites; inert
        # unless the session's ballista.tpu.aqe (or BALLISTA_AQE) turns
        # it on. The counter map feeds
        # ballista_aqe_rewrites_total{op,outcome} on /api/metrics.
        from ballista_tpu.scheduler.aqe import AqePolicy

        self.aqe = AqePolicy(self)
        self.obs_aqe_total: dict[tuple[str, str], int] = {}
        # serving fast path (docs/serving.md). Result cache: capacity
        # comes from the SCHEDULER's config (sessions cannot resize a
        # shared cache); keys fold in the session settings, so different
        # sessions never collide. In-memory only by design — a restarted
        # scheduler starts cold, which is the no-stale-serve-after-
        # _recover_state contract. Bypass bookkeeping: jobs granted
        # outside the stage state machine, all guarded by _lock.
        from ballista_tpu.scheduler.result_cache import ResultCache

        self.result_cache = ResultCache(self.config.result_cache_mb() << 20)
        self._bypass_pending: collections.deque = collections.deque()
        self._bypass_running: dict[str, str] = {}  # job_id -> executor_id
        self._bypass_attempts: dict[str, int] = {}
        self.obs_bypass_total = 0
        self.state = None
        if state_backend is not None:
            from ballista_tpu.scheduler.persistent_state import (
                PersistentSchedulerState,
            )

            self.state = PersistentSchedulerState(
                state_backend, namespace, self.codec
            )
            self._recover_state()
        self.event_loop = EventLoop("query-stage", QueryStageScheduler(self))
        # dispatch-lag metering: installed BEFORE start so every event is
        # enveloped; the observe is lock-cheap and allocation-free
        self.event_loop.lag_cb = self._h_dispatch_lag.labels().observe
        self.event_loop.start()
        import time as _time

        self.start_time = _time.time()
        # executor-lost recovery: periodic expiry sweep (ref
        # executor_manager.rs:55-77 expire_dead_executors + the
        # RUNNING->PENDING reset transition stage_manager.rs:553-558)
        self.executor_timeout_s = executor_timeout_s
        self._expiry_stop = threading.Event()
        self._expiry_thread = threading.Thread(
            target=self._expiry_loop,
            args=(expiry_check_interval_s,),
            daemon=True,
            name="executor-expiry",
        )
        self._expiry_thread.start()

    def _expiry_loop(self, interval_s: float) -> None:
        while not self._expiry_stop.wait(interval_s):
            try:
                self.check_expired_executors()
            except Exception:  # noqa: BLE001
                log.exception("executor expiry sweep failed")

    def check_expired_executors(self) -> list[str]:
        """Detect heartbeat-expired executors, reset their RUNNING tasks to
        PENDING, invalidate their COMPLETED shuffle outputs that downstream
        stages still need (lost-shuffle recovery — the files died with the
        executor), drop them from slot accounting, and re-offer. Returns
        the expired executor ids (exposed for tests and the REST /state
        view)."""
        em = self.executor_manager
        # read tracked BEFORE alive: an executor registering between the two
        # snapshots is then in alive-but-not-tracked (harmless) instead of
        # tracked-but-not-alive (would be expired at birth, resetting its
        # just-launched tasks into duplicate execution)
        tracked = em.tracked_executors()
        alive = em.get_alive_executors(self.executor_timeout_s)
        expired = tracked - alive
        if not expired:
            return []
        for eid in expired:
            self._drop_executor(eid)
        # bypass grants die with their executor exactly like RUNNING
        # stage tasks: requeue without charging an attempt (the blame is
        # the executor's, not the task's) — docs/serving.md
        with self._lock:
            lost_bypass = sorted(
                jid
                for jid, ex in self._bypass_running.items()
                if ex in expired
            )
            for jid in lost_bypass:
                del self._bypass_running[jid]
                self._bypass_pending.append(jid)
        reset = self.stage_manager.reset_tasks_of_executors(expired)
        log.warning(
            "executors %s expired; reset %d running tasks", expired, len(reset)
        )
        # completed shuffle output hosted on a dead executor is gone; any
        # stage with an incomplete consumer must recompute the lost map
        # partitions (a stage whose consumers all finished is left alone —
        # its output will never be read again)
        recovered = False
        for job_id, stage_id in self.stage_manager.stages_with_outputs_of(
            expired
        ):
            consumers = self.stage_manager.parents_of(job_id, stage_id)
            if consumers and all(
                self.stage_manager.is_completed_stage(job_id, c)
                for c in consumers
            ):
                continue
            job = self._get_job(job_id)
            if not consumers and job is not None:
                # final stage of a still-running job: its output is the
                # job result the client fetches — recompute it too
                if job.final_stage_id != stage_id:
                    continue
            for eid in sorted(expired):
                if self._on_shuffle_lost(job_id, stage_id, eid):
                    recovered = True
        if (reset or recovered) and (
            self.policy == TaskSchedulingPolicy.PUSH_STAGED
        ):
            self.event_loop.post(ReviveOffers())
        return sorted(expired)

    # -- locked accessors (racelint unguarded-field discipline) --------------
    def _get_job(self, job_id: str) -> JobInfo | None:
        """``self.jobs`` is written under ``_lock`` (submission, recovery);
        every cross-thread read goes through here. Also closes the
        teardown race: a job removed between a stage pick and its use now
        surfaces as ``None`` instead of a ``KeyError``."""
        with self._lock:
            return self.jobs.get(job_id)

    def _session_config(self, session_id: str) -> BallistaConfig:
        with self._lock:
            return self.sessions.get(session_id, self.config)

    def _recover_state(self) -> None:
        """Rebuild in-memory state from the backend on restart (ref
        persistent_state.rs init :85-181). Runs under the lock: it is
        called from ``__init__`` today, but it writes the same maps the
        gRPC threads read, and the lock keeps that true if recovery is
        ever re-run live."""
        with self._lock:
            for em in self.state.load_executors():
                self.executor_manager.save_executor_metadata(em)
            for sid, settings in self.state.load_sessions().items():
                try:
                    self.sessions[sid] = (
                        BallistaConfig(settings) if settings else self.config
                    )
                except Exception:  # noqa: BLE001 — stale/unknown keys
                    self.sessions[sid] = self.config
            for rec in self.state.load_jobs():
                job = JobInfo(
                    job_id=rec["job_id"],
                    session_id=rec["session_id"],
                    status=rec["status"],
                    error=rec.get("error", ""),
                    final_stage_id=rec.get("final_stage_id", 0),
                )
                job.dependencies = {
                    int(k): set(v)
                    for k, v in rec.get("dependencies", {}).items()
                }
                job.completed_locations = self.state.locations_from_json(
                    rec.get("locations", [])
                )
                plans = self.state.load_stage_plans(job.job_id)
                for stage_id, plan in plans.items():
                    job.stages[stage_id] = QueryStage(
                        job.job_id, stage_id, plan
                    )
                if job.status in ("queued", "running"):
                    # tasks in flight died with the old scheduler; fail
                    # loudly rather than dangle (running StageManager state
                    # is not persisted, matching the reference)
                    job.status = "failed"
                    job.error = "scheduler restarted while job was in flight"
                    self.state.save_job(job)
                    # the history log must agree with the job record: the
                    # predecessor wrote "submitted" but never a terminal
                    # record — close it out so system.queries never shows
                    # an eternally-submitted ghost
                    try:
                        self.history.record_terminal(
                            job.job_id, "failed", error=job.error,
                            session_id=job.session_id,
                        )
                    except Exception:  # noqa: BLE001 — history is
                        # observability, never recovery-critical
                        log.exception(
                            "history terminal record failed for %s",
                            job.job_id,
                        )
                self.jobs[job.job_id] = job
            if self.jobs:
                log.info(
                    "recovered %d jobs, %d sessions from state backend",
                    len(self.jobs), len(self.sessions),
                )

    # -- session management (ref grpc.rs:350-374) ----------------------------
    def get_or_create_session(
        self, session_id: str, settings: dict[str, str]
    ) -> str:
        plugin_dir = (settings or {}).get("ballista.plugin_dir")
        if plugin_dir:
            from ballista_tpu.plugin import load_plugins

            load_plugins(plugin_dir)
        with self._lock:
            if session_id and session_id in self.sessions:
                if settings:
                    self.sessions[session_id] = BallistaConfig(settings)
                return session_id
            new_id = "".join(  # detlint: nondet=id-minting
                random.choices(string.ascii_lowercase + string.digits, k=16)
            )
            self.sessions[new_id] = (
                BallistaConfig(settings) if settings else self.config
            )
            if self.state is not None:
                self.state.save_session(new_id, settings or {})
            return new_id

    def persist_executor(self, em: ExecutorMetadata) -> None:
        if self.state is not None:
            self.state.save_executor_metadata(em)

    # -- query submission ----------------------------------------------------
    def submit_sql(self, sql: str, session_id: str) -> str:
        stmt = parse_sql(sql)
        if not isinstance(stmt, (ast.Select, ast.SetOp)):
            raise PlanError("ExecuteQuery requires a SELECT statement")
        logical = SqlPlanner(self.provider).plan(stmt)
        return self.submit_logical(logical, session_id)

    def _mint_trace(self, cfg) -> dict | None:
        """Start a job trace when the session's ``ballista.tpu.trace`` is
        not off (docs/observability.md): a fresh trace_id, the open root
        span, and a list the pre-job-id plan/verify spans accumulate in.
        None (no allocation anywhere downstream) when tracing is off."""
        mode = cfg.trace()
        if mode == "off":
            return None
        from ballista_tpu.obs import trace as obs_trace

        obs_trace.configure(mode)
        trace_id = obs_trace.new_trace_id()
        return {
            "trace_id": trace_id,
            "root": obs_trace.start("job", trace_id),
            "pre": [],
        }

    @staticmethod
    def _trace_step(tctx: dict | None, name: str):
        """Context manager recording one plan/verify span under the
        pending job's root (no-op when tracing is off)."""
        import contextlib

        if tctx is None:
            return contextlib.nullcontext()
        from ballista_tpu.obs import trace as obs_trace

        @contextlib.contextmanager
        def step():
            s = obs_trace.start(
                name, tctx["trace_id"], tctx["root"].span_id
            )
            try:
                yield s
            except BaseException as e:
                s.outcome = "error"
                s.attrs["error"] = type(e).__name__
                raise
            finally:
                obs_trace.finish(s, s.outcome)
                tctx["pre"].append(s)

        return step()

    def submit_logical(self, logical, session_id: str) -> str:
        cfg = self._session_config(session_id)
        tctx = self._mint_trace(cfg)
        verify = cfg.verify_plans()
        with self._trace_step(tctx, "plan"):
            optimized = optimize(logical)
            # serving fast path (docs/serving.md): a repeated identical
            # query over unchanged data is answered from the result
            # cache right here — no physical planning, no stages, no
            # executor. The key folds in the session settings and the
            # provider's data versions; result_cache_key returns None
            # (uncacheable, counted as a miss) for system.* scans or
            # when no data-version-capable provider is attached.
            cache_key = None
            if self.result_cache.enabled:
                from ballista_tpu.scheduler.result_cache import (
                    result_cache_key,
                )

                cache_key = result_cache_key(optimized, cfg, self.provider)
                entry = self.result_cache.get(cache_key)
                if entry is not None:
                    from ballista_tpu.analysis import stalewitness

                    if stalewitness.enabled() and stalewitness.should_sample(
                        "result_cache"
                    ):
                        # staleness witness (docs/analysis.md): demote
                        # this sampled hit to a miss — the job runs
                        # fresh through the full stage machinery, and
                        # the committed repopulation must hash-match
                        # what this hit WOULD have served
                        # (_populate_result_cache resolves the pending
                        # expectation)
                        from ballista_tpu.analysis import replay
                        from ballista_tpu.scheduler.result_cache import (
                            ipc_to_table,
                        )

                        stalewitness.expect(
                            "result_cache", cache_key,
                            replay.canonical_hash(ipc_to_table(entry[0])),
                            payload=entry[0],
                        )
                    else:
                        return self._serve_cached_result(
                            entry, session_id, trace=tctx
                        )
            if verify:
                # submission-time gate: reject inconsistent plans with a
                # typed PlanVerificationError (naming the operator path)
                # BEFORE any stage exists — the client sees it as the
                # job-submission failure rather than an executor task
                # failure minutes later
                with self._trace_step(tctx, "verify_logical"):
                    from ballista_tpu.analysis import verify_logical

                    verify_logical(optimized)
            # distributed=True inserts HashRepartitionExec exchange
            # boundaries (honoring ballista.repartition.*) so the stage
            # splitter can cut multi-partition hash shuffles (ref
            # planner.rs:133-157)
            physical = PhysicalPlanner(
                self.provider,
                cfg.default_shuffle_partitions(),
                config=cfg,
                distributed=True,
                mesh_runtime=self._mesh_planning_runtime(cfg),
            ).plan(optimized)
            if verify:
                with self._trace_step(tctx, "verify_physical"):
                    from ballista_tpu.analysis import verify_physical

                    verify_physical(physical)
        return self.submit_physical(
            physical, session_id, trace=tctx, cache_key=cache_key
        )

    def _mesh_planning_runtime(self, cfg):
        """Planning-only mesh handle: when the session keeps collective
        shuffle on AND some alive executor advertises >= 2 devices
        (ExecutorSpecification.n_devices), the plan lowers repartitioned
        aggregates / partitioned joins / bounded sorts to Mesh*Exec.
        Between shuffle boundaries those fuse a whole chain
        (scan -> join -> aggregate) into ONE task that the mesh-capable
        executor runs as a single shard_map program with all_to_all over
        its device mesh — the scheduler itself never executes this handle
        (the decoding executor binds its own MeshRuntime via serde).
        SURVEY build-order #6: stage placement onto TPU slices."""
        if not cfg.collective_shuffle():
            return None
        alive = self.executor_manager.get_alive_executors(
            self.executor_timeout_s
        )
        capable = any(
            (em.specification.n_devices or 1) >= 2
            for em in self.executor_manager.all_executors()
            if em.id in alive
        )
        return _MeshPlanningHandle() if capable else None

    def _serve_cached_result(
        self, entry: tuple[bytes, dict], session_id: str,
        trace: dict | None,
    ) -> str:
        """Mint a COMPLETED job for a result-cache hit (docs/serving.md).

        The job is real everywhere observability and charging look:
        history gets its submit + terminal records, the fleet latency
        histogram observes it under the ORIGINATING run's query class
        (carried in the cache entry — physical planning was skipped, so
        the class cannot be recomputed), and a traced session sees a
        ``cache`` event under the job root. Not written to the state
        backend: the payload lives only in this process, and recovering
        a "completed" job with no locations and no payload would serve
        an empty result — unknown-after-restart fails loudly instead.
        """
        payload, meta = entry
        qclass = meta.get("query_class", "unknown")
        job_id = generate_job_id()
        import time as _time

        now = _time.time()
        with self._lock:
            job = JobInfo(
                job_id=job_id, session_id=session_id, status="completed"
            )
            job.query_class = qclass
            job.submitted_s = now
            job.result_ipc = payload
            if trace is not None:
                job.trace_id = trace["trace_id"]
                root = trace["root"]
                root.attrs["job_id"] = job_id
                job.root_span_id = root.span_id
                job.root_span = root
                self._traces[job.trace_id] = job_id
                for s in trace["pre"]:
                    job.spans[s.span_id] = s
            self.jobs[job_id] = job
        self._job_event(
            job, "cache", attrs={"hit": True, "bytes": len(payload)}
        )
        latency = max(0.0, _time.time() - now)
        self._h_job_latency.labels(qclass).observe(latency)
        try:
            self.history.record_submit(
                job_id, query_class=qclass, session_id=session_id,
                submitted_s=now,
            )
            self._job_terminal_history(job, "completed")
        except Exception:  # noqa: BLE001 — observability, never
            # serving-critical
            log.exception("history record failed for %s", job_id)
        self._close_job_trace(job, "ok")
        self._retain_job_obs(job)
        log.info(
            "job %s served from result cache (%d bytes)", job_id,
            len(payload),
        )
        return job_id

    def submit_physical(
        self,
        physical: ExecutionPlan,
        session_id: str,
        trace: dict | None = None,
        cache_key: object = None,
    ) -> str:
        job_id = generate_job_id()
        if trace is None:
            # direct physical submissions (tests, embedders) trace too
            trace = self._mint_trace(self._session_config(session_id))
        # query-class fingerprint BEFORE stage splitting (no job ids or
        # locations exist yet to leak into it) — the label every fleet
        # latency series aggregates by (docs/observability.md)
        from ballista_tpu.obs.qclass import plan_class

        qclass = plan_class(physical)
        import time as _time

        now = _time.time()
        with self._lock:
            if qclass not in self._known_classes:
                if len(self._known_classes) < self.max_query_classes:
                    self._known_classes.add(qclass)
                else:
                    # cardinality cap: aggregate the long tail instead of
                    # leaking one histogram-child set per distinct shape
                    self.obs_class_overflow += 1
                    qclass = "overflow"
            job = JobInfo(job_id=job_id, session_id=session_id)
            job.query_class = qclass
            job.submitted_s = now
            job.cache_key = cache_key
            if trace is not None:
                job.trace_id = trace["trace_id"]
                root = trace["root"]
                root.attrs["job_id"] = job_id
                job.root_span_id = root.span_id
                job.root_span = root
                self._traces[job.trace_id] = job_id
                for s in trace["pre"]:
                    job.spans[s.span_id] = s
            self.jobs[job_id] = job
            if self.state is not None:
                self.state.save_job(job)
        # history log (docs/observability.md): the submit record — written
        # OUTSIDE the lock (backend I/O) and guarded (history is
        # observability, never submission-critical)
        try:
            self.history.record_submit(
                job_id, query_class=qclass, session_id=session_id,
                submitted_s=now,
            )
        except Exception:  # noqa: BLE001
            log.exception("history submit record failed for %s", job_id)
        self.event_loop.post(JobSubmitted(job_id, physical))
        return job_id

    # -- observability (docs/observability.md) -------------------------------
    def _store_job_span(self, job: JobInfo, span) -> None:
        """Keep one span in the job's bounded store (dict keyed span_id —
        re-shipped duplicates dedup)."""
        with self._lock:
            if len(job.spans) < 20000:
                job.spans.setdefault(span.span_id, span)

    def _job_event(
        self,
        job: JobInfo,
        name: str,
        parent_id: str = "",
        attrs: dict | None = None,
    ) -> None:
        """Record one scheduler-side point event on a traced job (no-op
        for untraced jobs — the zero-overhead off path)."""
        if not job.trace_id:
            return
        from ballista_tpu.obs import trace as obs_trace

        s = obs_trace.event(
            name,
            trace_id=job.trace_id,
            parent_id=parent_id or job.root_span_id,
            attrs=attrs,
        )
        self._store_job_span(job, s)

    def _stage_span_id(self, job: JobInfo, stage_id: int) -> str:
        with self._lock:
            s = job.stage_spans.get(stage_id)
        return s.span_id if s is not None else job.root_span_id

    def _open_stage_span(self, job: JobInfo, stage_id: int) -> None:
        if not job.trace_id:
            return
        from ballista_tpu.obs import trace as obs_trace

        with self._lock:
            if stage_id in job.stage_spans:
                return
            job.stage_spans[stage_id] = obs_trace.start(
                "stage",
                job.trace_id,
                job.root_span_id,
                attrs={"stage_id": stage_id},
            )

    def _finish_stage_span(self, job: JobInfo, stage_id: int) -> None:
        """Close a stage's span on first completion. The span OBJECT stays
        in stage_spans: its span_id keeps parenting recompute-round task
        attempts, so the recovery tree stays connected."""
        if not job.trace_id:
            return
        from ballista_tpu.obs import trace as obs_trace

        with self._lock:
            s = job.stage_spans.get(stage_id)
            if s is None or s.end_s:
                return
        obs_trace.finish(s)
        self._store_job_span(job, s)

    def ingest_spans(self, span_protos) -> None:
        """Executor-shipped spans (poll/heartbeat/status RPCs) land in
        their job's span store, matched by trace_id. Spans for unknown
        traces (job torn down, foreign) are dropped — the ring already
        has them for process-local debugging."""
        if not span_protos:
            return
        from ballista_tpu.obs import trace as obs_trace

        for p in span_protos:
            s = obs_trace.span_from_proto(p)
            with self._lock:
                job_id = self._traces.get(s.trace_id)
                job = self.jobs.get(job_id) if job_id is not None else None
            if job is not None:
                self._store_job_span(job, s)

    def _ingest_task_metrics(self, job_id: str, stage_id: int,
                             partition: int, status) -> None:
        """Per-operator metrics shipped in a CompletedTask: stored per
        (stage, partition) on the job, and summed into the cross-job
        counter aggregation /api/metrics serves."""
        if not status.completed.operator_metrics:
            return
        from ballista_tpu.obs import profile

        records = profile.metrics_from_proto(
            status.completed.operator_metrics
        )
        job = self._get_job(job_id)
        with self._lock:
            if job is not None:
                job.op_metrics[(stage_id, partition)] = records
            for r in records:
                for k, v in r["counters"].items():
                    if isinstance(v, (int, float)):
                        self.obs_task_counters[k] = (
                            self.obs_task_counters.get(k, 0) + v
                        )

    def _ingest_task_cost(self, tid: PartitionId, state: str,
                          executor_id: str, cost_msg) -> None:
        """One attempt's shipped cost vector (docs/observability.md):
        summed into the job's aggregate, rolled up per query class for
        the Prometheus cost counters, and appended to the history log as
        a task-attempt record. ``cost_msg`` is the CostVectorP or None
        (accounting off)."""
        if cost_msg is None:
            return
        from ballista_tpu.obs.history import CostVector, cost_from_proto

        cost = cost_from_proto(cost_msg)
        if cost.is_zero():
            return
        job = self._get_job(tid.job_id)
        qclass = job.query_class if job is not None else "unknown"
        with self._lock:
            if job is not None:
                if job.cost is None:
                    job.cost = CostVector()
                job.cost.add(cost)
            rollup = self.obs_class_cost.setdefault(qclass, {})
            for k, v in cost.to_dict().items():
                rollup[k] = rollup.get(k, 0) + v
        try:
            self.history.record_attempt(
                tid.job_id, tid.stage_id, tid.partition_id, state,
                executor_id, cost,
            )
        except Exception:  # noqa: BLE001 — metering must never outrank
            # the status RPC it rides along with
            log.exception("history attempt record failed for %s", tid)

    def _job_terminal_history(self, job: JobInfo, status: str) -> None:
        """Write the job's terminal history record (completed|failed):
        latency/queue-wait, retry/recompute/straggler/skew counters, and
        the aggregated cost vector. Guarded by callers."""
        import time as _time

        now = _time.time()
        latency = max(0.0, now - job.submitted_s) if job.submitted_s else 0.0
        wait = 0.0
        if job.first_assign_s and job.submitted_s:
            wait = max(0.0, job.first_assign_s - job.submitted_s)
        stragglers = 0
        for st in job.stage_stats or []:
            stragglers += sum(1 for t in st["tasks"] if t.get("straggler"))
        with self._lock:
            cost = job.cost
            skew = len(job.skew_flags)
            aqe_applied = sum(
                1 for d in job.aqe_decisions if d.get("outcome") == "applied"
            )
            aqe_rejected = sum(
                1 for d in job.aqe_decisions
                if d.get("outcome") == "rejected"
            )
        self.history.record_terminal(
            job.job_id,
            status,
            query_class=job.query_class,
            session_id=job.session_id,
            submitted_s=job.submitted_s,
            latency_s=latency,
            queue_wait_s=wait,
            retries=job.total_retries,
            recomputes=job.total_recomputes,
            stragglers=stragglers,
            skew_partitions=skew,
            aqe_applied=aqe_applied,
            aqe_rejected=aqe_rejected,
            error=job.error,
            cost=cost,
        )

    def history_payload(self, kind: str = "queries",
                        limit: int = 0) -> list[dict]:
        """The rows behind ``GET /api/history`` and the GetHistory RPC —
        one payload shape for every ``system.*`` table source."""
        if kind in ("", "queries"):
            return self.history.jobs(limit)
        if kind == "task_attempts":
            return self.history.attempts(limit)
        if kind == "executors":
            import time as _time

            em = self.executor_manager
            now = _time.time()
            alive = em.get_alive_executors(self.executor_timeout_s)
            rows = []
            for meta in em.all_executors():
                data = em.get_executor_data(meta.id)
                seen = em.last_seen(meta.id)
                rows.append(
                    {
                        "id": meta.id,
                        "host": meta.host,
                        "port": meta.port,
                        "grpc_port": meta.grpc_port,
                        "task_slots": (
                            data.total_task_slots if data
                            else meta.specification.task_slots
                        ),
                        "n_devices": meta.specification.n_devices or 1,
                        "alive": meta.id in alive,
                        "last_heartbeat_age_s": (
                            round(now - seen, 3) if seen is not None
                            else -1.0
                        ),
                    }
                )
            return rows[:limit] if limit else rows
        raise ValueError(f"unknown history kind {kind!r}")

    def ingest_hists(self, hist_protos) -> None:
        """Executor-shipped latency-histogram deltas (poll/heartbeat
        RPCs) merge into the scheduler's registry — the fleet view
        /api/metrics serves (docs/observability.md). Exception-guarded:
        this runs on the liveness RPC BEFORE apply_task_statuses, and a
        malformed delta (a version-skewed executor shipping a family
        with different labels) escaping here would poison-pill EVERY
        retry of that executor's poll — its statuses would never apply
        and its RUNNING tasks would strand. Metering must never outrank
        the work it rides along with."""
        if not hist_protos:
            return
        from ballista_tpu.obs import hist as obs_hist

        try:
            self.hists.ingest(obs_hist.deltas_from_proto(hist_protos))
        except Exception:  # noqa: BLE001
            log.exception("dropping unmergeable histogram deltas")

    def _observe_task_completion(self, tid: PartitionId) -> None:
        """Per-task duration into the stage histogram + the straggler
        check (docs/observability.md): a completed task exceeding
        straggler_factor x the median of its stage's completed durations
        (noise-floored) is flagged once — trace event, counter, timeline
        bit."""
        sm = self.stage_manager
        # consume-once: a replayed COMPLETED status (lost RPC response,
        # executor resend) must not observe the same attempt window into
        # the histogram twice
        dur = sm.take_unmetered_runtime(
            tid.job_id, tid.stage_id, tid.partition_id
        )
        if dur is None:
            return
        job = self._get_job(tid.job_id)
        if job is None:
            return
        self._h_stage_task.labels(
            job.query_class, str(tid.stage_id)
        ).observe(dur)
        cfg = self._session_config(job.session_id)
        # noise-floor fast path: the threshold is always >= min_s, so a
        # sub-floor task can never flag — skip the per-completion
        # durations scan+sort entirely (on a wide stage that scan is
        # O(n) per completion on the poll-RPC status path)
        if dur <= cfg.straggler_min_s():
            return
        durations = sm.completed_durations(tid.job_id, tid.stage_id)
        from ballista_tpu.scheduler.stage_manager import straggler_stats

        # (fewer than 3 completions -> no threshold: a 2-task stage
        # cannot name a straggler without one of them being half the
        # evidence)
        stats = straggler_stats(
            durations, cfg.straggler_factor(), cfg.straggler_min_s()
        )
        if stats is None:
            return
        threshold, med = stats
        if dur <= threshold:
            return
        if not sm.mark_straggler(tid.job_id, tid.stage_id,
                                 tid.partition_id):
            return
        with self._lock:
            self.obs_straggler_total[job.query_class] = (
                self.obs_straggler_total.get(job.query_class, 0) + 1
            )
        self._job_event(
            job, "straggler",
            parent_id=self._stage_span_id(job, tid.stage_id),
            attrs={
                "stage_id": tid.stage_id,
                "partition": tid.partition_id,
                "duration_s": round(dur, 4),
                "stage_median_s": round(med, 4),
            },
        )
        log.warning(
            "straggler: task %s/%s/%s took %.3fs (stage median %.3fs, "
            "factor %.1f)",
            tid.job_id, tid.stage_id, tid.partition_id, dur, med,
            cfg.straggler_factor(),
        )

    def _detect_skew(self, job: JobInfo, stage_id: int) -> None:
        """Skew monitor (docs/observability.md): when a stage completes,
        compare each (stage, partition)'s processed rows — the max
        output_rows across its shipped per-operator metrics, i.e. the
        widest point of the fragment — against the stage median. Flagged
        partitions are EXACTLY the candidates the AQE split policy
        (ROADMAP) will feed to SplitShufflePartitions."""
        cfg = self._session_config(job.session_id)
        ratio = cfg.skew_ratio()
        if ratio <= 0:
            return
        with self._lock:
            rows_by_part: dict[int, float] = {}
            for (sid, part), records in job.op_metrics.items():
                if sid != stage_id:
                    continue
                widest = 0.0
                for r in records:
                    v = r.get("counters", {}).get("output_rows")
                    if isinstance(v, (int, float)):
                        widest = max(widest, float(v))
                rows_by_part[part] = widest
        if len(rows_by_part) < 2:
            return
        import statistics

        med = statistics.median(rows_by_part.values())
        if med <= 0:
            return
        floor = cfg.skew_min_rows()
        for part in sorted(rows_by_part):
            rows = rows_by_part[part]
            if rows < floor or rows <= ratio * med:
                continue
            self._commit_skew_flag(
                job, stage_id, part, rows, med, ratio, source="output"
            )

    def _commit_skew_flag(
        self,
        job: JobInfo,
        stage_id: int,
        part: int,
        rows: float,
        med: float,
        ratio: float,
        source: str,
    ) -> None:
        """The ONE skew-commit protocol shared by the post-run
        output-rows pass (``_detect_skew``) and the pre-run input-bucket
        pass (``_detect_input_skew``): dedup'd flag, counter, trace
        event, warning — two hand-synced copies would drift, and both
        passes feed the same consumers (timeline ``skewed`` bit, the
        AQE split rule)."""
        with self._lock:
            if (stage_id, part) in job.skew_flags:
                return
            job.skew_flags.append((stage_id, part))
            self.obs_skew_total[job.query_class] = (
                self.obs_skew_total.get(job.query_class, 0) + 1
            )
        attrs = {
            "stage_id": stage_id,
            "partition": part,
            "rows": int(rows),
            "stage_median_rows": int(med),
        }
        if source != "output":
            # distinguishes the pre-run input-bucket flag from the
            # post-run output-rows flag (regression-tested)
            attrs["source"] = source
        self._job_event(
            job, "skew",
            parent_id=self._stage_span_id(job, stage_id),
            attrs=attrs,
        )
        log.warning(
            "skew (%s): partition %s/%s/%s carries %d rows "
            "(stage median %d, ratio %.1f)",
            source, job.job_id, stage_id, part, int(rows), int(med),
            ratio,
        )

    def _detect_input_skew(
        self, job: JobInfo, consumer_id: int, stats: dict
    ) -> None:
        """Input-bucket skew for a consumer whose producers just ALL
        completed (docs/aqe.md): the producers' committed shuffle-write
        metas give exact per-bucket rows BEFORE the consumer runs, so
        the flag — and the AQE split policy reading it — arrives in
        time to act. This is the timing fix for the final stage too:
        its own ``_detect_skew`` pass used to run only at job
        completion, after anything could be done about it; evaluating
        its producers at the last StageFinished closes that gap. Flags
        share the (stage, partition) key space with ``_detect_skew``
        (a consumer task ``p`` reads exactly input bucket ``p``), so
        the later output-rows pass dedups against these."""
        cfg = self._session_config(job.session_id)
        ratio = cfg.skew_ratio()
        if ratio <= 0:
            return
        from ballista_tpu.scheduler.aqe import keyed_bucket_totals

        with self._lock:
            stage = job.stages.get(consumer_id)
            n_buckets = (
                stage.input_partition_count if stage is not None else 0
            )
        if n_buckets < 2:
            return
        with self._lock:
            buckets, keyed = keyed_bucket_totals(job, stats)
        if not keyed:
            return
        rows_by_bucket = {
            b: buckets.get(b, (0, 0))[0] for b in range(n_buckets)
        }
        import statistics

        med = statistics.median(rows_by_bucket.values())
        if med <= 0:
            return
        floor = cfg.skew_min_rows()
        for part in sorted(rows_by_bucket):
            rows = rows_by_bucket[part]
            if rows < floor or rows <= ratio * med:
                continue
            self._commit_skew_flag(
                job, consumer_id, part, rows, med, ratio, source="input"
            )

    def record_aqe_decision(self, job: JobInfo, decision: dict) -> None:
        """One AQE policy decision (docs/aqe.md): appended to the job's
        decision log (REST /api/job), counted into the
        ballista_aqe_rewrites_total{op,outcome} family, and recorded as
        an ``aqe`` trace event carrying the before/after stats."""
        key = (decision.get("op", "?"), decision.get("outcome", "?"))
        with self._lock:
            if len(job.aqe_decisions) < 256:
                job.aqe_decisions.append(dict(decision))
            self.obs_aqe_total[key] = self.obs_aqe_total.get(key, 0) + 1
        attrs = {
            "op": decision.get("op", ""),
            "outcome": decision.get("outcome", ""),
            "stage_ids": decision.get("stage_ids", []),
            "source": decision.get("source", ""),
        }
        if decision.get("clause"):
            attrs["clause"] = decision["clause"]
        for side in ("before", "after"):
            for k, v in sorted((decision.get(side) or {}).items()):
                attrs[f"{side}_{k}"] = v
        self._job_event(job, "aqe", attrs=attrs)
        log.info(
            "aqe %s: %s %s stages=%s%s", decision.get("outcome"),
            decision.get("op"), decision.get("source", ""),
            decision.get("stage_ids"),
            f" clause={decision['clause']}" if decision.get("clause")
            else "",
        )

    def desired_executors(self) -> int:
        """The composite autoscale pressure the KEDA ExternalScaler
        reports (docs/observability.md): base demand = inflight tasks
        over per-executor slots, scaled up (capped 4x) when the p90 of
        recent queue waits exceeds the declared target — pending work
        alone under-scales when jobs are stacking up faster than slots
        free. Also served as the ballista_desired_executors gauge."""
        import math

        inflight = self.stage_manager.inflight_tasks()
        # bypassed jobs are invisible to the stage manager but are demand
        # all the same (docs/serving.md)
        with self._lock:
            inflight += len(self._bypass_pending) + len(self._bypass_running)
        if inflight <= 0:
            return 0
        em = self.executor_manager
        per_exec = 0
        for eid in sorted(em.tracked_executors()):
            data = em.get_executor_data(eid)
            if data is not None:
                per_exec = max(per_exec, data.total_task_slots)
        per_exec = per_exec or 4
        base = math.ceil(inflight / per_exec)
        target = self.config.scaler_queue_wait_target_s()
        import time as _time

        cutoff = _time.time() - self.queue_wait_window_s
        with self._lock:
            # recency-filtered: stale burst-era waits must stop driving
            # the multiplier once the queue has actually drained
            waits = sorted(
                w for at, w in self._recent_queue_waits if at >= cutoff
            )
        if waits and target > 0:
            p90 = waits[min(len(waits) - 1, int(0.9 * (len(waits) - 1)))]
            if p90 > target:
                base = math.ceil(base * min(p90 / target, 4.0))
        return max(base, 1)

    def job_stats(self, job_id: str) -> dict | None:
        """Aggregated per-stage / per-partition stats for one job (the
        /api/job/<id> payload body): task rows/bytes from the stage
        bookkeeping (live) or the completion snapshot, overlaid with the
        shipped per-operator metrics. None for unknown jobs."""
        job = self._get_job(job_id)
        if job is None:
            return None
        stages = job.stage_stats
        if stages is None:
            stages = self.stage_manager.job_stage_detail(job_id)
        with self._lock:
            op_metrics = {
                f"{sid}/{part}": records
                for (sid, part), records in sorted(job.op_metrics.items())
            }
        # key is "stage_stats", NOT "stages": the /api/job payload already
        # carries a "stages" list (DAG edges + plan display) the status UI
        # renders — clobbering it broke the expandable job rows
        return {"stage_stats": stages, "operator_metrics": op_metrics}

    def job_trace(self, job_id: str) -> list[dict] | None:
        """The job's reassembled span tree, start-ordered (REST + chaos
        assertions). None for unknown jobs; [] for untraced ones."""
        job = self._get_job(job_id)
        if job is None:
            return None
        with self._lock:
            spans = sorted(job.spans.values(), key=lambda s: s.start_s)
        return [
            {
                "trace_id": s.trace_id,
                "span_id": s.span_id,
                "parent_id": s.parent_id,
                "name": s.name,
                "start_s": round(s.start_s, 6),
                "end_s": round(s.end_s, 6),
                "status": s.outcome,
                "attrs": {k: str(v) for k, v in sorted(s.attrs.items())},
            }
            for s in spans
        ]

    # -- stage generation (ref query_stage_scheduler.rs:59-105) --------------
    def _generate_stages(self, job_id: str, plan: ExecutionPlan) -> None:
        job = self._get_job(job_id)
        if job is None:
            return
        try:
            planner = DistributedPlanner()
            stages = planner.plan_query_stages(job_id, plan)
            cfg = self._session_config(job.session_id)
            if cfg.verify_plans():
                # stage-DAG well-formedness: every UnresolvedShuffleExec
                # placeholder must agree with its writer stage on schema
                # and partition count, and reference an earlier stage —
                # the splitter bug class that otherwise dies mid-job on
                # an executor
                from ballista_tpu.analysis import verify_stages

                verify_stages(stages)
        except Exception as e:  # noqa: BLE001
            self._on_job_failed(job_id, f"planning failed: {e}")
            return
        job.max_attempts = cfg.task_max_attempts()
        job.eager = cfg.eager_shuffle()
        # serving fast path (docs/serving.md): exactly one stage with one
        # input partition group needs none of the stage state machine —
        # no dependencies to track, no shuffles to resolve, no
        # StageFinished to promote. Grant it as one direct task instead
        # (retries stay bounded by the same task_max_attempts snapshot).
        if (
            len(stages) == 1
            and stages[0].input_partition_count == 1
            and cfg.single_stage_bypass()
        ):
            self._submit_bypass(job, stages[0])
            return
        deps = _stage_dependencies(stages)
        for stage in stages:
            job.stages[stage.stage_id] = stage
        job.final_stage_id = stages[-1].stage_id
        job.dependencies = deps
        self.stage_manager.add_final_stage(job_id, job.final_stage_id)
        self.stage_manager.add_stages_dependency(job_id, deps)
        job.status = "running"
        if self.state is not None:
            # write-through: stage plans + job record (ref
            # persistent_state.rs save_stage_plan :183-324)
            for stage in stages:
                self.state.save_stage_plan(
                    job_id, stage.stage_id, stage.plan
                )
            self.state.save_job(job)
        # AQE proactive pass (docs/aqe.md): apply this query class's
        # LEARNED strategies while every stage is still fully pending —
        # the window where broadcast/coalesce/split (which re-bucket
        # producers) are acceptable. When strategies exist, the leaf
        # stages are submitted PENDING (not claimable) first: a pull
        # executor's PollWork thread could otherwise claim a leaf task
        # in the gap between submission and rewrite application and
        # close the window with a spurious runtime-state rejection.
        # The rewrites apply, then the dep-free stages promote below.
        defer_running = False
        try:
            defer_running = self.aqe.wants_to_adapt(job)
        except Exception:  # noqa: BLE001
            log.exception("AQE strategy lookup failed for %s", job_id)
        self._submit_stage(
            job_id, job.final_stage_id, set(), defer_running=defer_running
        )
        if defer_running:
            try:
                self.aqe.on_job_submitted(job)
            except Exception:  # noqa: BLE001 — adaptation must never
                # outrank the submission it advises
                log.exception("AQE submission policy failed for %s", job_id)
            # open the gates: promote every pending stage whose deps are
            # already complete (leaf stages; apply_certified_rewrite has
            # already re-promoted the ones it touched)
            deferred: list = []
            with self._lock:
                for sid in sorted(job.stages):
                    if not self.stage_manager.is_pending_stage(job_id, sid):
                        continue
                    if any(
                        not self.stage_manager.is_completed_stage(
                            job_id, u.stage_id
                        )
                        for u in find_unresolved_shuffles(
                            job.stages[sid].plan
                        )
                    ):
                        continue
                    self._resolve_stage(job_id, sid)
                    deferred.extend(
                        self.stage_manager.promote_pending_stage(
                            job_id, sid
                        )
                    )
            for e in deferred:
                self.event_loop.post(e)

    def _submit_stage(
        self,
        job_id: str,
        stage_id: int,
        seen: set[int],
        defer_running: bool = False,
    ) -> None:
        """Recursive dependency walk (ref :124-177). ``defer_running``
        registers even dependency-free stages as PENDING (nothing is
        claimable yet): the AQE submission pass rewrites templates
        first, then the caller promotes — see ``_generate_stages``."""
        if stage_id in seen:
            return
        seen.add(stage_id)
        if self.stage_manager.is_running_stage(
            job_id, stage_id
        ) or self.stage_manager.is_pending_stage(job_id, stage_id):
            return
        job = self._get_job(job_id)
        if job is None:
            return
        stage = job.stages[stage_id]
        unresolved = find_unresolved_shuffles(stage.plan)
        unfinished = [
            u
            for u in unresolved
            if not self.stage_manager.is_completed_stage(job_id, u.stage_id)
        ]
        n_tasks = stage.input_partition_count
        self._open_stage_span(job, stage_id)
        if unfinished:
            self.stage_manager.add_pending_stage(
                job_id, stage_id, n_tasks, max_attempts=job.max_attempts
            )
            for u in unfinished:
                self._submit_stage(
                    job_id, u.stage_id, seen, defer_running=defer_running
                )
        elif defer_running:
            self.stage_manager.add_pending_stage(
                job_id, stage_id, n_tasks, max_attempts=job.max_attempts
            )
        else:
            self._resolve_stage(job_id, stage_id)
            self.stage_manager.add_running_stage(
                job_id, stage_id, n_tasks, max_attempts=job.max_attempts
            )

    def _resolve_stage(self, job_id: str, stage_id: int) -> None:
        """Patch completed shuffle locations into a COPY of the stage plan
        and serialize it (ref try_resolve_stage :181-309 +
        task_scheduler.rs:146-156). ``stage.plan`` stays the pristine
        unresolved template: lost-shuffle recovery re-invokes this after an
        upstream recompute, and re-resolution needs the placeholders a
        destructive patch would have consumed."""
        job = self._get_job(job_id)
        if job is None:
            raise PlanError(f"job {job_id} torn down during stage resolution")
        stage = job.stages[stage_id]
        unresolved = find_unresolved_shuffles(stage.plan)
        plan = stage.plan
        if unresolved:
            locations: dict[int, list[list[PartitionLocation]]] = {}
            for u in unresolved:
                locations[u.stage_id] = self._stage_output_locations(
                    job_id, u.stage_id, u.output_partition_count
                )
            plan = remove_unresolved_shuffles(stage.plan, locations)
        job.resolved_plan_bytes[stage_id] = self.codec.physical_to_proto(
            plan
        ).SerializeToString()

    def _executor_endpoint(self, executor_id: str) -> tuple[str, int]:
        """(host, port) a reader should dial for an executor's shuffle
        output — the single resolution used by BOTH the barriered
        (_stage_output_locations) and eager (shuffle_locations_proto)
        paths, so their location construction cannot drift. An unknown
        executor resolves to localhost:0: the location still carries the
        local filesystem path, which colocated readers can consume."""
        meta_exec = self.executor_manager.get_executor_metadata(executor_id)
        host = meta_exec.host if meta_exec else "localhost"
        port = meta_exec.port if meta_exec else 0
        return host, port

    def _stage_output_locations(
        self, job_id: str, stage_id: int, n_out: int
    ) -> list[list[PartitionLocation]]:
        locs: list[list[PartitionLocation]] = [[] for _ in range(n_out)]
        for (task_idx, executor_id, metas) in (
            self.stage_manager.completed_partitions(job_id, stage_id)
        ):
            host, port = self._executor_endpoint(executor_id)
            for m in metas:
                locs[m.partition_id].append(
                    PartitionLocation(
                        job_id=job_id,
                        stage_id=stage_id,
                        partition=m.partition_id,
                        executor_id=executor_id,
                        host=host,
                        port=port,
                        path=m.path,
                        # push-capable metadata (docs/shuffle.md): the
                        # consumer tries the producer's in-memory stream
                        # (keyed by the producing map task) before the
                        # file path
                        push=m.push,
                        map_partition=task_idx,
                    )
                )
        return locs

    # -- event handlers ------------------------------------------------------
    def _on_stage_finished(self, job_id: str, stage_id: int) -> None:
        """Promote pending parents whose deps are all complete (ref
        :107-122). Re-resolution here is what repairs consumers after a
        lost-shuffle recompute: their cached plan bytes were invalidated,
        and the pristine template re-resolves against the refreshed
        locations."""
        job = self._get_job(job_id)
        if job is None:
            return
        self._finish_stage_span(job, stage_id)
        # skew monitor (docs/observability.md): every task of this stage
        # has reported — its shipped per-partition metrics are complete,
        # so the rows-vs-median comparison is meaningful exactly now
        self._detect_skew(job, stage_id)
        # consumers whose producers are ALL now complete: the stages the
        # promote loop below is about to start. Their input-bucket skew
        # is knowable exactly now (producer metas are final), and this
        # is the AQE policy's decision point — BEFORE promotion, while
        # the consumer is still fully pending and a certified rewrite of
        # it can still be accepted (docs/aqe.md).
        ready: list[int] = []
        # the stats pass below scans every completed producer's shuffle
        # metas — skip it entirely when neither consumer exists: the
        # skew monitor is off AND the AQE policy is disabled (the
        # common aqe=false default must not pay for adaptivity)
        from ballista_tpu.scheduler import aqe as aqe_mod

        cfg = self._session_config(job.session_id)
        want_stats = cfg.skew_ratio() > 0 or aqe_mod.enabled(cfg)
        if want_stats:
            with self._lock:
                for parent in sorted(
                    self.stage_manager.parents_of(job_id, stage_id)
                ):
                    if not self.stage_manager.is_pending_stage(
                        job_id, parent
                    ):
                        continue
                    stage = job.stages.get(parent)
                    if stage is not None and all(
                        self.stage_manager.is_completed_stage(
                            job_id, u.stage_id
                        )
                        for u in find_unresolved_shuffles(stage.plan)
                    ):
                        ready.append(parent)
        # producer stats computed ONCE per ready consumer (full scans of
        # the completed shuffle metas) and shared by the skew pass and
        # the policy — this runs on the event-loop thread, and doubling
        # the scan would show up straight in the dispatch-lag histogram
        ready_stats: dict[int, dict] = {}
        from ballista_tpu.scheduler.aqe import producer_stats

        for parent in ready:
            with self._lock:
                stage = job.stages.get(parent)
                plan = stage.plan if stage is not None else None
            if plan is None:
                continue
            ready_stats[parent] = producer_stats(self, job_id, plan)
            self._detect_input_skew(job, parent, ready_stats[parent])
        try:
            self.aqe.on_stage_finished(job, stage_id, ready_stats)
        except Exception:  # noqa: BLE001 — adaptation must never outrank
            # the promotion it advises; the job proceeds unadapted
            log.exception("AQE StageFinished policy failed for %s", job_id)
        deferred: list = []
        promoted: list[int] = []
        # sorted: parents_of returns a set, and promote/event order should
        # not vary with hash seed (detlint unordered-iteration hardening —
        # determinism of the recovery event sequence is what the chaos
        # trace assertions read)
        for parent in sorted(self.stage_manager.parents_of(job_id, stage_id)):
            # check+resolve+promote under the server lock, serialized
            # against _on_shuffle_lost: an invalidation racing this
            # resolve would otherwise let it bake EMPTY location lists
            # for just-lost partitions into the resolved plan bytes and
            # promote the consumer anyway — next_task would then hand
            # out the poisoned plan without its completeness re-check
            # (plan_bytes present). Completion events post AFTER the
            # lock: the event queue is bounded (racelint
            # blocking-under-lock).
            with self._lock:
                if not self.stage_manager.is_pending_stage(job_id, parent):
                    continue
                unresolved = find_unresolved_shuffles(
                    job.stages[parent].plan
                )
                if all(
                    self.stage_manager.is_completed_stage(job_id, u.stage_id)
                    for u in unresolved
                ):
                    self._resolve_stage(job_id, parent)
                    deferred.extend(
                        self.stage_manager.promote_pending_stage(
                            job_id, parent
                        )
                    )
                    promoted.append(parent)
        for parent in promoted:
            # recovery-shape visibility (docs/observability.md): the
            # promote is the recovery's commit point — the chaos trace
            # test asserts submit -> stage -> failed attempt -> recompute
            # -> promote connect under one trace_id
            self._job_event(
                job, "promote",
                parent_id=self._stage_span_id(job, parent),
                attrs={"stage_id": parent, "after_stage": stage_id},
            )
        for e in deferred:
            self.event_loop.post(e)

    def _on_task_rescheduled(self, event: TaskRescheduled) -> None:
        """Bookkeeping for a bounded retry (visibility: REST /api/state
        exposes the count; chaos tests assert on it)."""
        job = self._get_job(event.job_id)
        if job is not None:
            job.total_retries += 1
            self._job_event(
                job, "task_retry",
                parent_id=self._stage_span_id(job, event.stage_id),
                attrs={
                    "stage_id": event.stage_id,
                    "partition": event.partition_id,
                    "attempt": event.attempt,
                },
            )
        log.warning(
            "task %s/%s/%s requeued for attempt %d: %s",
            event.job_id, event.stage_id, event.partition_id,
            event.attempt, event.error.splitlines()[0] if event.error else "",
        )

    def _on_shuffle_lost(
        self, job_id: str, map_stage_id: int, executor_id: str
    ) -> bool:
        """Lost-shuffle (lineage) recovery: ``executor_id``'s COMPLETED
        shuffle output of ``map_stage_id`` is unreachable — re-open exactly
        those map partitions, roll the stage back to running, and force
        consumers to re-resolve against refreshed locations once it
        re-completes. Returns True when anything was invalidated.

        Recompute rounds are bounded by the stage's max_attempts: an
        output that keeps vanishing (crash-looping executor, corrupt
        writes) must eventually fail the job instead of recomputing
        forever."""
        job = self._get_job(job_id)
        if job is None or job.status != "running":
            return False
        with self._lock:
            # atomic with the consumer demotion below, and serialized
            # against next_task's lazy re-resolution (which re-checks
            # producer completeness under the same lock): a resolve racing
            # this invalidation must see either the old complete state or
            # the demoted one, never a half-invalidated stage
            reopened = self.stage_manager.invalidate_executor_outputs(
                job_id, map_stage_id, {executor_id}
            )
            if not reopened:
                return False
            job.total_recomputes += 1
            for consumer in sorted(  # set-ordered walk: see _on_stage_finished
                self.stage_manager.parents_of(job_id, map_stage_id)
            ):
                job.resolved_plan_bytes.pop(consumer, None)
                self.stage_manager.demote_running_stage(job_id, consumer)
        rounds = self.stage_manager.stage_recomputes(job_id, map_stage_id)
        cap = self.stage_manager.stage_max_attempts(job_id, map_stage_id)
        # recovery-shape visibility (docs/observability.md): the
        # invalidate+recompute decision, parented to the producing stage's
        # span so the kill -> invalidate -> recompute -> promote chain
        # reads off the span tree
        self._job_event(
            job, "recompute",
            parent_id=self._stage_span_id(job, map_stage_id),
            attrs={
                "stage_id": map_stage_id,
                "executor_id": executor_id,
                "reopened": len(reopened),
                "round": rounds,
            },
        )
        log.warning(
            "shuffle output of %s/%s on executor %s lost; re-running %d map "
            "partitions (recompute round %d/%d)",
            job_id, map_stage_id, executor_id, len(reopened), rounds, cap,
        )
        if rounds > cap:
            self.event_loop.post(
                JobFailed(
                    job_id,
                    map_stage_id,
                    f"shuffle output of stage {map_stage_id} lost "
                    f"{rounds} times (last on executor {executor_id}); "
                    "recompute bound exceeded",
                )
            )
            return True
        # (stale locations were dropped and consumers demoted above, under
        # the lock; they re-resolve from their pristine templates when the
        # map stage re-completes: StageFinished -> _on_stage_finished)
        if self.policy == TaskSchedulingPolicy.PUSH_STAGED:
            self.event_loop.post(ReviveOffers())
        return True

    # -- certified plan rewrites (ballista_tpu/rewrite.py) -------------------
    def apply_certified_rewrite(self, job_id: str, op):
        """The ONLY sanctioned way to change a running job's stage
        templates (docs/analysis.md): apply a typed rewrite op over THIS
        server's pristine templates under the server lock — the
        certificate is derived here, never accepted from a caller —
        enforce the runtime precondition (every touched stage fully
        pending), and only then swap templates + bookkeeping atomically.
        Any failure raises the typed :class:`RewriteRejected` carrying
        the failing clause and leaves the pristine templates untouched —
        the job proceeds on the unrewritten plan. Returns the validated
        certificate.

        This is the seam the AQE policy layer (ROADMAP) plugs into: it
        decides WHAT to rewrite from runtime stats; this method decides
        whether the rewrite is provably safe."""
        from ballista_tpu import rewrite as rewrite_mod
        from ballista_tpu.testing import faults

        job = self._get_job(job_id)
        if job is None or job.status != "running":
            raise RewriteRejected(
                f"job {job_id} is not running", clause="job-state"
            )
        deferred: list = []
        try:
            with self._lock:
                old_stages = list(job.stages.values())
                result = rewrite_mod.apply_rewrite(
                    old_stages, op, job_id=job_id
                )
                inj = faults.active()
                if inj is not None:
                    # chaos: the certificate-validation failure path
                    # (rewrite_reject rules raise RewriteRejected here)
                    inj.on_rewrite_validate(
                        job_id, getattr(op, "stage_id", -1)
                    )
                # the certificate was derived HERE, under the lock, from
                # this server's own pristine templates (apply_rewrite
                # certifies and raises on any failing clause) — there is
                # no producer-supplied copy to distrust
                cert = result.certificate
                new_by = {s.stage_id: s for s in result.stages}
                touched = cert.rewritten_stages + cert.added_stages
                err = self.stage_manager.rebind_stages_for_rewrite(
                    job_id,
                    affected={
                        sid: new_by[sid].input_partition_count
                        for sid in cert.rewritten_stages
                    },
                    removed=cert.removed_stages,
                    added={
                        sid: new_by[sid].input_partition_count
                        for sid in cert.added_stages
                    },
                    deps=_stage_dependencies(result.stages),
                    max_attempts=job.max_attempts,
                )
                if err is not None:
                    raise RewriteRejected(err, clause="runtime-state")
                # accepted: swap the pristine templates + invalidate every
                # cached resolution of a touched stage (eager bytes too —
                # they are location-free but template-derived)
                job.stages = {s.stage_id: s for s in result.stages}
                job.dependencies = _stage_dependencies(result.stages)
                for sid in touched + cert.removed_stages:
                    job.resolved_plan_bytes.pop(sid, None)
                    job.eager_plan_bytes.pop(sid, None)
                job.total_rewrites += 1
                # rewrite visibility (docs/aqe.md): the decision log
                # /api/job serves + the /timeline "rewritten" stage
                # marker (why did this stage's partition count change?)
                job.rewritten_stages.update(touched)
                if len(job.rewrite_log) < 256:
                    job.rewrite_log.append(
                        {
                            "op": op.describe(),
                            "outcome": "applied",
                            "exactness": cert.exactness,
                            "rewritten": sorted(cert.rewritten_stages),
                            "added": sorted(cert.added_stages),
                            "removed": sorted(cert.removed_stages),
                        }
                    )
                from ballista_tpu import rewrite as _rw
                from ballista_tpu.analysis import replay

                if replay.enabled():
                    # the witness must not compare across content that
                    # legitimately changes: re-bucketed stages always;
                    # for MULTISET_EXACT rewrites also every touched
                    # stage and its transitive consumers (float folds
                    # re-associate downstream — see rewrite.BIT_EXACT)
                    forget = set(cert.bucket_changed_stages)
                    if cert.exactness != _rw.BIT_EXACT:
                        forget |= set(touched)
                    frontier = set(forget)
                    while frontier:
                        frontier = {
                            parent
                            for child in frontier
                            for parent in job.dependencies.get(
                                child, set()
                            )
                        } - forget
                        forget |= frontier
                    for sid in sorted(forget):
                        replay.forget_stage(job_id, sid)
                if self.state is not None:
                    for sid in touched:
                        self.state.save_stage_plan(
                            job_id, sid, new_by[sid].plan
                        )
                # re-promote touched stages whose dependencies are already
                # complete (they were forced PENDING by the rebind; nothing
                # else re-promotes them until a dependency finishes)
                for sid in sorted(touched):
                    if not self.stage_manager.is_pending_stage(job_id, sid):
                        continue
                    unresolved = find_unresolved_shuffles(
                        job.stages[sid].plan
                    )
                    if all(
                        self.stage_manager.is_completed_stage(
                            job_id, u.stage_id
                        )
                        for u in unresolved
                    ):
                        self._resolve_stage(job_id, sid)
                        deferred.extend(
                            self.stage_manager.promote_pending_stage(
                                job_id, sid
                            )
                        )
        except RewriteRejected as e:
            with self._lock:
                # same discipline as the accepted-path counter: REST and
                # chaos assertions read these, and an unlocked
                # read-modify-write can drop concurrent increments
                job.total_rewrite_rejects += 1
                if len(job.rewrite_log) < 256:
                    job.rewrite_log.append(
                        {
                            "op": op.describe(),
                            "outcome": "rejected",
                            "clause": e.clause,
                            "stage_ids": sorted(
                                int(s) for s in (e.stage_ids or ())
                            ),
                        }
                    )
            self._job_event(
                job, "rewrite_reject",
                attrs={"op": op.describe(), "clause": e.clause},
            )
            log.warning(
                "certified rewrite REJECTED for %s: %s", job_id, e
            )
            raise
        # events post after the lock: the queue is bounded (racelint
        # blocking-under-lock), and every accepted rewrite may unlock work
        self._job_event(
            job, "rewrite",
            attrs={
                "op": op.describe(),
                "rewritten": list(cert.rewritten_stages),
                "added": list(cert.added_stages),
                "removed": list(cert.removed_stages),
            },
        )
        log.warning(
            "certified rewrite ACCEPTED for %s: %s (%s)",
            job_id, op.describe(), cert.summary(),
        )
        for e in deferred:
            self.event_loop.post(e)
        if self.policy == TaskSchedulingPolicy.PUSH_STAGED:
            self.event_loop.post(ReviveOffers())
        return cert

    def _close_job_trace(self, job: JobInfo, outcome: str = "ok") -> None:
        """Finish whatever spans are still open (stage spans, root) and
        store them — the job's span tree must be complete once the job
        reaches a terminal status."""
        if not job.trace_id:
            return
        from ballista_tpu.obs import trace as obs_trace

        with self._lock:
            open_spans = [
                s for s in job.stage_spans.values() if not s.end_s
            ]
            root = job.root_span
        for s in open_spans:
            obs_trace.finish(s)
            self._store_job_span(job, s)
        if root is not None and not root.end_s:
            obs_trace.finish(root, outcome)
            self._store_job_span(job, root)

    def _retain_job_obs(self, job: JobInfo) -> None:
        """Enroll a terminal job in the bounded observability-retention
        window: the newest ``obs_retained_jobs`` terminal jobs keep their
        spans / operator metrics / stage-stats snapshot (served by
        /api/job/<id>); older ones are stripped back to the light
        JobInfo record the pre-observability scheduler kept."""
        with self._lock:
            self._obs_retained.append(job.job_id)
            while len(self._obs_retained) > max(1, self.obs_retained_jobs):
                old_id = self._obs_retained.popleft()
                old = self.jobs.get(old_id)
                if old is None:
                    continue
                old.spans.clear()
                old.op_metrics.clear()
                old.stage_spans.clear()
                old.stage_stats = None
                old.root_span = None
                # decision logs follow the same retention discipline as
                # the other heavy per-job payloads (counters stay)
                old.rewrite_log.clear()
                old.aqe_decisions.clear()
                # cache-served payloads follow the same retention window
                # (clients poll status within moments of submission; only
                # the cache itself keeps results long-term)
                old.result_ipc = b""
                if old.trace_id:
                    self._traces.pop(old.trace_id, None)

    def _on_job_finished(self, job_id: str) -> None:
        """Assemble CompletedJob locations (ref :370-388, :416-473)."""
        job = self._get_job(job_id)
        if job is None:
            return
        final = job.stages[job.final_stage_id]
        locs = self._stage_output_locations(
            job_id, job.final_stage_id, final.output_partition_count
        )
        flat: list[PartitionLocation] = []
        for part in locs:
            flat.extend(part)
        job.completed_locations = flat
        job.status = "completed"
        # the final stage has no StageFinished event (JobFinished fires
        # instead) — run its skew check here so the last stage's
        # partitions are monitored like every other stage's
        self._detect_skew(job, job.final_stage_id)
        # fleet plane: end-to-end latency by query class
        if job.submitted_s:
            import time as _time

            self._h_job_latency.labels(job.query_class).observe(
                max(0.0, _time.time() - job.submitted_s)
            )
        if self.state is not None:
            self.state.save_job(job)
        # AQE learning that needs the full run's per-operator metrics
        # (inline-probe collect joins can only be sized post-hoc) —
        # BEFORE the trace closes so its decisions land in the span tree
        try:
            self.aqe.on_job_finished(job)
        except Exception:  # noqa: BLE001 — learning must never outrank
            # job completion
            log.exception("AQE completion policy failed for %s", job_id)
        # observability: stats + trace snapshot BEFORE the stage teardown
        # below — /api/job/<id> keeps serving the run's per-stage/
        # per-partition stats after completion (docs/observability.md)
        job.stage_stats = self.stage_manager.job_stage_detail(job_id)
        self._close_job_trace(job, "ok")
        self._retain_job_obs(job)
        # history log: exactly ONE terminal record per job, carrying the
        # latency/queue-wait/retry/skew counters and the aggregated cost
        # vector — the durable row system.queries serves
        try:
            self._job_terminal_history(job, "completed")
        except Exception:  # noqa: BLE001 — observability, never
            # completion-critical
            log.exception("history record failed for %s", job_id)
        # serving fast path (docs/serving.md): populate the result cache
        # from the COMMITTED locations, off-thread
        self._maybe_cache_result(job)
        # locations are snapshotted on the JobInfo; dropping the stage
        # bookkeeping zeroes the inflight count (KEDA's scale signal) and
        # stops fetch_schedulable_stage from ever seeing this job again
        self.stage_manager.remove_job_stages(job_id)
        log.info("job %s completed (%d partitions)", job_id, len(flat))

    def _on_job_failed(self, job_id: str, error: str) -> None:
        job = self._get_job(job_id)
        if job is None:
            return
        job.status = "failed"
        job.error = error
        job.stage_stats = self.stage_manager.job_stage_detail(job_id)
        self._close_job_trace(job, "error")
        self._retain_job_obs(job)
        try:
            self._job_terminal_history(job, "failed")
        except Exception:  # noqa: BLE001 — the failure path must not
            # fail on its own bookkeeping
            log.exception("history record failed for %s", job_id)
        # stage cleanup FIRST, and the write-through guarded: failure may
        # be the persistence backend itself, and skipping cleanup would
        # leave the failed job's PENDING tasks schedulable forever (push
        # mode hot-loops JobFailed<->ReviveOffers on an unresolvable
        # stage, and KEDA never sees the cluster go idle)
        self.stage_manager.remove_job_stages(job_id)
        if self.state is not None:
            try:
                self.state.save_job(job)
            except Exception:  # noqa: BLE001 — in-memory state still marks
                # the job failed; clients polling status get the error
                log.exception("persisting failed-job record for %s", job_id)
        log.error("job %s failed: %s", job_id, error)

    # -- task handout (pull mode; ref grpc.rs:121-147) -----------------------
    def _pick_eager_task(self, executor_id: str):
        """Eager-shuffle handout, tried only after assign_next_task found
        no runnable work: a pending consumer stage whose producers all
        have committed output may start fetching early (docs/shuffle.md).
        Soaking otherwise-idle slots is what makes this deadlock-free —
        any producer task that becomes PENDING again does so by freeing a
        slot (failure) or by lost-shuffle invalidation, and the next free
        slot always prefers runnable stages over eager ones."""
        with self._lock:
            eager_jobs = {
                jid
                for jid, j in self.jobs.items()
                if j.status == "running" and j.eager
            }
        if not eager_jobs:
            return None
        return self.stage_manager.assign_next_eager_task(
            executor_id, eager_jobs
        )

    def _eager_plan_bytes(self, job, job_id: str, stage_id: int) -> bytes:
        """Serialized eager resolution of one stage (cached: it depends
        only on the pristine template, never on locations, so recovery
        cannot invalidate it). Caller holds the server lock."""
        plan_bytes = job.eager_plan_bytes.get(stage_id)
        if plan_bytes is None:
            from ballista_tpu.distributed_plan import resolve_shuffles_eager

            plan = resolve_shuffles_eager(
                job.stages[stage_id].plan, job_id
            )
            plan_bytes = self.codec.physical_to_proto(
                plan
            ).SerializeToString()
            job.eager_plan_bytes[stage_id] = plan_bytes
        return plan_bytes

    def next_task(self, executor_id: str) -> pb.TaskDefinition | None:
        tasks = self.next_tasks(executor_id, 1)
        return tasks[0] if tasks else None

    def next_tasks(
        self, executor_id: str, max_n: int
    ) -> list[pb.TaskDefinition]:
        """Batched pull-mode handout (docs/serving.md): up to ``max_n``
        task definitions for one PollWork round-trip. Bypass grants go
        first (the latency-sensitive small jobs, queued FIFO outside the
        stage machinery), then stage tasks via ONE atomic batched pick
        (assign_next_tasks — the pick/mark race stays closed per batch),
        and only when nothing else was runnable, a single eager-shuffle
        task (eager consumers soak otherwise-idle slots; granting them a
        whole batch would starve runnable work arriving mid-poll)."""
        max_n = max(1, max_n)
        out: list[pb.TaskDefinition] = []
        while len(out) < max_n:
            td = self._next_bypass_task(executor_id)
            if td is None:
                break
            out.append(td)
        if len(out) < max_n:
            for picked in self.stage_manager.assign_next_tasks(
                executor_id, max_n - len(out)
            ):
                td = self._task_def_from_pick(picked, eager_pick=False)
                if td is not None:
                    out.append(td)
        if not out:
            picked = self._pick_eager_task(executor_id)
            if picked is not None:
                td = self._task_def_from_pick(picked, eager_pick=True)
                if td is not None:
                    out.append(td)
        return out

    def _task_def_from_pick(
        self, picked, eager_pick: bool
    ) -> pb.TaskDefinition | None:
        # atomic pick+mark inside the stage manager: two concurrent
        # PollWork threads previously could both see the same partition
        # PENDING (the second RUNNING mark was silently dropped as an
        # illegal RUNNING->RUNNING hop) and both run the task
        job_id, stage_id, partition, attempt, events = picked
        for e in events:
            self.event_loop.post(e)
        task_id = PartitionId(job_id, stage_id, partition)
        job = self._get_job(job_id)
        if job is None:
            # job torn down between the pick and here; release the task
            self.stage_manager.update_task_status(task_id, TaskState.PENDING)
            return None
        failure: JobFailed | None = None
        with self._lock:
            if eager_pick:
                try:
                    plan_bytes = self._eager_plan_bytes(
                        job, job_id, stage_id
                    )
                except Exception as e:  # noqa: BLE001 — deterministic
                    self.stage_manager.update_task_status(
                        task_id, TaskState.PENDING
                    )
                    failure = JobFailed(
                        job_id, stage_id,
                        f"eager stage resolution failed: {e}",
                    )
                    log.exception(
                        "eager stage %s/%s resolution failed",
                        job_id, stage_id,
                    )
            else:
                plan_bytes = job.resolved_plan_bytes.get(stage_id)
            if not eager_pick and plan_bytes is None:
                # lazy (re-)resolution under the server lock, serialized
                # against _on_shuffle_lost: recovery may have demoted this
                # stage and dropped its resolved bytes between the
                # schedulable pick above and here. Resolving while a
                # producer is incomplete would bake EMPTY location lists
                # for the lost partitions into the plan — the task would
                # then "succeed" with rows silently missing — so re-check
                # producer completeness first and back out.
                unresolved = find_unresolved_shuffles(
                    job.stages[stage_id].plan
                )
                if any(
                    not self.stage_manager.is_completed_stage(
                        job_id, u.stage_id
                    )
                    for u in unresolved
                ):
                    self.stage_manager.update_task_status(
                        task_id, TaskState.PENDING
                    )
                    return None
                try:
                    self._resolve_stage(job_id, stage_id)
                    plan_bytes = job.resolved_plan_bytes[stage_id]
                except Exception as e:  # noqa: BLE001
                    # roll the RUNNING mark back so the task isn't leaked
                    # on an executor that never received it, and fail the
                    # job — resolution is deterministic, retrying can't
                    # help. The JobFailed is POSTED AFTER the lock is
                    # released: the event queue is bounded, and a blocking
                    # put under the server lock while the consumer thread
                    # wants the same lock is the racelint deadlock shape
                    self.stage_manager.update_task_status(
                        task_id, TaskState.PENDING
                    )
                    failure = JobFailed(
                        job_id, stage_id, f"stage resolution failed: {e}"
                    )
                    log.exception(
                        "stage %s/%s resolution failed", job_id, stage_id
                    )
        if failure is not None:
            self.event_loop.post(failure)
            return None
        self._meter_first_assign(job)
        props = self._task_props(job, stage_id, attempt)
        return pb.TaskDefinition(
            task_id=pb.PartitionId(
                job_id=job_id, stage_id=stage_id, partition_id=partition
            ),
            plan=plan_bytes,
            props=props,
            session_id=job.session_id,
        )

    def _meter_first_assign(self, job: JobInfo) -> None:
        """Queue-wait metering (docs/observability.md): the FIRST task
        assignment of a job closes its submit->assignment gap — the
        admission/backpressure signal the composite autoscale pressure
        and the SLO harness read. Shared by the stage and bypass handout
        paths so bypassed jobs meter identically."""
        import time as _time

        now = _time.time()
        with self._lock:
            first_assign = job.first_assign_s == 0.0
            if first_assign:
                job.first_assign_s = now
        if first_assign and job.submitted_s:
            wait = max(0.0, now - job.submitted_s)
            self._h_queue_wait.labels(job.query_class).observe(wait)
            with self._lock:
                self._recent_queue_waits.append((now, wait))

    def _task_props(
        self, job: JobInfo, stage_id: int, attempt: int
    ) -> list[pb.KeyValuePair]:
        cfg = self._session_config(job.session_id)
        from ballista_tpu.config import (
            BALLISTA_INTERNAL_QUERY_CLASS,
            BALLISTA_INTERNAL_SPAN_PARENT,
            BALLISTA_INTERNAL_TASK_ATTEMPT,
            BALLISTA_INTERNAL_TRACE_ID,
        )

        props = [
            pb.KeyValuePair(key=k, value=v)
            for k, v in cfg.settings().items()
        ] + [
            # task-scoped (NOT session config; executors strip the
            # ballista.internal. prefix before building BallistaConfig):
            # the attempt number keys fault injection and retry logging;
            # the query class labels the executor's task-run histogram
            pb.KeyValuePair(
                key=BALLISTA_INTERNAL_TASK_ATTEMPT, value=str(attempt)
            ),
            pb.KeyValuePair(
                key=BALLISTA_INTERNAL_QUERY_CLASS, value=job.query_class
            ),
        ]
        if job.trace_id:
            # distributed tracing (docs/observability.md): the trace id
            # plus the stage span as the task-attempt span's parent —
            # a RETRY of a killed producer carries the SAME trace_id with
            # a new attempt span, which is what the chaos trace test
            # asserts
            props += [
                pb.KeyValuePair(
                    key=BALLISTA_INTERNAL_TRACE_ID, value=job.trace_id
                ),
                pb.KeyValuePair(
                    key=BALLISTA_INTERNAL_SPAN_PARENT,
                    value=self._stage_span_id(job, stage_id),
                ),
            ]
        return props

    # -- serving fast path (docs/serving.md) ---------------------------------
    def _submit_bypass(self, job: JobInfo, stage: QueryStage) -> None:
        """Register a single-stage job for direct grant: serialize the
        (already fully resolved — one stage means no placeholders) plan
        once, queue the job FIFO, and never touch the stage manager.
        Called from _generate_stages on the event-loop thread."""
        job_id = job.job_id
        job.stages[stage.stage_id] = stage
        job.final_stage_id = stage.stage_id
        job.bypass = True
        job.status = "running"
        plan_bytes = self.codec.physical_to_proto(
            stage.plan
        ).SerializeToString()
        if self.state is not None:
            self.state.save_stage_plan(job_id, stage.stage_id, stage.plan)
            self.state.save_job(job)
        self._open_stage_span(job, stage.stage_id)
        self._job_event(job, "bypass", attrs={"stage_id": stage.stage_id})
        with self._lock:
            job.resolved_plan_bytes[stage.stage_id] = plan_bytes
            self.obs_bypass_total += 1
            self._bypass_pending.append(job_id)

    def _next_bypass_task(
        self, executor_id: str
    ) -> pb.TaskDefinition | None:
        """Pop one queued bypass grant. The pending queue only ever holds
        job ids; torn-down/failed jobs are skipped here rather than
        scrubbed at teardown (the queue is short-lived and bounded by
        submission rate)."""
        job = None
        with self._lock:
            while self._bypass_pending:
                job_id = self._bypass_pending.popleft()
                j = self.jobs.get(job_id)
                if j is None or j.status != "running":
                    continue
                job = j
                stage_id = job.final_stage_id
                plan_bytes = job.resolved_plan_bytes[stage_id]
                attempt = self._bypass_attempts.get(job_id, 0)
                self._bypass_running[job_id] = executor_id
                break
        if job is None:
            return None
        self._meter_first_assign(job)
        props = self._task_props(job, stage_id, attempt)
        return pb.TaskDefinition(
            task_id=pb.PartitionId(
                job_id=job.job_id, stage_id=stage_id, partition_id=0
            ),
            plan=plan_bytes,
            props=props,
            session_id=job.session_id,
        )

    def _apply_bypass_status(
        self, job: JobInfo, tid: PartitionId, st: pb.TaskStatus, kind: str
    ) -> None:
        """Terminal handling for a bypassed job's single task — inline on
        the status RPC thread (no event-loop hop: bypass exists to cut
        exactly that latency, and a bypass job has no other events its
        completion could race)."""
        if kind == "completed":
            with self._lock:
                if job.status != "running":
                    return  # duplicate report after a terminal state
                self._bypass_running.pop(job.job_id, None)
            metas = [
                ShuffleWritePartitionMeta(
                    partition_id=int(p.partition_id),
                    path=p.path,
                    num_batches=int(p.num_batches),
                    num_rows=int(p.num_rows),
                    num_bytes=int(p.num_bytes),
                    push=bool(p.push),
                )
                for p in st.completed.partitions
            ]
            self._ingest_task_metrics(
                tid.job_id, tid.stage_id, tid.partition_id, st
            )
            try:
                self._ingest_task_cost(
                    tid, "completed", st.completed.executor_id,
                    st.completed.cost
                    if st.completed.HasField("cost") else None,
                )
            except Exception:  # noqa: BLE001
                log.exception("task-cost ingest failed for %s", tid)
            self._finish_bypass_job(job, st.completed.executor_id, metas)
        elif kind == "failed":
            error = st.failed.error
            try:
                self._ingest_task_cost(
                    tid, "failed", "",
                    st.failed.cost if st.failed.HasField("cost") else None,
                )
            except Exception:  # noqa: BLE001
                log.exception("task-cost ingest failed for %s", tid)
            retry = False
            with self._lock:
                if job.status != "running":
                    return
                self._bypass_running.pop(job.job_id, None)
                n = self._bypass_attempts.get(job.job_id, 0) + 1
                self._bypass_attempts[job.job_id] = n
                # same bounded-retry contract as the stage machinery:
                # the job's task_max_attempts snapshot caps attempts
                retry = error_is_retryable(error) and n < job.max_attempts
                if retry:
                    job.total_retries += 1
                    self._bypass_pending.append(job.job_id)
            if not retry:
                self._on_job_failed(
                    job.job_id,
                    f"task {tid.job_id}/{tid.stage_id}/"
                    f"{tid.partition_id} failed: {error}",
                )

    def _finish_bypass_job(
        self, job: JobInfo, executor_id: str,
        metas: list[ShuffleWritePartitionMeta],
    ) -> None:
        """Complete a bypassed job with full observability parity: the
        same locations shape (the client streams the result back through
        the existing Flight path), latency histogram, terminal history
        record, trace close, retention enrollment, and result-cache
        population as _on_job_finished."""
        host, port = self._executor_endpoint(executor_id)
        flat = [
            PartitionLocation(
                job_id=job.job_id,
                stage_id=job.final_stage_id,
                partition=m.partition_id,
                executor_id=executor_id,
                host=host,
                port=port,
                path=m.path,
                push=m.push,
                map_partition=0,
            )
            for m in metas
        ]
        job.completed_locations = flat
        job.status = "completed"
        if job.submitted_s:
            import time as _time

            self._h_job_latency.labels(job.query_class).observe(
                max(0.0, _time.time() - job.submitted_s)
            )
        if self.state is not None:
            try:
                self.state.save_job(job)
            except Exception:  # noqa: BLE001 — persistence must not
                # outrank the completion the client is polling for
                log.exception("persisting bypass job %s failed", job.job_id)
        self._finish_stage_span(job, job.final_stage_id)
        self._close_job_trace(job, "ok")
        self._retain_job_obs(job)
        try:
            self._job_terminal_history(job, "completed")
        except Exception:  # noqa: BLE001
            log.exception("history record failed for %s", job.job_id)
        self._maybe_cache_result(job)
        log.info(
            "job %s completed via bypass (%d partitions)",
            job.job_id, len(flat),
        )

    def _maybe_cache_result(self, job: JobInfo) -> None:
        """Kick off background result-cache population for a COMPLETED
        job. Off-thread: it re-reads the committed partitions (file or
        Flight), and the callers hold the completion path."""
        if not self.result_cache.enabled or job.cache_key is None:
            return
        if not job.completed_locations:
            return  # nothing committed to re-read; never cache a guess
        # fire-and-forget by design: one short-lived thread per
        # completed job, observed through result_cache.stats() (and the
        # resource witness when enabled), not a join
        t = threading.Thread(  # lifelint: transfer=job-completion-scoped
            target=self._populate_result_cache,
            args=(job,),
            daemon=True,
            name=f"result-cache-{job.job_id}",
        )
        t.start()

    def _populate_result_cache(self, job: JobInfo) -> None:
        """Fetch the job's committed final-stage partitions through the
        SAME reader path the client uses and store them as one Arrow IPC
        stream. Running strictly after the job completed is the
        committed-only guarantee: a task killed mid-run never reported
        partitions, so nothing partial is reachable from
        completed_locations; any fetch failure (executor died in the
        window) stores nothing."""
        try:
            import pyarrow as pa

            from ballista_tpu.executor.reader import fetch_partition_table
            from ballista_tpu.scheduler.result_cache import table_to_ipc

            # the client concatenates in completed_locations order —
            # matching it keeps a cache-served result bit-exact with a
            # freshly fetched one
            tables = [
                fetch_partition_table(loc)
                for loc in job.completed_locations
            ]
            table = (
                pa.concat_tables(tables) if len(tables) > 1 else tables[0]
            )
            payload = table_to_ipc(table)
            from ballista_tpu.analysis import stalewitness

            if stalewitness.enabled():
                # staleness witness: this fresh committed result is the
                # re-derivation for any demoted hit on the same key —
                # the served-payload hash registered at the demotion
                # must match it (no pending expectation -> no-op)
                from ballista_tpu.analysis import replay

                stalewitness.resolve(
                    "result_cache", job.cache_key,
                    replay.canonical_hash(table), table=table,
                )
            stored = self.result_cache.put(
                job.cache_key, payload, {"query_class": job.query_class}
            )
            if stored:
                self._job_event(
                    job, "cache",
                    attrs={"stored": True, "bytes": len(payload)},
                )
        except Exception:  # noqa: BLE001 — the cache is an optimization;
            # population failure must never surface to the finished job
            log.exception(
                "result-cache population failed for %s", job.job_id
            )

    # -- task handout (push mode; ref scheduler_server/event_loop.rs:35-169
    # + state/task_scheduler.rs:53-211) --------------------------------------
    def _drop_executor(self, executor_id: str) -> None:
        """Remove one executor from scheduling: slot data, heartbeats,
        dial-back client/channel, failure counter. Shared by the expiry
        sweep, the launch-failure path, and shutdown."""
        self.executor_manager.remove_executor(executor_id)
        self._launch_failures.pop(executor_id, None)
        with self._lock:
            self.executor_clients.pop(executor_id, None)
            ch = self._executor_channels.pop(executor_id, None)
        if ch is not None:
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass

    def _get_executor_client(self, executor_id: str):
        """Dial-back client to a push-mode executor's ExecutorGrpc service
        (ref scheduler_grpc.rs:180-192 — the scheduler connects using the
        grpc_port carried in RegisterExecutor metadata)."""
        import grpc as _grpc

        from ballista_tpu.scheduler.rpc import executor_stub

        with self._lock:
            stub = self.executor_clients.get(executor_id)
        if stub is not None:
            return stub
        em = self.executor_manager.get_executor_metadata(executor_id)
        if em is None or not em.grpc_port:
            return None
        # dial OUTSIDE the lock (racelint blocking-under-lock): channel
        # setup toward an unreachable executor must never stall other
        # control threads; a concurrent dial loses the store-race below
        # and its channel is closed
        ch = _grpc.insecure_channel(f"{em.host}:{em.grpc_port}")
        stub = executor_stub(ch)
        extra = None
        with self._lock:
            raced = self.executor_clients.get(executor_id)
            if raced is not None:
                stub, extra = raced, ch
            elif (
                self.executor_manager.get_executor_data(executor_id) is None
            ):
                # the expiry sweep dropped this executor while we dialed:
                # storing now would resurrect a stale entry that a later
                # re-registration (possibly on a new port) would keep
                # serving dead addresses from
                stub, extra = None, ch
            else:
                self._executor_channels[executor_id] = ch
                self.executor_clients[executor_id] = stub
        if extra is not None:
            try:
                extra.close()
            except Exception:  # noqa: BLE001
                pass
        return stub

    def _offer_resources(self) -> None:
        """Round-robin pack pending tasks onto free executor slots and
        LaunchTask each batch (ref task_scheduler.rs:53-211: walk executors
        in most-free-first order assigning one task per visit until slots
        or tasks run out; event_loop.rs:68-103 drives this on every
        ReviveOffers)."""
        if self.policy != TaskSchedulingPolicy.PUSH_STAGED:
            return
        # NO server lock around the assignment loop: ReviveOffers events
        # are consumed solely by the single event-loop thread (the only
        # caller), every structure touched has its own lock (executor
        # manager slots, stage manager picks — atomic via
        # assign_next_task), and holding the server lock across next_task
        # would hold it across event posts — the blocking-under-lock
        # deadlock shape racelint bans.
        assignments: dict[str, list[pb.TaskDefinition]] = {}
        execs = self.executor_manager.get_available_executors_data(
            self.executor_timeout_s
        )
        free = sum(d.available_task_slots for d in execs)
        i = 0
        while free > 0:
            d = execs[i % len(execs)]
            i += 1
            if d.available_task_slots <= 0:
                continue
            try:
                td = self.next_task(d.executor_id)
            except Exception:  # noqa: BLE001 — plan resolution failure
                log.exception("offer: next_task failed")
                break
            if td is None:
                break
            assignments.setdefault(d.executor_id, []).append(td)
            d.available_task_slots -= 1
            free -= 1
            self.executor_manager.update_executor_data(d.executor_id, -1)
        for eid, tasks in assignments.items():
            stub = self._get_executor_client(eid)
            ok = False
            if stub is not None:
                try:
                    # deadline is load-bearing: this runs on the single
                    # event-loop thread, and a blackholed executor without a
                    # call deadline would wedge all scheduling
                    stub.LaunchTask(
                        pb.LaunchTaskParams(tasks=tasks), timeout=10.0
                    )
                    ok = True
                    self._launch_failures.pop(eid, None)
                except Exception as e:  # noqa: BLE001 — executor unreachable
                    log.warning("LaunchTask to %s failed: %s", eid, e)
            if not ok:
                # roll back: tasks go RUNNING->PENDING (the legal executor-
                # lost reset) and slots are returned
                for td in tasks:
                    self.stage_manager.update_task_status(
                        PartitionId(
                            td.task_id.job_id,
                            td.task_id.stage_id,
                            td.task_id.partition_id,
                        ),
                        TaskState.PENDING,
                    )
                self.executor_manager.update_executor_data(eid, len(tasks))
                # a heartbeating-but-undialable executor would soak every
                # re-offer forever; after N consecutive failures drop it
                # from scheduling (its next heartbeat gets reregister=true,
                # which retries the dial-back from scratch)
                n_fail = self._launch_failures.get(eid, 0) + 1
                self._launch_failures[eid] = n_fail
                if n_fail >= self.max_launch_failures:
                    log.error(
                        "executor %s unreachable after %d LaunchTask "
                        "attempts; dropping from scheduling", eid, n_fail,
                    )
                    self._drop_executor(eid)
                # schedule a delayed re-offer (delayed, not immediate, so a
                # persistently unreachable executor can't spin the event
                # loop)
                t = threading.Timer(
                    1.0, self.event_loop.post, args=(ReviveOffers(),)
                )
                t.daemon = True
                t.start()

    def apply_task_statuses(self, statuses: list[pb.TaskStatus]) -> None:
        """ref scheduler_server/mod.rs update_task_status :171-191."""
        for st in statuses:
            tid = PartitionId(
                st.task_id.job_id, st.task_id.stage_id, st.task_id.partition_id
            )
            kind = st.WhichOneof("status")
            # bypassed jobs (docs/serving.md) have no stage bookkeeping:
            # their single task's terminal status completes/fails the job
            # inline instead of flowing through the stage state machine
            bjob = self._get_job(tid.job_id)
            if bjob is not None and bjob.bypass:
                if kind in ("completed", "failed"):
                    self._apply_bypass_status(bjob, tid, st, kind)
                continue
            if kind == "completed":
                metas = [
                    ShuffleWritePartitionMeta(
                        partition_id=int(p.partition_id),
                        path=p.path,
                        num_batches=int(p.num_batches),
                        num_rows=int(p.num_rows),
                        num_bytes=int(p.num_bytes),
                        push=bool(p.push),
                    )
                    for p in st.completed.partitions
                ]
                events = self.stage_manager.update_task_status(
                    tid,
                    TaskState.COMPLETED,
                    executor_id=st.completed.executor_id,
                    partitions=metas,
                )
                # per-operator metrics shipped home (docs/observability.md)
                self._ingest_task_metrics(
                    tid.job_id, tid.stage_id, tid.partition_id, st
                )
                # cost accounting: the attempt's resource vector sums
                # into the job + class rollups and the history log.
                # Guarded like the straggler metering below — an
                # escaping exception after the transition applied would
                # wedge the job (see that comment).
                try:
                    self._ingest_task_cost(
                        tid, "completed", st.completed.executor_id,
                        st.completed.cost
                        if st.completed.HasField("cost") else None,
                    )
                except Exception:  # noqa: BLE001
                    log.exception("task-cost ingest failed for %s", tid)
                # fleet plane: stage-task duration histogram + the
                # straggler check, both off the just-closed window.
                # Guarded: an escaping metering exception here would
                # abort the RPC AFTER update_task_status already applied
                # the transition — the executor's retry then replays a
                # now-illegal COMPLETED->COMPLETED hop that returns no
                # events, so the StageFinished/JobFinished generated
                # above would be lost FOREVER and the job would wedge
                # "running" (observed: a NameError in the straggler log
                # line wedged every straggler-flagging run).
                try:
                    self._observe_task_completion(tid)
                except Exception:  # noqa: BLE001 — metering must never
                    # outrank the terminal events it rides along with
                    log.exception(
                        "task-completion metering failed for %s", tid
                    )
            elif kind == "failed":
                error = st.failed.error
                # a ShuffleFetchError carries the SOURCE of the lost data;
                # trigger producer-side recovery and requeue the reader
                # without consuming one of its own attempts (the blame
                # belongs to the producing executor's lost output, and
                # boundedness comes from the producer's recompute cap)
                src = parse_shuffle_fetch_error(error)
                count_attempt = True
                if src is not None:
                    src_job, src_stage, _src_part, src_exec = src
                    recovered = self._on_shuffle_lost(
                        src_job or tid.job_id, src_stage, src_exec
                    )
                    # only skip the attempt charge when recovery actually
                    # re-opened something: otherwise (unparseable executor,
                    # repeated loss already handled) the normal bounded
                    # path keeps the failure from looping forever.
                    # Exception: an eager reader giving up on a SLOW (not
                    # lost) producer (docs/shuffle.md) — charging that
                    # would fail healthy jobs barriered mode would have
                    # waited out; the requeue is bounded by producer
                    # progress, exactly like barriered waiting.
                    eager_timeout = "[eager-wait-timeout]" in error
                    count_attempt = not (recovered or eager_timeout)
                # failed attempts charge their cost too (retries are
                # exactly the attempts a tenant should see billed)
                try:
                    self._ingest_task_cost(
                        tid, "failed", "",
                        st.failed.cost
                        if st.failed.HasField("cost") else None,
                    )
                except Exception:  # noqa: BLE001
                    log.exception("task-cost ingest failed for %s", tid)
                events = self.stage_manager.update_task_status(
                    tid,
                    TaskState.FAILED,
                    error=error,
                    retryable=error_is_retryable(error),
                    count_attempt=count_attempt,
                )
            elif kind == "running":
                events = self.stage_manager.update_task_status(
                    tid, TaskState.RUNNING, executor_id=st.running.executor_id
                )
            else:
                events = []
            for e in events:
                self.event_loop.post(e)

    def shuffle_locations_proto(
        self, job_id: str, stage_id: int, partition: int
    ) -> pb.ShuffleLocationsResult:
        """GetShuffleLocations (eager shuffle, docs/shuffle.md): the
        published map outputs of one producing stage feeding one output
        partition, plus the completed-task prefix and commit flag.
        ``failed`` tells the polling reader to stop waiting: the job is
        gone/failed, or the stage bookkeeping was torn down."""
        res = pb.ShuffleLocationsResult()
        job = self._get_job(job_id)
        if job is None or job.status not in ("queued", "running"):
            res.failed = True
            return res
        snap = self.stage_manager.shuffle_locations(
            job_id, stage_id, partition
        )
        if snap is None:
            res.failed = True
            return res
        entries, prefix, complete = snap
        res.tasks_done_prefix = prefix
        res.complete = complete
        for task_idx, executor_id, m in entries:
            host, port = self._executor_endpoint(executor_id)
            res.map_task.append(task_idx)
            res.locations.append(
                loc_to_proto(
                    PartitionLocation(
                        job_id=job_id,
                        stage_id=stage_id,
                        partition=partition,
                        executor_id=executor_id,
                        host=host,
                        port=port,
                        path=m.path,
                        # push-capable eager metadata (docs/shuffle.md)
                        push=m.push,
                        map_partition=task_idx,
                    )
                )
            )
        return res

    def job_status_proto(self, job_id: str) -> pb.JobStatus:
        job = self._get_job(job_id)
        if job is None:
            return pb.JobStatus(failed=pb.FailedJob(error="unknown job"))
        if job.status == "queued":
            return pb.JobStatus(queued=pb.QueuedJob())
        if job.status == "running":
            return pb.JobStatus(running=pb.RunningJob())
        if job.status == "failed":
            return pb.JobStatus(failed=pb.FailedJob(error=job.error))
        return pb.JobStatus(
            completed=pb.CompletedJob(
                partition_location=[
                    loc_to_proto(l) for l in job.completed_locations
                ],
                # result-cache hits (docs/serving.md): the payload rides
                # the status reply and the client short-circuits the
                # partition fetch entirely
                result_ipc=job.result_ipc,
            )
        )

    def shutdown(self) -> None:
        """Stop and JOIN every thread this server started (expiry sweep,
        event loop) — abandoning daemon threads leaks them across repeated
        start/stop cycles in one process (tests assert a zero
        ``threading.enumerate()`` delta)."""
        self._expiry_stop.set()
        self._expiry_thread.join(timeout=5)
        self.event_loop.stop()
        with self._lock:
            channels = list(self._executor_channels.values())
            self._executor_channels.clear()
            self.executor_clients.clear()
        # close outside the lock: channel teardown does socket work
        for ch in channels:
            try:
                ch.close()
            except Exception:  # noqa: BLE001
                pass


class SchedulerGrpcServicer:
    """The gRPC surface (ref grpc.rs:57-553)."""

    def __init__(self, server: SchedulerServer):
        self.s = server

    def PollWork(self, request: pb.PollWorkParams, context):
        # policy handshake: a pull-mode executor against a push-staged
        # scheduler must fail loudly, not be silently half-served (the
        # reference rejects PollWork under push-staged, grpc.rs:110-118)
        if self.s.policy == TaskSchedulingPolicy.PUSH_STAGED:
            import grpc as _grpc

            context.abort(
                _grpc.StatusCode.FAILED_PRECONDITION,
                "scheduler is push-staged; start the executor with "
                "--task-scheduling-policy push-staged",
            )
        meta = request.metadata
        em = ExecutorMetadata(
            id=meta.id,
            host=meta.host,
            port=meta.port,
            grpc_port=meta.grpc_port,
            specification=ExecutorSpecification(
                task_slots=meta.specification.task_slots or 4,
                n_devices=meta.specification.n_devices or 1,
            ),
        )
        self.s.executor_manager.save_executor_metadata(em)
        self.s.executor_manager.save_executor_heartbeat(meta.id)
        self.s.executor_manager.save_executor_metrics(
            meta.id, {kv.key: float(kv.value) for kv in request.metrics}
        )
        self.s.persist_executor(em)
        if self.s.executor_manager.get_executor_data(meta.id) is None:
            self.s.executor_manager.save_executor_data(
                ExecutorData(
                    meta.id,
                    em.specification.task_slots,
                    em.specification.task_slots,
                )
            )
        self.s.ingest_spans(list(request.spans))
        self.s.ingest_hists(list(request.hists))
        self.s.apply_task_statuses(list(request.task_status))
        result = pb.PollWorkResult()
        if request.can_accept_task:
            # batched grants (docs/serving.md): an executor advertising
            # free_slots gets up to min(free_slots, task_grant_batch)
            # tasks per round-trip; free_slots == 0 is a pre-batching
            # executor, which gets at most one. The batch knob is read
            # from the SCHEDULER's config — PollWork carries no session.
            max_n = 1
            if request.free_slots > 0:
                max_n = min(
                    int(request.free_slots),
                    self.s.config.task_grant_batch(),
                )
            tasks = self.s.next_tasks(meta.id, max_n)
            if tasks:
                result.tasks.extend(tasks)
                # mirror the first grant into the singular field so a
                # pre-batching executor still makes progress
                result.task.CopyFrom(tasks[0])
        return result

    def RegisterExecutor(self, request, context):
        # inverse policy handshake: a push-mode executor registering with a
        # pull-staged scheduler would wait for LaunchTasks that never come
        if self.s.policy != TaskSchedulingPolicy.PUSH_STAGED:
            import grpc as _grpc

            context.abort(
                _grpc.StatusCode.FAILED_PRECONDITION,
                "scheduler is pull-staged; start the executor with "
                "--task-scheduling-policy pull-staged",
            )
        meta = request.metadata
        em = ExecutorMetadata(
            id=meta.id,
            host=meta.host,
            port=meta.port,
            grpc_port=meta.grpc_port,
            specification=ExecutorSpecification(
                task_slots=meta.specification.task_slots or 4,
                n_devices=meta.specification.n_devices or 1,
            ),
        )
        self.s.executor_manager.save_executor_metadata(em)
        self.s.executor_manager.save_executor_heartbeat(meta.id)
        self.s.persist_executor(em)
        # keep existing slot accounting on re-registration (a recovered
        # executor may still be draining pre-expiry tasks; resetting to
        # full would oversubscribe it). After an expiry the data is gone and
        # a fresh full grant is unavoidable — tasks still physically running
        # from before the expiry can then transiently oversubscribe the
        # executor by up to task_slots; they queue behind its runner pool,
        # so the bound is 2x threads queued, not 2x executing
        if self.s.executor_manager.get_executor_data(meta.id) is None:
            self.s.executor_manager.save_executor_data(
                ExecutorData(
                    meta.id,
                    em.specification.task_slots,
                    em.specification.task_slots,
                )
            )
        # push mode: a new executor is new capacity — offer immediately
        # (ref scheduler_grpc.rs:166-199)
        if self.s.policy == TaskSchedulingPolicy.PUSH_STAGED:
            self.s.event_loop.post(ReviveOffers())
        return pb.RegisterExecutorResult(success=True)

    def HeartBeatFromExecutor(self, request, context):
        self.s.executor_manager.save_executor_heartbeat(request.executor_id)
        self.s.executor_manager.save_executor_metrics(
            request.executor_id,
            {kv.key: float(kv.value) for kv in request.metrics},
        )
        self.s.ingest_spans(list(request.spans))
        self.s.ingest_hists(list(request.hists))
        # an executor the expiry sweep dropped (or a scheduler that restarted
        # without its registration) must re-register to get slots back
        reregister = (
            self.s.executor_manager.get_executor_data(request.executor_id)
            is None
        )
        return pb.HeartBeatResult(reregister=reregister)

    def UpdateTaskStatus(self, request, context):
        self.s.ingest_spans(list(request.spans))
        self.s.apply_task_statuses(list(request.task_status))
        n_done = sum(
            1
            for st in request.task_status
            if st.WhichOneof("status") in ("completed", "failed")
        )
        if n_done:
            self.s.executor_manager.update_executor_data(
                request.executor_id, n_done
            )
            # push mode: freed slots may unlock queued tasks even when no
            # stage event fired (ref scheduler_grpc.rs:246-252)
            if self.s.policy == TaskSchedulingPolicy.PUSH_STAGED:
                self.s.event_loop.post(ReviveOffers())
        return pb.UpdateTaskStatusResult(success=True)

    def GetFileMetadata(self, request, context):
        """Parquet-only schema inference (ref grpc.rs:279-326)."""
        import pyarrow.parquet as papq

        from ballista_tpu.columnar.arrow_interop import schema_from_arrow
        from ballista_tpu.serde import schema_to_proto

        if request.file_type not in ("parquet", ""):
            context.abort(
                __import__("grpc").StatusCode.INVALID_ARGUMENT,
                f"unsupported file type {request.file_type!r}",
            )
        schema = schema_from_arrow(papq.read_schema(request.path))
        return pb.GetFileMetadataResult(schema=schema_to_proto(schema))

    def ExecuteQuery(self, request, context):
        settings = {kv.key: kv.value for kv in request.settings}
        session_id = self.s.get_or_create_session(request.session_id, settings)
        kind = request.WhichOneof("query")
        if kind is None:
            # session-create-only call (ref context.rs remote() :83-135)
            return pb.ExecuteQueryResult(job_id="", session_id=session_id)
        try:
            if kind == "sql":
                job_id = self.s.submit_sql(request.sql, session_id)
            else:
                from ballista_tpu.serde import logical_from_proto

                node = pb.LogicalPlanNode()
                node.ParseFromString(request.logical_plan)
                job_id = self.s.submit_logical(
                    logical_from_proto(node), session_id
                )
        except Exception as e:  # noqa: BLE001
            log.exception("ExecuteQuery failed")
            job_id = generate_job_id()
            with self.s._lock:
                self.s.jobs[job_id] = JobInfo(
                    job_id=job_id, session_id=session_id, status="failed",
                    error=str(e),
                )
        return pb.ExecuteQueryResult(job_id=job_id, session_id=session_id)

    def GetJobStatus(self, request, context):
        return pb.GetJobStatusResult(
            status=self.s.job_status_proto(request.job_id)
        )

    def GetShuffleLocations(self, request, context):
        """Eager-shuffle location poll (request reuses the FetchPartition
        vocabulary: job, producing stage, output partition)."""
        return self.s.shuffle_locations_proto(
            request.job_id, request.stage_id, request.partition_id
        )

    def GetHistory(self, request, context):
        """Queryable history (docs/observability.md): the persistent
        query log / per-attempt cost records / executor roster, as JSON
        rows — the source the client-side system.* SQL tables
        materialize from."""
        import json as _json

        try:
            rows = self.s.history_payload(
                request.kind or "queries", int(request.limit)
            )
        except ValueError as e:
            import grpc as _grpc

            context.abort(_grpc.StatusCode.INVALID_ARGUMENT, str(e))
        return pb.GetHistoryResult(payload=_json.dumps(rows).encode())


def start_scheduler_grpc(
    server: SchedulerServer, host: str = "0.0.0.0", port: int = 0
):
    """Start the gRPC server; returns (grpc_server, bound_port)."""
    import grpc as _grpc

    from ballista_tpu.scheduler.rpc import (
        SCHEDULER_METHODS,
        SCHEDULER_SERVICE,
        add_service,
    )

    gs = _grpc.server(
        __import__("concurrent.futures", fromlist=["ThreadPoolExecutor"])
        .ThreadPoolExecutor(max_workers=16)
    )
    add_service(gs, SCHEDULER_SERVICE, SCHEDULER_METHODS, SchedulerGrpcServicer(server))
    # KEDA external scaler rides the same port (ref main.rs:136-166
    # multiplexes gRPC services on the scheduler's bind address)
    from ballista_tpu.scheduler.external_scaler import add_external_scaler

    add_external_scaler(gs, server)
    bound = gs.add_insecure_port(f"{host}:{port}")
    gs.start()
    return gs, bound
