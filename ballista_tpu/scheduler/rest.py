"""Scheduler REST API + status UI.

ref ballista/rust/scheduler/src/api/{mod,handlers}.rs — ``GET /api/state``
returns the executor roster + uptime as JSON (handlers.rs:34-57); the
scheduler also serves a human status page (the reference ships a yew/WASM
UI under ballista/ui; here a single self-contained HTML page renders the
same state from ``/api/state``).

Implemented over the stdlib ThreadingHTTPServer — the REST tier is a thin
read-only view of :class:`SchedulerServer`, not a data path.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger(__name__)

BALLISTA_VERSION = "0.6.0-tpu"

_UI_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>ballista-tpu scheduler</title>
<style>
 :root { --ink:#1a1a2e; --mut:#6b7280; --line:#e5e7eb; --bg:#f8f9fb;
         --ok:#15803d; --run:#1d4ed8; --bad:#b91c1c; --pend:#92400e; }
 body { font-family: system-ui, sans-serif; margin: 0; color: var(--ink);
        background: var(--bg); }
 header { background: #111827; color: #f9fafb; padding: .8rem 1.5rem;
          display: flex; align-items: baseline; gap: 1rem; }
 header h1 { font-size: 1.1rem; margin: 0; }
 header .muted { color: #9ca3af; font-size: .8rem; }
 main { padding: 1rem 1.5rem 3rem; max-width: 72rem; margin: 0 auto; }
 .tiles { display: grid; grid-template-columns: repeat(auto-fit, minmax(9rem,1fr));
          gap: .8rem; margin: 1rem 0 1.5rem; }
 .tile { background: #fff; border: 1px solid var(--line); border-radius: .5rem;
         padding: .7rem .9rem; }
 .tile .v { font-size: 1.45rem; font-weight: 600; }
 .tile .l { color: var(--mut); font-size: .75rem; text-transform: uppercase;
            letter-spacing: .05em; }
 h2 { font-size: .95rem; margin: 1.4rem 0 .5rem; }
 table { border-collapse: collapse; width: 100%; background: #fff;
         border: 1px solid var(--line); border-radius: .5rem; overflow: hidden; }
 th, td { text-align: left; padding: .4rem .8rem;
          border-bottom: 1px solid var(--line); font-size: .85rem; }
 th { background: #f3f4f6; font-weight: 600; }
 tr:last-child td { border-bottom: none; }
 .muted { color: var(--mut); font-size: .85rem; }
 .pill { display: inline-block; border-radius: 999px; padding: .05rem .55rem;
         font-size: .72rem; font-weight: 600; }
 .pill.completed { background: #dcfce7; color: var(--ok); }
 .pill.running   { background: #dbeafe; color: var(--run); }
 .pill.failed    { background: #fee2e2; color: var(--bad); }
 .pill.queued, .pill.pending { background: #fef3c7; color: var(--pend); }
 .bar { background: var(--line); border-radius: 999px; height: .5rem;
        min-width: 7rem; overflow: hidden; }
 .bar > div { background: var(--run); height: 100%; }
 .bar.done > div { background: var(--ok); }
 details.job { margin: 0; }
 .stageplan { font-family: ui-monospace, monospace; font-size: .75rem;
              white-space: pre; overflow-x: auto; background: #f9fafb;
              border: 1px solid var(--line); border-radius: .35rem;
              padding: .5rem .7rem; margin: .3rem 0 .7rem; }
 .dag { font-size: .8rem; color: var(--mut); margin: .2rem 0 .4rem; }
 td.exp { cursor: pointer; color: var(--run); user-select: none; }
</style></head>
<body>
<header><h1>ballista-tpu scheduler</h1><div class="muted" id="meta"></div></header>
<main>
<div class="tiles">
 <div class="tile"><div class="v" id="t-exec">–</div><div class="l">executors alive</div></div>
 <div class="tile"><div class="v" id="t-slots">–</div><div class="l">slots free / total</div></div>
 <div class="tile"><div class="v" id="t-dev">–</div><div class="l">mesh devices</div></div>
 <div class="tile"><div class="v" id="t-running">–</div><div class="l">jobs running</div></div>
 <div class="tile"><div class="v" id="t-done">–</div><div class="l">jobs completed</div></div>
 <div class="tile"><div class="v" id="t-failed">–</div><div class="l">jobs failed</div></div>
</div>
<h2>Executors</h2>
<table id="executors"><thead><tr>
 <th>id</th><th>host</th><th>flight port</th><th>devices</th>
 <th>slots (free/total)</th><th>last seen</th>
</tr></thead><tbody></tbody></table>
<h2>Jobs</h2>
<table id="jobs"><thead><tr>
 <th></th><th>job id</th><th>status</th><th>stages</th><th>progress</th>
 <th>stage detail</th><th>error</th>
</tr></thead><tbody></tbody></table>
</main>
<script>
// textContent only — job errors echo user SQL fragments, never as HTML
function td(parent, text, cls) {
  const el = document.createElement('td');
  if (cls) el.className = cls;
  el.textContent = text;
  parent.appendChild(el);
  return el;
}
function pill(state) {
  const s = document.createElement('span');
  s.className = 'pill ' + state;
  s.textContent = state;
  return s;
}
const open = new Set();
async function expand(jobId, tr, ncols) {
  if (open.has(jobId)) { open.delete(jobId); tr.nextSibling?.remove(); return; }
  open.add(jobId);
  const r = await fetch('api/job/' + encodeURIComponent(jobId));
  if (!r.ok) return;
  const d = await r.json();
  const drow = document.createElement('tr');
  const cell = document.createElement('td');
  cell.colSpan = ncols;
  for (const st of d.stages) {
    const h = document.createElement('div');
    h.className = 'dag';
    h.textContent = `stage ${st.stage_id}` +
      (st.depends_on.length ? ` ⇐ depends on [${st.depends_on.join(', ')}]` : ' (leaf)') +
      (st.stage_id === d.final_stage_id ? '  · final' : '');
    cell.appendChild(h);
    const pre = document.createElement('div');
    pre.className = 'stageplan';
    pre.textContent = st.plan;
    cell.appendChild(pre);
  }
  drow.appendChild(cell);
  tr.after(drow);
}
async function refresh() {
  const r = await fetch('api/state'); const s = await r.json();
  document.getElementById('meta').textContent =
    `v${s.version} · up ${Math.round(s.uptime_seconds)}s · policy ${s.policy}`;
  let free = 0, total = 0, dev = 0;
  const ex = document.querySelector('#executors tbody'); ex.innerHTML = '';
  for (const e of s.executors) {
    free += e.available_task_slots ?? 0; total += e.total_task_slots ?? 0;
    dev += e.n_devices ?? 1;
    const tr = document.createElement('tr');
    td(tr, e.id); td(tr, e.host); td(tr, e.port);
    td(tr, e.n_devices ?? 1);
    td(tr, `${e.available_task_slots ?? '-'} / ${e.total_task_slots ?? '-'}`);
    td(tr, e.last_seen_seconds_ago == null ? 'never'
        : e.last_seen_seconds_ago.toFixed(1) + 's ago');
    ex.appendChild(tr);
  }
  document.getElementById('t-exec').textContent = s.executors.length;
  document.getElementById('t-slots').textContent = `${free} / ${total}`;
  document.getElementById('t-dev').textContent = dev;
  const counts = {running: 0, completed: 0, failed: 0};
  const jb = document.querySelector('#jobs tbody'); jb.innerHTML = '';
  for (const j of s.jobs) {
    counts[j.status] = (counts[j.status] ?? 0) + 1;
    const stages = j.stages || [];
    let done = 0, total = 0;
    const detail = stages.map(st => {
      done += st.tasks.completed; total += st.n_tasks;
      return `s${st.stage_id}:${st.state}` +
        (st.state === 'running'
          ? ` (${st.tasks.completed}/${st.n_tasks})` : '');
    }).join('  ');
    const tr = document.createElement('tr');
    const e = td(tr, open.has(j.job_id) ? '▾' : '▸', 'exp');
    e.onclick = () => expand(j.job_id, tr, 7).then(refreshCaret);
    function refreshCaret() { e.textContent = open.has(j.job_id) ? '▾' : '▸'; }
    td(tr, j.job_id);
    td(tr, '').appendChild(pill(j.status));
    td(tr, j.n_stages);
    // finished jobs have their stage bookkeeping torn down — no counts
    const pc = td(tr, '');
    if (j.status === 'completed' || (total > 0)) {
      const bar = document.createElement('div');
      bar.className = 'bar' + (j.status === 'completed' ? ' done' : '');
      const fill = document.createElement('div');
      fill.style.width = (j.status === 'completed' ? 100
        : total ? Math.round(100 * done / total) : 0) + '%';
      bar.appendChild(fill); pc.appendChild(bar);
    } else pc.textContent = '-';
    td(tr, detail);
    td(tr, j.error || '');
    jb.appendChild(tr);
    if (open.has(j.job_id)) { open.delete(j.job_id); expand(j.job_id, tr, 7); }
  }
  document.getElementById('t-running').textContent = counts.running ?? 0;
  document.getElementById('t-done').textContent = counts.completed ?? 0;
  document.getElementById('t-failed').textContent = counts.failed ?? 0;
}
refresh(); setInterval(refresh, 2000);
</script>
</body></html>
"""


def scheduler_state(server) -> dict:
    """The /api/state payload (ref handlers.rs:34-57, extended with slot
    and job detail the UI renders)."""
    now = time.time()
    executors = []
    for em in server.executor_manager.all_executors():
        data = server.executor_manager.get_executor_data(em.id)
        seen = server.executor_manager.last_seen(em.id)
        executors.append(
            {
                "id": em.id,
                "host": em.host,
                "port": em.port,
                "grpc_port": em.grpc_port,
                "n_devices": em.specification.n_devices or 1,
                "total_task_slots": data.total_task_slots if data else None,
                "available_task_slots": (
                    data.available_task_slots if data else None
                ),
                "last_seen_seconds_ago": (
                    round(now - seen, 3) if seen is not None else None
                ),
                # REST-hardening alias (docs/observability.md): the
                # monitoring-friendly name dashboards expect
                "last_heartbeat_age_s": (
                    round(now - seen, 3) if seen is not None else None
                ),
                # latest compile-latency counters (traces, XLA compiles,
                # persistent-cache hits/misses, prewarm progress) the
                # executor shipped on its heartbeat/poll
                # (docs/compile_cache.md)
                "compile": server.executor_manager.get_executor_metrics(
                    em.id
                ),
            }
        )
    with server._lock:
        job_snapshot = list(server.jobs.values())
    jobs = [
        {
            "job_id": j.job_id,
            "status": j.status,
            "n_stages": len(j.stages),
            "error": j.error,
            # fault-tolerance visibility: bounded task retries + lost-
            # shuffle recompute rounds (both 0 on a clean run; chaos tests
            # assert on these)
            "retries": j.total_retries,
            "recomputes": j.total_recomputes,
            "rewrites": j.total_rewrites,
            # per-stage DAG state + task counts (the reference UI's job
            # detail view; ref ballista/ui job/stage tables)
            "stages": server.stage_manager.job_stage_summary(j.job_id),
        }
        for j in job_snapshot
    ]
    return {
        "executors": executors,
        "jobs": jobs,
        "started": int(server.start_time * 1000),
        "uptime_seconds": now - server.start_time,
        # monitoring-friendly alias (docs/observability.md)
        "uptime_s": round(now - server.start_time, 3),
        "policy": server.policy.value,
        "version": BALLISTA_VERSION,
    }


def job_detail(server, job_id: str) -> dict | None:
    """Per-job stage DAG detail for the UI's expandable rows: stage
    dependency edges + the physical plan display of every stage (the
    reference UI's query-detail view, ballista/ui stage tables)."""
    with server._lock:
        job = server.jobs.get(job_id)
        if job is None:
            return None
        stages = []
        for sid in sorted(job.stages):
            deps = sorted(
                child
                for child, parents in job.dependencies.items()
                if sid in parents
            )
            stages.append(
                {
                    "stage_id": sid,
                    "depends_on": deps,
                    "plan": job.stages[sid].plan.display(),
                }
            )
        out = {
            "job_id": job_id,
            "status": job.status,
            "error": job.error,
            "final_stage_id": job.final_stage_id,
            "stages": stages,
            "retries": job.total_retries,
            "recomputes": job.total_recomputes,
            # certified-rewrite visibility (docs/analysis.md): accepted
            # template swaps + certificate rejections
            "rewrites": job.total_rewrites,
            "rewrite_rejects": job.total_rewrite_rejects,
            # per-rewrite decision log (docs/aqe.md): op, touched stage
            # ids, outcome, and the failing certificate clause on a
            # reject — the "why did this stage change shape" answer
            "rewrite_log": [dict(r) for r in job.rewrite_log],
            # AQE policy decisions layered over the rewrites: source
            # (reactive/learned) + before/after stats per decision
            "aqe": [dict(d) for d in job.aqe_decisions],
            "trace_id": job.trace_id,
            # fleet observability (docs/observability.md): the label
            # every latency series for this job aggregates under, plus
            # the skew monitor's flagged partitions (the AQE split input)
            "query_class": job.query_class,
            "skew": [
                {"stage_id": s, "partition": p}
                for s, p in sorted(job.skew_flags)
            ],
            # aggregated resource cost (docs/observability.md): every
            # attempt's shipped cost vector summed — the same numbers
            # the history record persists
            "cost": job.cost.to_dict() if job.cost is not None else {},
        }
    # stats/trace aggregation takes the server lock itself — outside the
    # block above (the lock is reentrant, but the narrower the section
    # the better)
    stats = server.job_stats(job_id)
    if stats is not None:
        # per-stage / per-(stage,partition) rows+bytes+attempts plus the
        # shipped per-operator metrics (docs/observability.md) — live
        # while running, from the completion snapshot afterwards
        out.update(stats)
    trace = server.job_trace(job_id)
    if trace:
        out["spans"] = trace
    return out


def job_timeline(server, job_id: str) -> dict | None:
    """``GET /api/job/<id>/timeline``: the per-task Gantt view
    (docs/observability.md) — one row per (stage, partition) with the
    current attempt's wall-clock window, executor, attempt count, and
    the straggler/skew flags. Reconstructed from the stage bookkeeping
    while the job runs and from the completion snapshot afterwards;
    running tasks additionally get a LIVE straggler projection (now -
    start already beyond the flag threshold) so a wedged task shows up
    before it finishes. None for unknown jobs."""
    with server._lock:
        job = server.jobs.get(job_id)
        if job is None:
            return None
        skew = set(job.skew_flags)
        # stages whose template an accepted certified rewrite swapped
        # (docs/aqe.md): the Gantt view marks their rows so a mid-job
        # partition-count change is explained, not mysterious
        rewritten = set(job.rewritten_stages)
        # push-shuffle data-plane counters per (stage, partition) from
        # the shipped per-operator metrics (docs/shuffle.md): how many
        # bytes each task committed in memory, spilled under window
        # pressure, or made consumers fall back to the pull plane
        push_by_task: dict = {}
        for (sid, part), records in job.op_metrics.items():
            agg = {"pushed_bytes": 0, "push_spill_bytes": 0,
                   "push_fallbacks": 0}
            for r in records:
                for k in agg:
                    v = r.get("counters", {}).get(k)
                    if isinstance(v, (int, float)):
                        agg[k] += int(v)
            push_by_task[(sid, part)] = agg
    stages = job.stage_stats
    if stages is None:
        stages = server.stage_manager.job_stage_detail(job_id)
    from ballista_tpu.scheduler.stage_manager import straggler_stats

    cfg = server._session_config(job.session_id)
    factor = cfg.straggler_factor()
    min_s = cfg.straggler_min_s()
    now = time.time()
    tasks = []
    for st in stages:
        durations = [
            t["ended_s"] - t["started_s"]
            for t in st["tasks"]
            if t["state"] == "completed" and t["started_s"] and t["ended_s"]
        ]
        # the SAME threshold the committing monitor uses — the live
        # projection must agree with the counter about the same task
        stats = straggler_stats(durations, factor, min_s)
        threshold = stats[0] if stats is not None else None
        for t in st["tasks"]:
            start, end = t["started_s"], t["ended_s"]
            dur = (end - start) if (start and end) else (
                (now - start) if start else 0.0
            )
            straggler = bool(t.get("straggler"))
            if (
                not straggler
                and threshold is not None
                and t["state"] == "running"
                and start
                and now - start > threshold
            ):
                straggler = True  # live projection, not yet committed
            push = push_by_task.get(
                (st["stage_id"], t["partition"]),
                {"pushed_bytes": 0, "push_spill_bytes": 0,
                 "push_fallbacks": 0},
            )
            tasks.append(
                {
                    "stage_id": st["stage_id"],
                    "partition": t["partition"],
                    "state": t["state"],
                    "executor_id": t["executor_id"],
                    "attempts": t["attempts"],
                    "start_s": start,
                    "end_s": end,
                    "duration_s": round(max(0.0, dur), 6),
                    "straggler": straggler,
                    "skewed": (st["stage_id"], t["partition"]) in skew,
                    # this stage's template was swapped by an accepted
                    # certified rewrite (AQE or manual) — docs/aqe.md
                    "rewritten": st["stage_id"] in rewritten,
                    # push data-plane visibility (docs/shuffle.md)
                    "pushed_bytes": push["pushed_bytes"],
                    "push_spill_bytes": push["push_spill_bytes"],
                    "push_fallbacks": push["push_fallbacks"],
                }
            )
    return {
        "job_id": job_id,
        "status": job.status,
        "query_class": job.query_class,
        "submitted_s": round(job.submitted_s, 6),
        "first_assign_s": round(job.first_assign_s, 6),
        "tasks": tasks,
    }


def start_rest_server(server, host: str = "0.0.0.0", port: int = 0):
    """Serve /api/state, /api/job/<id> + the status page. Returns
    (httpd, bound_port)."""

    class Handler(BaseHTTPRequestHandler):
        def _reply(self, status: int, body: bytes, ctype: str) -> None:
            self.send_response(status)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if path in ("/api/state", "/state"):
                body = json.dumps(scheduler_state(server)).encode()
                ctype = "application/json"
            elif path in ("/api/history", "/history"):
                # the persistent query log (docs/observability.md):
                # ?kind=queries|task_attempts|executors, ?limit=N
                from urllib.parse import parse_qs, urlparse

                q = parse_qs(urlparse(self.path).query)
                kind = (q.get("kind") or ["queries"])[0]
                try:
                    limit = int((q.get("limit") or ["0"])[0])
                except ValueError:
                    limit = 0
                try:
                    rows = server.history_payload(kind, limit)
                except ValueError:
                    self._reply(
                        400,
                        json.dumps(
                            {"error": "unknown kind", "kind": kind}
                        ).encode(),
                        "application/json",
                    )
                    return
                body = json.dumps({"kind": kind, "rows": rows}).encode()
                ctype = "application/json"
            elif path in ("/api/metrics", "/metrics"):
                # the scrapeable metrics plane (docs/observability.md):
                # Prometheus text exposition of scheduler + shipped
                # executor counters. Guarded like the executor-side
                # endpoint: a scrape racing executor expiry must get a
                # 500, not an aborted connection.
                from ballista_tpu.obs import prometheus as prom

                try:
                    body = prom.render(
                        prom.scheduler_families(server)
                    ).encode()
                except Exception:  # noqa: BLE001
                    log.exception("metrics render failed")
                    self._reply(
                        500,
                        json.dumps({"error": "metrics render failed"}).encode(),
                        "application/json",
                    )
                    return
                ctype = prom.CONTENT_TYPE
            elif path.startswith("/api/job/"):
                from urllib.parse import unquote

                tail = unquote(path[len("/api/job/"):])
                if tail.endswith("/timeline"):
                    # per-task Gantt view (docs/observability.md)
                    job_id = tail[: -len("/timeline")]
                    detail = job_timeline(server, job_id)
                else:
                    job_id = tail
                    detail = job_detail(server, job_id)
                if detail is None:
                    # REST hardening: a proper 404 with a JSON body (the
                    # stdlib send_error serves an HTML error page, which
                    # API clients then fail to parse on top of the 404)
                    self._reply(
                        404,
                        json.dumps(
                            {"error": "unknown job", "job_id": job_id}
                        ).encode(),
                        "application/json",
                    )
                    return
                body = json.dumps(detail).encode()
                ctype = "application/json"
            elif path == "/":
                body = _UI_PAGE.encode()
                ctype = "text/html; charset=utf-8"
            else:
                self._reply(
                    404,
                    json.dumps({"error": "not found", "path": path}).encode(),
                    "application/json",
                )
                return
            self._reply(200, body, ctype)

        def log_message(self, fmt, *args):
            log.debug("rest: " + fmt, *args)

    httpd = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True, name="rest")
    # the serve thread rides on the httpd so stop_rest_server can join it
    # (a bare .shutdown() stopped serve_forever but left the LISTENING
    # SOCKET open and the thread unjoined — lifelint leaked-resource)
    httpd._serve_thread = t
    t.start()
    return httpd, httpd.server_address[1]


def stop_rest_server(httpd) -> None:
    """Full REST teardown: stop serve_forever, join the serve thread, and
    CLOSE the listening socket (``shutdown()`` alone leaks it until
    process exit — repeated start/stop cycles would pile up bound fds)."""
    httpd.shutdown()
    t = getattr(httpd, "_serve_thread", None)
    if t is not None:
        t.join(timeout=5)
    httpd.server_close()
