"""Scheduler REST API + status UI.

ref ballista/rust/scheduler/src/api/{mod,handlers}.rs — ``GET /api/state``
returns the executor roster + uptime as JSON (handlers.rs:34-57); the
scheduler also serves a human status page (the reference ships a yew/WASM
UI under ballista/ui; here a single self-contained HTML page renders the
same state from ``/api/state``).

Implemented over the stdlib ThreadingHTTPServer — the REST tier is a thin
read-only view of :class:`SchedulerServer`, not a data path.
"""

from __future__ import annotations

import json
import logging
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

log = logging.getLogger(__name__)

BALLISTA_VERSION = "0.6.0-tpu"

_UI_PAGE = """<!doctype html>
<html><head><meta charset="utf-8"><title>ballista-tpu scheduler</title>
<style>
 body { font-family: system-ui, sans-serif; margin: 2rem; color: #1a1a2e; }
 h1 { font-size: 1.3rem; } h2 { font-size: 1.05rem; margin-top: 1.5rem; }
 table { border-collapse: collapse; min-width: 40rem; }
 th, td { text-align: left; padding: .35rem .8rem; border-bottom: 1px solid #ddd; }
 th { background: #f4f4f8; }
 .muted { color: #777; font-size: .85rem; }
</style></head>
<body>
<h1>ballista-tpu scheduler</h1>
<div class="muted" id="meta"></div>
<h2>Executors</h2>
<table id="executors"><thead><tr>
 <th>id</th><th>host</th><th>flight port</th><th>slots (free/total)</th><th>last seen</th>
</tr></thead><tbody></tbody></table>
<h2>Jobs</h2>
<table id="jobs"><thead><tr>
 <th>job id</th><th>status</th><th>stages</th><th>tasks (done/total)</th><th>stage detail</th><th>error</th>
</tr></thead><tbody></tbody></table>
<script>
// textContent only — job errors echo user SQL fragments, never as HTML
function row(tbody, cells) {
  const tr = document.createElement('tr');
  for (const c of cells) {
    const td = document.createElement('td');
    td.textContent = c;
    tr.appendChild(td);
  }
  tbody.appendChild(tr);
}
async function refresh() {
  const r = await fetch('api/state'); const s = await r.json();
  document.getElementById('meta').textContent =
    `version ${s.version} — up ${Math.round(s.uptime_seconds)}s — policy ${s.policy}`;
  const ex = document.querySelector('#executors tbody'); ex.innerHTML = '';
  for (const e of s.executors) {
    row(ex, [e.id, e.host, e.port,
      `${e.available_task_slots ?? '-'} / ${e.total_task_slots ?? '-'}`,
      e.last_seen_seconds_ago == null ? 'never'
        : e.last_seen_seconds_ago.toFixed(1) + 's ago']);
  }
  const jb = document.querySelector('#jobs tbody'); jb.innerHTML = '';
  for (const j of s.jobs) {
    const stages = j.stages || [];
    let done = 0, total = 0;
    const detail = stages.map(st => {
      done += st.tasks.completed; total += st.n_tasks;
      return `s${st.stage_id}:${st.state}` +
        (st.state === 'running'
          ? ` (${st.tasks.completed}/${st.n_tasks})` : '');
    }).join('  ');
    // finished jobs have their stage bookkeeping torn down — no counts
    row(jb, [j.job_id, j.status, j.n_stages,
             stages.length ? `${done} / ${total}` : '-',
             detail, j.error || '']);
  }
}
refresh(); setInterval(refresh, 2000);
</script>
</body></html>
"""


def scheduler_state(server) -> dict:
    """The /api/state payload (ref handlers.rs:34-57, extended with slot
    and job detail the UI renders)."""
    now = time.time()
    executors = []
    for em in server.executor_manager.all_executors():
        data = server.executor_manager.get_executor_data(em.id)
        seen = server.executor_manager.last_seen(em.id)
        executors.append(
            {
                "id": em.id,
                "host": em.host,
                "port": em.port,
                "grpc_port": em.grpc_port,
                "total_task_slots": data.total_task_slots if data else None,
                "available_task_slots": (
                    data.available_task_slots if data else None
                ),
                "last_seen_seconds_ago": (
                    round(now - seen, 3) if seen is not None else None
                ),
            }
        )
    with server._lock:
        job_snapshot = list(server.jobs.values())
    jobs = [
        {
            "job_id": j.job_id,
            "status": j.status,
            "n_stages": len(j.stages),
            "error": j.error,
            # per-stage DAG state + task counts (the reference UI's job
            # detail view; ref ballista/ui job/stage tables)
            "stages": server.stage_manager.job_stage_summary(j.job_id),
        }
        for j in job_snapshot
    ]
    return {
        "executors": executors,
        "jobs": jobs,
        "started": int(server.start_time * 1000),
        "uptime_seconds": now - server.start_time,
        "policy": server.policy.value,
        "version": BALLISTA_VERSION,
    }


def start_rest_server(server, host: str = "0.0.0.0", port: int = 0):
    """Serve /api/state + the status page. Returns (httpd, bound_port)."""

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (http.server API)
            path = self.path.split("?", 1)[0].rstrip("/") or "/"
            if path in ("/api/state", "/state"):
                body = json.dumps(scheduler_state(server)).encode()
                ctype = "application/json"
            elif path == "/":
                body = _UI_PAGE.encode()
                ctype = "text/html; charset=utf-8"
            else:
                self.send_error(404)
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):
            log.debug("rest: " + fmt, *args)

    httpd = ThreadingHTTPServer((host, port), Handler)
    t = threading.Thread(target=httpd.serve_forever, daemon=True, name="rest")
    t.start()
    return httpd, httpd.server_address[1]
